"""Sparse cosine DBSCAN: TF-IDF-style CSR input on the MXU.

The reference has no sparse support (its only metric is 2-D Euclidean,
DBSCANPoint.scala:26-30); this implements BASELINE.json configs[3]
("TF-IDF 20-Newsgroups sparse vectors") TPU-first:

1. only the nonzeros travel to the device — (row, col, val) triples sorted
   by feature column, sliced into feature blocks, padded to one static
   shape (tens of MB for ~2M nnz vs tens of GB densified);
2. a ``lax.scan`` over feature blocks scatter-densifies each [N, F_block]
   slab on device and accumulates the gram matrix with one MXU matmul per
   block — rows are L2-normalized on the host first, so the gram IS the
   cosine similarity;
3. cosine distance = 1 - gram; thresholding yields the [N, N] adjacency,
   and the shared engine tail (ops.local_dbscan.cluster_from_adjacency)
   produces labels/flags.

Memory is bounded by the [N, N] f32 gram (N = 20k -> 1.6 GB), not by the
vocabulary size: D only affects how many feature blocks the scan walks.
Single-partition by design — ample for the 20-Newsgroups-scale config
this implements. (Dense cosine at larger N decomposes through metric
spill partitioning, parallel/spill.py; extending the spill front-end to
CSR input — sparse-dense pivot products + per-leaf gram — is the
documented growth path past ~50k sparse rows.)
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbscan_tpu.ops.local_dbscan import LocalResult, cluster_from_adjacency

FEATURE_BLOCK = 4096


class _PackedCSR(NamedTuple):
    rows: np.ndarray  # [n_blocks, max_nnz] int32 row index per nnz
    cols: np.ndarray  # [n_blocks, max_nnz] int32 col index WITHIN its block
    vals: np.ndarray  # [n_blocks, max_nnz] f32; 0 on padding
    n_rows: int
    n_blocks: int


def _pack_csr(x_csr, feature_block: int) -> _PackedCSR:
    """Sort nnz by feature column and slice into equal-width feature blocks,
    padded to the max per-block nnz count (one static scan shape)."""
    coo = x_csr.tocoo()
    rows = np.asarray(coo.row, dtype=np.int64)
    cols = np.asarray(coo.col, dtype=np.int64)
    vals = np.asarray(coo.data, dtype=np.float32)
    n, d = x_csr.shape
    n_blocks = max(1, math.ceil(d / feature_block))

    order = np.argsort(cols, kind="stable")
    rows, cols, vals = rows[order], cols[order], vals[order]
    block_of = cols // feature_block
    starts = np.searchsorted(block_of, np.arange(n_blocks))
    ends = np.r_[starts[1:], len(cols)]
    max_nnz = int((ends - starts).max()) if len(cols) else 1
    # pad slot: row 0 / col 0 / val 0 — scatters +0.0, a no-op
    r = np.zeros((n_blocks, max_nnz), dtype=np.int32)
    c = np.zeros((n_blocks, max_nnz), dtype=np.int32)
    v = np.zeros((n_blocks, max_nnz), dtype=np.float32)
    for b in range(n_blocks):
        s, e = starts[b], ends[b]
        r[b, : e - s] = rows[s:e]
        c[b, : e - s] = cols[s:e] - b * feature_block
        v[b, : e - s] = vals[s:e]
    return _PackedCSR(r, c, v, n, n_blocks)


@functools.partial(jax.jit, static_argnames=("n_rows", "feature_block"))
def _gram_from_packed(rows, cols, vals, n_rows: int, feature_block: int):
    """Accumulate X @ X.T over feature blocks: scatter-densify each
    [N, F_block] slab, one MXU matmul per block."""

    def step(gram, triple):
        r, c, v = triple
        slab = jnp.zeros((n_rows, feature_block), dtype=jnp.float32)
        slab = slab.at[r, c].add(v)
        gram = gram + jnp.dot(
            slab, slab.T, preferred_element_type=jnp.float32
        )
        return gram, None

    init = jnp.zeros((n_rows, n_rows), dtype=jnp.float32)
    gram, _ = jax.lax.scan(step, init, (rows, cols, vals))
    return gram


def sparse_cosine_gram(x_csr, feature_block: int = FEATURE_BLOCK) -> jnp.ndarray:
    """Cosine-similarity gram matrix of a scipy CSR matrix, on device.

    Rows are L2-normalized on the host (zero rows stay zero). Returns the
    [N, N] f32 similarity.
    """
    import scipy.sparse as sp

    x = sp.csr_matrix(x_csr, dtype=np.float64)
    norms = np.sqrt(np.asarray(x.multiply(x).sum(axis=1)).ravel())
    inv = np.where(norms > 0, 1.0 / np.maximum(norms, 1e-300), 0.0)
    x = sp.diags(inv) @ x
    packed = _pack_csr(x.tocsr(), feature_block)
    return _gram_from_packed(
        jnp.asarray(packed.rows),
        jnp.asarray(packed.cols),
        jnp.asarray(packed.vals),
        packed.n_rows,
        feature_block,
    )


@functools.partial(jax.jit, static_argnames=("min_points", "engine"))
def _cluster_gram(gram, eps, min_points: int, engine: str) -> LocalResult:
    n = gram.shape[0]
    dist = 1.0 - gram
    adj = dist <= eps
    adj = adj | jnp.eye(n, dtype=bool)  # self-inclusive regardless of eps
    return cluster_from_adjacency(
        adj, jnp.ones(n, dtype=bool), min_points, engine
    )


def sparse_cosine_dbscan(
    x_csr,
    eps: float,
    min_points: int,
    engine: str = "archery",
    feature_block: int = FEATURE_BLOCK,
) -> Tuple[np.ndarray, np.ndarray]:
    """DBSCAN over sparse rows with cosine distance (1 - similarity) <= eps.

    Returns (clusters [N] int32 with 0 = noise, flags [N] int8) in the
    package's standard label conventions. Zero rows (empty documents) have
    similarity 0 to everything — they cluster only if eps >= 1.
    """
    gram = sparse_cosine_gram(x_csr, feature_block)
    res: LocalResult = _cluster_gram(gram, jnp.float32(eps), min_points, engine)
    from dbscan_tpu.ops.labels import seed_to_local_ids

    clusters = seed_to_local_ids(np.asarray(res.seed_labels))
    return clusters, np.asarray(res.flags)
