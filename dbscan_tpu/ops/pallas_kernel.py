"""Pallas TPU kernels for the per-partition DBSCAN hot loop.

The XLA path in :mod:`dbscan_tpu.ops.local_dbscan` materializes the full
[N, N] eps-adjacency in HBM — fine for small partition buckets, quadratic
memory for large ones. These kernels stream (row-tile x col-tile) blocks
through VMEM instead, recomputing the tiny 2-D distance math per sweep
(a handful of VPU flops per pair) so memory stays O(N) no matter how large
the bucket. This is the "never materialize N x N, stream tile pairs"
strategy from SURVEY.md section 7 and replaces the reference's O(n^2)
scalar scan (LocalDBSCANNaive.scala:72-78) with hardware-shaped tiles.

Two sweeps, both with grid (rows/T, cols/T) and an output block revisited
across the column dimension (init at j == 0, accumulate after):

- ``neighbor_counts``: per-row count of valid eps-neighbors, self-inclusive
  (d^2 to itself is 0), accumulated with ``+``.
- ``neighbor_min_label``: per-row minimum of ``labels[j]`` over eps-adjacent
  columns with ``col_mask`` set, accumulated with ``min``. One such sweep is
  one step of min-label propagation; at the fixed point it also yields each
  non-core row's minimum adjacent core seed (the border-assignment input).

Coordinates are fed twice — as an [N, 1] column vector for rows and a
[1, N] row vector for columns — so the (T, T) broadcast needs no in-kernel
relayout. Scalars ride in SMEM. Padding rows/cols are masked out by
``mask`` / ``col_mask``; callers pad N to a tile multiple via the wrappers.

On non-TPU backends the kernels run in interpreter mode, which is how the
CPU test suite validates them bit-for-bit against the XLA path; the real
Mosaic lowering is exercised on TPU via ``bench.py`` with ``BENCH_PALLAS=1``
and by the driver harness's bench runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dbscan_tpu.ops.labels import SEED_NONE
from dbscan_tpu.ops.propagation import min_label_fixed_point

# Row/col tile edge. (T, T) f32/int32 intermediates must fit VMEM several
# times over: 256^2 * 4 B = 256 KiB per buffer — comfortable.
TILE = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to_tile(a: jnp.ndarray, fill) -> jnp.ndarray:
    n = a.shape[0]
    pad = (-n) % TILE
    if pad == 0:
        return a
    return jnp.pad(a, ((0, pad),), constant_values=fill)


def _counts_kernel(eps2_ref, xr, yr, vr, xc, yc, vc, out):
    j = pl.program_id(1)
    dx = xr[:] - xc[:]  # (T,1) - (1,T) -> (T,T)
    dy = yr[:] - yc[:]
    d2 = dx * dx + dy * dy
    adj = (d2 <= eps2_ref[0, 0]) & (vr[:] > 0.5) & (vc[:] > 0.5)
    partial = jnp.sum(
        jnp.where(adj, jnp.float32(1.0), jnp.float32(0.0)),
        axis=1,
        keepdims=True,
    )

    @pl.when(j == 0)
    def _():
        out[:] = partial

    @pl.when(j > 0)
    def _():
        out[:] = out[:] + partial


def _min_label_kernel(eps2_ref, xr, yr, vr, xc, yc, cmask, lab, out):
    j = pl.program_id(1)
    dx = xr[:] - xc[:]
    dy = yr[:] - yc[:]
    d2 = dx * dx + dy * dy
    adj = (d2 <= eps2_ref[0, 0]) & (vr[:] > 0.5) & (cmask[:] > 0.5)
    partial = jnp.min(
        jnp.where(adj, lab[:], jnp.int32(SEED_NONE)), axis=1, keepdims=True
    )

    @pl.when(j == 0)
    def _():
        out[:] = partial

    @pl.when(j > 0)
    def _():
        out[:] = jnp.minimum(out[:], partial)


def _row_spec():
    return pl.BlockSpec((TILE, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM)


def _col_spec():
    return pl.BlockSpec((1, TILE), lambda i, j: (0, j), memory_space=pltpu.VMEM)


def _smem_spec():
    return pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM)


# pallas renamed TPUCompilerParams -> CompilerParams across jax releases;
# resolve whichever this jax ships so the kernels import on both sides.
_COMPILER_PARAMS = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)


def _grid_params(n: int):
    grid = (n // TILE, n // TILE)
    compiler_params = _COMPILER_PARAMS(
        dimension_semantics=("parallel", "arbitrary")
    )
    return grid, compiler_params


def neighbor_counts(
    points: jnp.ndarray, mask: jnp.ndarray, eps2: jnp.ndarray
) -> jnp.ndarray:
    """Self-inclusive eps-neighbor counts.

    points: [N, 2] float; mask: [N] bool; eps2: scalar threshold on squared
    distance. Returns [N] int32. Equivalent to
    ``sum_j [d2(i,j) <= eps2 and mask_i and mask_j]``.
    """
    n = points.shape[0]
    x = _pad_to_tile(points[:, 0].astype(jnp.float32), 0.0)
    y = _pad_to_tile(points[:, 1].astype(jnp.float32), 0.0)
    v = _pad_to_tile(mask.astype(jnp.float32), 0.0)
    npad = x.shape[0]
    grid, compiler_params = _grid_params(npad)
    out = pl.pallas_call(
        _counts_kernel,
        grid=grid,
        in_specs=[
            _smem_spec(),
            _row_spec(),
            _row_spec(),
            _row_spec(),
            _col_spec(),
            _col_spec(),
            _col_spec(),
        ],
        out_specs=_row_spec(),
        out_shape=jax.ShapeDtypeStruct((npad, 1), jnp.float32),
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(
        jnp.asarray(eps2, jnp.float32).reshape(1, 1),
        x[:, None],
        y[:, None],
        v[:, None],
        x[None, :],
        y[None, :],
        v[None, :],
    )
    return out[:n, 0].astype(jnp.int32)


def neighbor_min_label(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    col_mask: jnp.ndarray,
    labels: jnp.ndarray,
    eps2: jnp.ndarray,
) -> jnp.ndarray:
    """Per-row min of ``labels[j]`` over eps-adjacent cols with col_mask set.

    Rows with ``mask`` unset, or with no qualifying neighbor, return
    SEED_NONE. One call is one masked min-propagation step.
    """
    n = points.shape[0]
    x = _pad_to_tile(points[:, 0].astype(jnp.float32), 0.0)
    y = _pad_to_tile(points[:, 1].astype(jnp.float32), 0.0)
    v = _pad_to_tile(mask.astype(jnp.float32), 0.0)
    c = _pad_to_tile(col_mask.astype(jnp.float32), 0.0)
    lab = _pad_to_tile(labels.astype(jnp.int32), SEED_NONE)
    npad = x.shape[0]
    grid, compiler_params = _grid_params(npad)
    out = pl.pallas_call(
        _min_label_kernel,
        grid=grid,
        in_specs=[
            _smem_spec(),
            _row_spec(),
            _row_spec(),
            _row_spec(),
            _col_spec(),
            _col_spec(),
            _col_spec(),
            _col_spec(),
        ],
        out_specs=_row_spec(),
        out_shape=jax.ShapeDtypeStruct((npad, 1), jnp.int32),
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(
        jnp.asarray(eps2, jnp.float32).reshape(1, 1),
        x[:, None],
        y[:, None],
        v[:, None],
        x[None, :],
        y[None, :],
        c[None, :],
        lab[None, :],
    )
    return out[:n, 0]


def pallas_engine(points, mask, eps, min_points, mode=None):
    """Resolve the propagation mode (ops/propagation.py) BEFORE the jit
    so an in-process DBSCAN_PROP_UNIONFIND flip mints a fresh trace —
    see :func:`_pallas_engine_jit` for the engine itself."""
    from dbscan_tpu.ops.propagation import prop_mode

    return _pallas_engine_jit(points, mask, eps, min_points, prop_mode(mode))


@functools.partial(jax.jit, static_argnames=("min_points", "mode"))
def _pallas_engine_jit(points, mask, eps, min_points, mode):
    """counts / core / component seeds via the streaming sweeps.

    Returns (counts [N] i32, core [N] bool, comp [N] i32 — component seed on
    core rows else SEED_NONE, core_nbr_seed [N] i32 — min adjacent core seed,
    meaningful for non-core rows).

    The propagation loop runs min-sweeps over core columns for ALL rows:
    core rows converge to their component minimum (seed index) exactly as
    the XLA path's masked matrix-min does, and non-core rows converge — one
    step behind — to the min seed among their adjacent cores, which is
    precisely the border-assignment input. The pointer-jump
    (``labels[labels]`` gather) stays plain XLA between sweeps.
    """
    n = points.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    none = jnp.int32(SEED_NONE)
    eps2 = jnp.asarray(eps, jnp.float32) ** 2

    counts = neighbor_counts(points, mask, eps2)
    core = (counts >= jnp.int32(min_points)) & mask
    init = jnp.where(core, idx, none)

    def neighbor_min(labels):
        return neighbor_min_label(points, mask, core, labels, eps2)

    final = min_label_fixed_point(init, neighbor_min, mode=mode)

    comp = jnp.where(core, final, none)
    core_nbr_seed = final
    return counts, core, comp, core_nbr_seed
