"""The per-partition DBSCAN kernel: vectorized, jittable, TPU-native.

This replaces the reference's sequential queue-BFS engines
(LocalDBSCANNaive.scala:37-118, LocalDBSCANArchery.scala:36-112) with a
fixed-point formulation built from ops XLA tiles onto the MXU/VPU:

1. pairwise measure matrix via the metric registry (matmul form — MXU);
2. eps-adjacency + self-inclusive neighbor counts -> core mask
   (``counts >= min_points``, matching the reference where the query point is
   its own neighbor, LocalDBSCANNaive.scala:72-78);
3. connected components of the core-core adjacency by iterated min-label
   propagation + pointer jumping inside ``lax.while_loop`` — converges in
   O(log diameter) iterations; the resulting component label IS the minimum
   core row index, i.e. exactly the fold index of the point that would have
   seeded that cluster in the reference's sequential scan ("seed index");
4. border assignment closed-form from seed indices. Both reference engines'
   order-dependent behaviors become order-free algebra:
   - the cluster any border point joins is the one whose expansion runs
     first = min seed index among eps-adjacent clusters (both engines);
   - NAIVE additionally leaves the point Noise unless that min adjacent seed
     precedes the point's own fold index (min_seed < own row index), which is
     precisely "was first reached by an expansion before its own fold visit"
     (the dead adoption branch, LocalDBSCANNaive.scala:108-111);
   - ARCHERY adopts unconditionally (LocalDBSCANArchery.scala:103-106).

Cluster ids are "seed labels" (min core row index, SEED_NONE for noise);
``labels.seed_to_local_ids`` densifies them to the reference's sequential
1-based numbering when needed.

Inputs are padded to static shapes with a validity mask — partitions of
varying size share one compiled kernel per bucket size (no dynamic shapes
under jit).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from dbscan_tpu.ops import distance as dist_mod
from dbscan_tpu.ops.labels import BORDER, CORE, NOISE, NOT_FLAGGED, SEED_NONE
from dbscan_tpu.ops.propagation import min_label_fixed_point


class LocalResult(NamedTuple):
    """Per-point outputs of the local kernel (all padded to the input shape).

    seed_labels: int32 cluster seed index per point; SEED_NONE for
      noise/invalid.
    flags: int8 in {NOT_FLAGGED (padding), CORE, BORDER, NOISE}.
    counts: int32 eps-neighborhood sizes (self-inclusive); diagnostics.
    """

    seed_labels: jnp.ndarray
    flags: jnp.ndarray
    counts: jnp.ndarray


def _components_min_label(
    adj_cc: jnp.ndarray, core: jnp.ndarray, mode: str = None
) -> jnp.ndarray:
    """Min-row-index label per connected component of the core-core adjacency
    (the "seed index"); non-core rows hold SEED_NONE throughout."""
    n = core.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    none = jnp.int32(SEED_NONE)
    init = jnp.where(core, idx, none)

    def neighbor_min(labels):
        return jnp.min(jnp.where(adj_cc, labels[None, :], none), axis=1)

    return min_label_fixed_point(init, neighbor_min, mode=mode)


def local_dbscan(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    eps: float,
    min_points: int,
    engine: str = "naive",
    metric: str = "euclidean",
    use_pallas: bool = False,
    mode: str = None,
) -> LocalResult:
    """Cluster one (padded) partition.

    Args:
      points: [N, D] coordinates (D == 2 for parity with the reference,
        DBSCANPoint.scala:23-24; any D for the extended metrics). Padding
        rows can hold arbitrary values.
      mask: [N] bool validity; padding rows False.
      eps: neighborhood radius (measure scale set by the metric).
      min_points: self-inclusive density threshold (static).
      engine: "naive" | "archery" — see module docstring (static).
      metric: registered metric name (static).
      use_pallas: route the adjacency sweeps through the streaming Pallas
        kernels (O(N) memory, euclidean 2-D only) instead of the
        materialized [N, N] XLA form (static).
      mode: propagation mode (ops/propagation.py; None resolves
        DBSCAN_PROP_UNIONFIND) — resolved HERE, before the jit below, so
        an in-process knob flip mints a fresh trace instead of reusing
        the other mode's compiled loop.

    Returns a :class:`LocalResult` of [N] arrays.
    """
    from dbscan_tpu.ops.propagation import prop_mode

    return _local_dbscan_jit(
        points, mask, eps, min_points, engine, metric, use_pallas,
        prop_mode(mode),
    )


# the jit cache surface stays reachable through the public name: the
# compile accounting (obs/compile.py tracked_call) and the streaming
# zero-recompile pins read fn._cache_size() off whatever they dispatch
def _local_cache_size():
    return _local_dbscan_jit._cache_size()


local_dbscan._cache_size = _local_cache_size


@functools.partial(
    jax.jit,
    static_argnames=("min_points", "engine", "metric", "use_pallas", "mode"),
)
def _local_dbscan_jit(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    eps: float,
    min_points: int,
    engine: str,
    metric: str,
    use_pallas: bool,
    mode: str,
) -> LocalResult:
    if engine not in ("naive", "archery"):
        raise ValueError(f"unknown engine {engine!r}")
    n = points.shape[0]

    if use_pallas:
        if metric != "euclidean":
            raise ValueError(
                f"use_pallas supports only the euclidean metric, got {metric!r}"
            )
        if points.shape[1] != 2:
            raise ValueError(
                "use_pallas supports only 2-D points (the sweeps read x/y "
                f"columns); got D={points.shape[1]} — use the XLA path"
            )
        from dbscan_tpu.ops.pallas_kernel import pallas_engine

        counts, core, comp, core_nbr_seed = pallas_engine(
            points, mask, eps, min_points, mode=mode
        )
    else:
        m = dist_mod.get_metric(metric)
        measure = m.pairwise(points, points)
        thr = m.threshold(jnp.asarray(eps, dtype=measure.dtype))
        adj = (measure <= thr) & mask[None, :] & mask[:, None]
        # Self-adjacency for every valid point: guaranteed for
        # euclidean/cosine (measure 0 at the diagonal) but made explicit so
        # counts are self-inclusive under any registered metric.
        adj = adj | (jnp.eye(n, dtype=bool) & mask[:, None])
        return cluster_from_adjacency(adj, mask, min_points, engine, mode)

    return _finalize(mask, core, comp, core_nbr_seed, counts, engine)


def cluster_from_adjacency(
    adj: jnp.ndarray,
    mask: jnp.ndarray,
    min_points: int,
    engine: str,
    mode: str = None,
) -> LocalResult:
    """Full DBSCAN labeling from a materialized [N, N] eps-adjacency.

    The engine tail shared by every adjacency producer: the dense-metric
    path above, and external adjacency builders (e.g. the sparse TF-IDF
    gram pipeline in :mod:`dbscan_tpu.ops.sparse`). ``adj`` must already be
    masked (no true entries on invalid rows/cols) and self-inclusive on
    valid rows. Cached/jitted callers pass their resolved propagation
    ``mode`` so it rides their trace key; eager callers may leave None.
    """
    if engine not in ("naive", "archery"):
        raise ValueError(f"unknown engine {engine!r}")
    none = jnp.int32(SEED_NONE)
    counts = jnp.sum(adj, axis=1, dtype=jnp.int32)
    core = (counts >= jnp.int32(min_points)) & mask

    adj_cc = adj & core[None, :] & core[:, None]
    comp = _components_min_label(adj_cc, core, mode)

    # Min seed index among eps-adjacent cores (for cores: own component).
    core_nbr_seed = jnp.min(
        jnp.where(adj & core[None, :], comp[None, :], none), axis=1
    )
    return _finalize(mask, core, comp, core_nbr_seed, counts, engine)


def _finalize(
    mask, core, comp, core_nbr_seed, counts, engine: str, own_idx=None
) -> LocalResult:
    """Border/noise algebra + flag packing shared by all engine backends
    (see module docstring items 3-4).

    own_idx: optional [N] int32 fold index per array position, for backends
    whose arrays are not in fold order (the banded engine sorts by cell);
    None means position == fold index.
    """
    n = mask.shape[0]
    idx = (
        jnp.arange(n, dtype=jnp.int32) if own_idx is None else own_idx
    )
    none = jnp.int32(SEED_NONE)
    has_core_nbr = core_nbr_seed != none
    if engine == "naive":
        border = mask & ~core & has_core_nbr & (core_nbr_seed < idx)
    else:
        border = mask & ~core & has_core_nbr

    seed_labels = jnp.where(
        core, comp, jnp.where(border, core_nbr_seed, none)
    )
    flags = jnp.where(
        ~mask,
        jnp.int8(NOT_FLAGGED),
        jnp.where(
            core,
            jnp.int8(CORE),
            jnp.where(border, jnp.int8(BORDER), jnp.int8(NOISE)),
        ),
    )
    return LocalResult(seed_labels.astype(jnp.int32), flags, counts)
