"""Pallas banded engine: the production two-sweep structure on Mosaic.

Round 2 measured the original streaming Pallas path (ops/pallas_kernel.py)
5x slower than the banded XLA engine at its best partition size — a
structural loss, not a kernel-quality one: it iterates O(cluster diameter)
min-label sweeps, each re-streaming every distance tile, while the banded
engine (ops/banded.py) does a FIXED two sweeps over host-measured cell
runs and solves connected components on the host cell graph. This module
ports the banded structure itself into Pallas kernels, so the
no-[B, B]-materialization path stops paying the re-sweeps:

  kernel 1 (counts): per-point eps-neighbor counts over the 5 window-row
    slabs -> core mask (threshold applied outside the kernel);
  kernel 2 (bits): per-point 25-bit window-cell mask — bit k*5+dx set iff
    some CORE point of window cell (k-2, dx-2) is eps-adjacent.

The inputs are ops/banded.py's exact contract (cell-sorted points, per-row
run tables, per-block slab origins from parallel/binning.py), and the
outputs feed the same compact postpass + host cell-CC
(parallel/cellgraph.py), so labels are bit-identical to the XLA banded
engine (asserted by tests/test_pallas_banded.py).

The Pallas-specific part is the slab fetch: slab origins are
DATA-DEPENDENT (host-measured), which BlockSpec index maps cannot express
— so origins ride in as a scalar-prefetch SMEM array and each kernel
issues manual `make_async_copy` DMAs from the full HBM-resident planes
into [R, S] VMEM scratch, overlapping the 5 window rows' fetches. Blocked
views of the same arrays arrive through ordinary BlockSpecs. Run tables
are fed [R, T]-transposed so the minor (lane) dimension is the block
edge, not the 5-wide window.

On non-TPU backends the kernels run in interpreter mode (how the CPU
suite pins them bit-for-bit against ops/banded.py); Mosaic lowering is
exercised on TPU via ``bench.py`` BENCH_PALLAS=1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dbscan_tpu.parallel.binning import BANDED_BLOCK, BANDED_ROWS


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _start_slab_copies(ss_ref, i, full_arrays, slabs, sem, slab):
    """Kick off the [R, S] slab DMAs for every (array, window row) pair and
    return the descriptors to wait on. full_arrays[a] is an HBM-resident
    [B] ref; slabs[a] its [R, S] VMEM scratch; sem is an (A, R) DMA
    semaphore array."""
    copies = []
    for k in range(BANDED_ROWS):
        start = ss_ref[i, k]
        for a, (src, dst) in enumerate(zip(full_arrays, slabs)):
            c = pltpu.make_async_copy(
                src.at[pl.ds(start, slab)], dst.at[k], sem.at[a, k]
            )
            c.start()
            copies.append(c)
    return copies


def _tile_adj(bl_planes, bm_row, brel, bspan, slabs, smask, offs, eps2, k):
    """The [T, S] adjacency tile of window row k (recomputed per consumer,
    never stored across sweeps — the banded engine's memory contract)."""
    d2 = None
    for bp, sl in zip(bl_planes, slabs):
        df = bp[0][:, None] - sl[k][None, :]
        d2 = df * df if d2 is None else d2 + df * df
    rel_k = brel[0, k][:, None]
    span_k = bspan[0, k][:, None]
    inrun = (offs >= rel_k) & (offs < rel_k + span_k)
    return (
        inrun
        & (smask[k][None, :] > 0)
        & (d2 <= eps2)
        & (bm_row[0][:, None] > 0)
    )


def _make_counts_kernel(d: int, slab: int):
    t = BANDED_BLOCK

    def kernel(ss_ref, eps2_ref, *refs):
        bl_planes = refs[0:d]
        bm = refs[d]
        brel = refs[d + 1]
        bspan = refs[d + 2]
        full = refs[d + 3 : 2 * d + 4]  # d planes + mask, HBM-resident
        out = refs[2 * d + 4]
        slabs = refs[2 * d + 5 : 3 * d + 5]
        smask = refs[3 * d + 5]
        sem = refs[3 * d + 6]

        i = pl.program_id(0)
        for c in _start_slab_copies(
            ss_ref, i, full, (*slabs, smask), sem, slab
        ):
            c.wait()
        offs = jax.lax.broadcasted_iota(jnp.int32, (t, slab), 1)
        eps2 = eps2_ref[0, 0]
        acc = jnp.zeros((t,), jnp.int32)
        for k in range(BANDED_ROWS):
            adj = _tile_adj(
                bl_planes, bm, brel, bspan, slabs, smask, offs, eps2, k
            )
            acc = acc + jnp.sum(adj.astype(jnp.int32), axis=1)
        out[0] = acc

    return kernel


def _make_bits_kernel(d: int, slab: int):
    t = BANDED_BLOCK

    def kernel(ss_ref, eps2_ref, *refs):
        bl_planes = refs[0:d]
        bm = refs[d]
        brel = refs[d + 1]
        bspan = refs[d + 2]
        bcx = refs[d + 3]
        full = refs[d + 4 : 2 * d + 7]  # d planes + mask + cx + core
        out = refs[2 * d + 7]
        slabs = refs[2 * d + 8 : 3 * d + 8]
        smask = refs[3 * d + 8]
        scx = refs[3 * d + 9]
        score = refs[3 * d + 10]
        sem = refs[3 * d + 11]

        i = pl.program_id(0)
        for c in _start_slab_copies(
            ss_ref, i, full, (*slabs, smask, scx, score), sem, slab
        ):
            c.wait()
        offs = jax.lax.broadcasted_iota(jnp.int32, (t, slab), 1)
        eps2 = eps2_ref[0, 0]
        bits = jnp.zeros((t,), jnp.int32)
        for k in range(BANDED_ROWS):
            adj = _tile_adj(
                bl_planes, bm, brel, bspan, slabs, smask, offs, eps2, k
            )
            adj_cc = adj & (score[k][None, :] > 0)
            # window column slot: 0..4 whenever adj_cc is true (the run
            # covers exactly cx-2..cx+2); a boolean any() per slot keeps
            # the reduction a plain max — no bitwise-or reduce needed
            dxm = scx[k][None, :] - bcx[0][:, None] + 2
            for dx in range(5):
                hit = jnp.any(adj_cc & (dxm == dx), axis=1)
                bits = bits | (
                    hit.astype(jnp.int32) << jnp.int32(k * 5 + dx)
                )
        out[0] = bits

    return kernel


def _block_spec(t):
    return pl.BlockSpec((1, t), lambda i, ss: (i, 0))


def _run_spec(t):
    return pl.BlockSpec((1, BANDED_ROWS, t), lambda i, ss: (i, 0, 0))


@functools.partial(jax.jit, static_argnames=("min_points", "slab"))
def banded_phase1_pallas(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    rel_starts: jnp.ndarray,
    spans: jnp.ndarray,
    slab_starts: jnp.ndarray,
    cx: jnp.ndarray,
    eps: float,
    min_points: int,
    slab: int = 128,
):
    """Drop-in Pallas replacement for ops/banded.py::banded_phase1 (same
    contract, same outputs: counts [B] i32, core [B] bool, bits [B] i32)."""
    b, d = points.shape
    t = BANDED_BLOCK
    r = BANDED_ROWS
    if b % t:
        raise ValueError(f"bucket width {b} not a multiple of {t}")
    nb = b // t

    planes = tuple(points[:, j].astype(jnp.float32) for j in range(d))
    m32 = mask.astype(jnp.int32)
    # [B, R] run tables -> [nb, R, T]: lane dim = block edge
    rel = rel_starts.astype(jnp.int32).reshape(nb, t, r).transpose(0, 2, 1)
    spn = spans.astype(jnp.int32).reshape(nb, t, r).transpose(0, 2, 1)
    ss = slab_starts.astype(jnp.int32)
    eps2 = jnp.asarray(eps, jnp.float32).reshape(1, 1) ** 2

    blocked_specs = [
        pl.BlockSpec((1, 1), lambda i, ss: (0, 0), memory_space=pltpu.SMEM),
        *[_block_spec(t) for _ in range(d + 1)],  # planes + mask
        _run_spec(t),
        _run_spec(t),
    ]
    blocked_args = [
        eps2,
        *[p.reshape(nb, t) for p in planes],
        m32.reshape(nb, t),
        rel,
        spn,
    ]

    counts = pl.pallas_call(
        _make_counts_kernel(d, slab),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nb,),
            in_specs=[
                *blocked_specs,
                *[
                    pl.BlockSpec(memory_space=pl.ANY)
                    for _ in range(d + 1)
                ],
            ],
            out_specs=_block_spec(t),
            scratch_shapes=[
                *[pltpu.VMEM((r, slab), jnp.float32) for _ in range(d)],
                pltpu.VMEM((r, slab), jnp.int32),
                pltpu.SemaphoreType.DMA((d + 1, r)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((nb, t), jnp.int32),
        interpret=_interpret(),
    )(ss, *blocked_args, *planes, m32).reshape(-1)

    core = (counts >= jnp.int32(min_points)) & mask
    cx32 = cx.astype(jnp.int32)
    core32 = core.astype(jnp.int32)

    bits = pl.pallas_call(
        _make_bits_kernel(d, slab),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nb,),
            in_specs=[
                *blocked_specs,
                _block_spec(t),  # cx blocked
                *[
                    pl.BlockSpec(memory_space=pl.ANY)
                    for _ in range(d + 3)
                ],
            ],
            out_specs=_block_spec(t),
            scratch_shapes=[
                *[pltpu.VMEM((r, slab), jnp.float32) for _ in range(d)],
                pltpu.VMEM((r, slab), jnp.int32),  # mask slab
                pltpu.VMEM((r, slab), jnp.int32),  # cx slab
                pltpu.VMEM((r, slab), jnp.int32),  # core slab
                pltpu.SemaphoreType.DMA((d + 3, r)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((nb, t), jnp.int32),
        interpret=_interpret(),
    )(ss, *blocked_args, cx32.reshape(nb, t), *planes, m32, cx32, core32)

    return counts, core, bits.reshape(-1)
