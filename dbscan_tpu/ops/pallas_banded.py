"""Pallas banded engine: the production two-sweep structure on Mosaic.

Round 2 measured the original streaming Pallas path (ops/pallas_kernel.py)
5x slower than the banded XLA engine at its best partition size — a
structural loss, not a kernel-quality one: it iterates O(cluster diameter)
min-label sweeps, each re-streaming every distance tile, while the banded
engine (ops/banded.py) does a FIXED two sweeps over host-measured cell
runs and solves connected components on the host cell graph. This module
ports the banded structure itself into Pallas kernels, so the
no-[B, B]-materialization path stops paying the re-sweeps:

  kernel 1 (counts): per-point eps-neighbor counts over the 5 window-row
    slabs -> core mask (threshold applied outside the kernel);
  kernel 2 (bits): per-point 25-bit window-cell mask — bit k*5+dx set iff
    some CORE point of window cell (k-2, dx-2) is eps-adjacent.

The inputs are ops/banded.py's exact contract (cell-sorted points, per-row
run tables, per-block slab origins from parallel/binning.py), and the
outputs feed the same compact postpass + host cell-CC
(parallel/cellgraph.py), so labels are bit-identical to the XLA banded
engine (asserted by tests/test_pallas_banded.py).

Slab origins are DATA-DEPENDENT (host-measured), which Mosaic's tiling
rules make hostile to in-kernel consumption: BlockSpec index maps cannot
express them, and manual HBM->VMEM DMAs require the dynamic start be
provably 1024-element aligned — paying for that alignment would widen
every slab window several-fold. So the slab FETCH stays in XLA, which is
exactly the kind of data-dependent gather it is good at: one advanced-
indexing gather builds the [nb, R, S] slab tensors (a few percent of the
bucket in bytes — S << B), and the Pallas kernels consume them through
ordinary aligned BlockSpecs, fusing the 5-row adjacency sweep with its
count/bit reductions so no [T, S] intermediate ever reaches HBM. Wide
slabs are additionally walked in ladder-divisor chunks by a third grid
dimension (_PALLAS_SLAB_CHUNK) so the per-step [TSUB, SC] transients fit
VMEM at ANY production slab width — the same chunking contract as
banded.py's _slab_chunks, accumulated across chunk steps.

Per-point blocked arrays ride as [nb, 1, T] (the (1, 1, T) block passes
Mosaic's last-two-dims rule by dimension equality where a (1, T) block
over [nb, T] fails the sublane-divisibility check).

On non-TPU backends the kernels run in interpreter mode (how the CPU
suite pins them bit-for-bit against ops/banded.py); Mosaic lowering is
exercised on TPU via ``bench.py`` BENCH_PALLAS=1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dbscan_tpu import config as config_mod
from dbscan_tpu.ops.banded import _slab_chunks
from dbscan_tpu.parallel.binning import BANDED_BLOCK, BANDED_ROWS, BANDED_WIN


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# Rows of a block processed per inner grid step: every [TSUB, SC]
# intermediate of the unrolled 5-row sweep must fit VMEM at once, and at
# the full BANDED_BLOCK=512 the compiler runs out for wide slabs. The
# slab bundle's index map ignores the inner dim, so it stays resident
# across a block's inner steps.
TSUB = 128

# Slab-chunk width target for the THIRD grid dimension: production slabs
# reach ~196k elements, and a [TSUB, S] f32 sweep intermediate at that
# width is ~100 MB — far past VMEM. Kernels consume the slab in even
# ladder-divisor chunks of at most this width (the [TSUB, 4096] f32
# transients are ~2 MB each; the resident [R, 4096] bundles ~80 KB per
# plane), accumulating counts/bits across chunk steps exactly like
# banded.py's _slab_chunks sweeps — bit-identical at any slab width.
_PALLAS_SLAB_CHUNK = 4096


def _tile_adj(bl_planes, bm_row, brel, bspan, slabs, smask, offs, eps2, k):
    """The [T, S] adjacency tile of window row k (recomputed per consumer,
    never stored across sweeps — the banded engine's memory contract)."""
    d2 = None
    for bp, sl in zip(bl_planes, slabs):
        df = bp[0, 0][:, None] - sl[0, k][None, :]
        d2 = df * df if d2 is None else d2 + df * df
    rel_k = brel[0, k][:, None]
    span_k = bspan[0, k][:, None]
    inrun = (offs >= rel_k) & (offs < rel_k + span_k)
    return (
        inrun
        & (smask[0, k][None, :] > 0)
        & (d2 <= eps2)
        & (bm_row[0, 0][:, None] > 0)
    )


def _accumulate(out, acc_ref, val, nsub: int, ns: int, combine):
    """Chunk-accumulation plumbing shared by both kernels, grid
    (nb, ns, nsub) with the slab-chunk dim s in the MIDDLE: a fetched
    [R, SC] chunk stays resident across a block's nsub sub-row steps
    (the big operand moves once per chunk, not once per sub-row). The
    running value can NOT live in the output ref — out blocks for a
    given (block, sub-row) are revisited non-consecutively across s, and
    Mosaic's output pipelining only preserves consecutively-revisited
    blocks (confirmed on-chip: ref-accumulation here produced corrupt
    bits). Instead a persistent [nsub, T] VMEM scratch holds one running
    row per sub-row, addressed with STATICALLY unrolled predication
    (pl.when on the sub-row id — nsub is 4; dynamic sublane starts are
    the thing Mosaic makes expensive), and the final chunk writes the
    scratch row through to the out block."""
    s = pl.program_id(1)
    j = pl.program_id(2)
    for jj in range(nsub):

        @pl.when(j == jj)
        def _one_row():
            @pl.when(s == 0)
            def _init():
                acc_ref[jj] = val

            @pl.when(s != 0)
            def _acc():
                acc_ref[jj] = combine(acc_ref[jj], val)

            @pl.when(s == ns - 1)
            def _emit():
                out[0, 0] = acc_ref[jj]


def _make_counts_kernel(d: int, sc: int, nsub: int, ns: int):
    t = TSUB

    def kernel(eps2_ref, *refs):
        bl_planes = refs[0:d]
        bm = refs[d]
        brel = refs[d + 1]
        bspan = refs[d + 2]
        slabs = refs[d + 3 : 2 * d + 3]
        smask = refs[2 * d + 3]
        out = refs[2 * d + 4]
        acc_ref = refs[2 * d + 5]

        # offsets are GLOBAL slab positions so the run-window test
        # (rel/span live in slab coordinates) is unchanged by chunking
        base = pl.program_id(1) * sc
        offs = base + jax.lax.broadcasted_iota(jnp.int32, (t, sc), 1)
        eps2 = eps2_ref[0, 0]
        acc = jnp.zeros((t,), jnp.int32)
        for k in range(BANDED_ROWS):
            adj = _tile_adj(
                bl_planes, bm, brel, bspan, slabs, smask, offs, eps2, k
            )
            # dtype pinned: under interpret+x64 a default integer sum
            # widens to int64 and the scratch store rejects the mix
            acc = acc + jnp.sum(
                adj.astype(jnp.int32), axis=1, dtype=jnp.int32
            )
        _accumulate(out, acc_ref, acc, nsub, ns, lambda a, b: a + b)

    return kernel


def _make_bits_kernel(d: int, sc: int, nsub: int, ns: int):
    t = TSUB

    def kernel(eps2_ref, *refs):
        bl_planes = refs[0:d]
        bm = refs[d]
        brel = refs[d + 1]
        bspan = refs[d + 2]
        bcx = refs[d + 3]
        slabs = refs[d + 4 : 2 * d + 4]
        smask = refs[2 * d + 4]
        scx = refs[2 * d + 5]
        score = refs[2 * d + 6]
        out = refs[2 * d + 7]
        acc_ref = refs[2 * d + 8]

        base = pl.program_id(1) * sc
        offs = base + jax.lax.broadcasted_iota(jnp.int32, (t, sc), 1)
        eps2 = eps2_ref[0, 0]
        bits = jnp.zeros((t,), jnp.int32)
        for k in range(BANDED_ROWS):
            adj = _tile_adj(
                bl_planes, bm, brel, bspan, slabs, smask, offs, eps2, k
            )
            adj_cc = adj & (score[0, k][None, :] > 0)
            # window column slot: 0..4 whenever adj_cc is true (the run
            # covers exactly cx-2..cx+2); a boolean any() per slot keeps
            # the reduction a plain max — no bitwise-or reduce needed
            dxm = scx[0, k][None, :] - bcx[0, 0][:, None] + 2
            for dx in range(5):
                hit = jnp.any(adj_cc & (dxm == dx), axis=1)
                bits = bits | (
                    hit.astype(jnp.int32) << jnp.int32(k * 5 + dx)
                )
        _accumulate(out, acc_ref, bits, nsub, ns, lambda a, b: a | b)

    return kernel


def _block_spec(t):
    # [nb * nsub, 1, t] layout: Mosaic requires the last two block dims
    # to be (divisible by 8, divisible by 128) OR equal to the array dims
    # — a (1, t) block over [rows, t] fails the sublane rule, while
    # (1, 1, t) over [rows, 1, t] passes by equality. Grid is
    # (nb, ns, nsub): outer picks the block (and its slab), middle the
    # slab chunk, inner (fastest) the t-row sub-block — per-point blocks
    # are tiny ([1, 1, T]), so their per-chunk refetches cost ~nothing,
    # while the big [R, SC] chunk stays resident across the sub-rows.
    return pl.BlockSpec(
        (1, 1, t), lambda i, s, j: (i * (BANDED_BLOCK // t) + j, 0, 0)
    )


def _slab_spec(sc):
    # one [R, SC] chunk of a block's slab bundle per MIDDLE grid step;
    # the index map ignores the fastest (sub-row) dim, so a fetched
    # chunk is consumed by every sub-row before the next chunk loads.
    # Tiling rule: R equals the array dim; SC is a ladder divisor — a
    # multiple of 128 whenever ns > 1, and equal to the array dim S when
    # ns == 1.
    return pl.BlockSpec((1, BANDED_ROWS, sc), lambda i, s, j: (i, 0, s))


def _gather_slabs(plane, ss, slab):
    """[nb, R, S] slab tensor: plane[ss[i, k] + j]. XLA lowers this to a
    gather — the data-dependent fetch Mosaic cannot cheaply express."""
    idx = ss[:, :, None] + jnp.arange(slab, dtype=jnp.int32)[None, None, :]
    return plane[idx]


@functools.partial(jax.jit, static_argnames=("min_points", "slab"))
def banded_phase1_pallas(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    rel_starts: jnp.ndarray,
    spans: jnp.ndarray,
    slab_starts: jnp.ndarray,
    cx: jnp.ndarray,
    eps: float,
    min_points: int,
    slab: int = 128,
):
    """Drop-in Pallas replacement for ops/banded.py::banded_phase1 (same
    contract, same outputs: counts [B] i32, core [B] bool, bits [B] i32)."""
    b, d = points.shape
    t = BANDED_BLOCK
    r = BANDED_ROWS
    if b % t:
        raise ValueError(f"bucket width {b} not a multiple of {t}")
    nb = b // t

    nsub = t // TSUB
    rows = nb * nsub
    ns = _slab_chunks(slab, _PALLAS_SLAB_CHUNK)
    sc = slab // ns

    planes = tuple(points[:, j].astype(jnp.float32) for j in range(d))
    m32 = mask.astype(jnp.int32)
    # [B, R] run tables -> [rows, R, TSUB]: lane dim = sub-block edge
    rel = (
        rel_starts.astype(jnp.int32)
        .reshape(rows, TSUB, r)
        .transpose(0, 2, 1)
    )
    spn = (
        spans.astype(jnp.int32).reshape(rows, TSUB, r).transpose(0, 2, 1)
    )
    ss = slab_starts.astype(jnp.int32)
    eps2 = jnp.asarray(eps, jnp.float32).reshape(1, 1) ** 2

    blocked_specs = [
        pl.BlockSpec(
            (1, 1), lambda i, s, j: (0, 0), memory_space=pltpu.SMEM
        ),
        *[_block_spec(TSUB) for _ in range(d + 1)],  # planes + mask
        pl.BlockSpec((1, r, TSUB), lambda i, s, j: (i * nsub + j, 0, 0)),
        pl.BlockSpec((1, r, TSUB), lambda i, s, j: (i * nsub + j, 0, 0)),
    ]
    blocked_args = [
        eps2,
        *[p.reshape(rows, 1, TSUB) for p in planes],
        m32.reshape(rows, 1, TSUB),
        rel,
        spn,
    ]

    plane_slabs = [_gather_slabs(p, ss, slab) for p in planes]
    mask_slab = _gather_slabs(m32, ss, slab)

    counts = pl.pallas_call(
        _make_counts_kernel(d, sc, nsub, ns),
        grid=(nb, ns, nsub),
        in_specs=[
            *blocked_specs,
            *[_slab_spec(sc) for _ in range(d + 1)],
        ],
        out_specs=_block_spec(TSUB),
        out_shape=jax.ShapeDtypeStruct((rows, 1, TSUB), jnp.int32),
        scratch_shapes=[pltpu.VMEM((nsub, TSUB), jnp.int32)],
        interpret=_interpret(),
    )(*blocked_args, *plane_slabs, mask_slab).reshape(-1)

    core = (counts >= jnp.int32(min_points)) & mask
    cx32 = cx.astype(jnp.int32)
    core32 = core.astype(jnp.int32)

    bits = pl.pallas_call(
        _make_bits_kernel(d, sc, nsub, ns),
        grid=(nb, ns, nsub),
        in_specs=[
            *blocked_specs,
            _block_spec(TSUB),  # cx blocked
            *[_slab_spec(sc) for _ in range(d + 3)],
        ],
        out_specs=_block_spec(TSUB),
        out_shape=jax.ShapeDtypeStruct((rows, 1, TSUB), jnp.int32),
        scratch_shapes=[pltpu.VMEM((nsub, TSUB), jnp.int32)],
        interpret=_interpret(),
    )(
        *blocked_args,
        cx32.reshape(rows, 1, TSUB),
        *plane_slabs,
        mask_slab,
        _gather_slabs(cx32, ss, slab),
        _gather_slabs(core32, ss, slab),
    )

    return counts, core, bits.reshape(-1)


# --- fused cellcc unpack + fold + first propagation sweep ---------------
#
# The device cellcc finalize used to be TWO families: a per-chunk
# `cellcc.unpack` (big-endian bit unpack of the packed postpass slabs +
# scatter-fold into per-cell partials, ops/banded.py
# compiled_cellcc_unpack) and the tail `cellcc.cc` (the iterated
# window_cc propagation from identity labels). `cellcc.fused` merges the
# unpack, the fold, AND the first propagation sweep into the per-chunk
# dispatch riding the packing window: the bit expansions (the
# np.unpackbits analog — pure elementwise shift/mask work) run as Pallas
# kernels, while the scatter-folds and the folded first sweep stay XLA
# in the SAME jitted dispatch — exactly the split this module's phase-1
# kernels already use (Mosaic's tiling rules make data-dependent
# scatters hostile, the slab-gather rationale in the module docstring),
# so nothing round-trips HBM between unpack, fold, and sweep.
#
# The folded sweep: ``lab0[c] = min(c, min over this chunk's cellor
# edges of wintab[c, j])`` is the chunk-restricted first neighbor-min
# relaxation from identity labels. The full graph's first sweep is the
# elementwise min over chunks of these partials (cellor_full = OR of
# chunk cellors), so the tail `cellcc.cc` starts from "sweep 1 already
# ran" — same fixed point, byte-identical labels, one fewer counted
# sweep (compiled_cellcc_cc's ``warm`` path). DBSCAN_CELLCC_FUSED
# gates it: auto = Pallas-capable (TPU) backends, 1 forces interpreter
# mode (how the CPU suite pins bit-exactness), 0 keeps the split pair.
# DBSCAN_CELLCC_DEVICE semantics — fault site, degrade ladder,
# residency cap — are untouched: the fused dispatch stages the same
# record fields and degrades through the same paths.

#: packed bytes per fused-unpack grid step (512 core bits each — one
#: SCAN_BLOCK; M is a SCAN_BLOCK multiple, so the grid always divides)
_UNPACK_BYTES = 64

#: or-scan values per fused-expand grid step (the or_gid pad ladder is
#: 4096-based — binning._ladder_width multiples of 128 — so 128 always
#: divides the padded K)
_UNPACK_ORV = 128


def fused_mode(raw=None) -> bool:
    """Resolve ``DBSCAN_CELLCC_FUSED``: True routes the per-chunk cellcc
    unpack through :func:`compiled_cellcc_fused`. ``auto`` engages only
    on Pallas-capable (TPU) backends — the fused family's win is the
    merged dispatch in the packing window; CPU runs keep the split
    unpack/cc pair unless forced ('1'), which runs the kernels in
    interpreter mode (the bit-exactness test path)."""
    if raw is None:
        raw = str(config_mod.env("DBSCAN_CELLCC_FUSED") or "auto")
    raw = raw.strip().lower()
    if raw in ("1", "true", "yes", "on"):
        return True
    if raw in ("0", "false", "off", "no"):
        return False
    return jax.default_backend() == "tpu"


def _unpack_core_kernel(bytes_ref, out_ref):
    """[B8] packed bytes -> [B8, 8] bits (np.unpackbits-compatible
    big-endian order: bit 7 of byte i lands at out[i, 0]). Everything
    int32-strict: interpret mode under x64 rejects a mixed-width store."""
    b = bytes_ref[0, 0, :]
    shifts = jnp.int32(7) - jax.lax.broadcasted_iota(
        jnp.int32, (_UNPACK_BYTES, 8), 1
    )
    out_ref[0] = (b[:, None] >> shifts) & jnp.int32(1)


def _unpack_orv_kernel(orv_ref, out_ref):
    """[KB] gathered segmented-OR scan values -> [KB, 25] window-slot
    bits (the per-cell OR mask expansion the scatter-fold consumes)."""
    v = orv_ref[0, 0, :]
    win = jax.lax.broadcasted_iota(
        jnp.int32, (_UNPACK_ORV, BANDED_WIN), 1
    )
    out_ref[0] = (v[:, None] >> win) & jnp.int32(1)


@functools.lru_cache(maxsize=64)
def compiled_cellcc_fused(n_cells_pad: int):
    """Build (once per padded cell count) the fused per-chunk dispatch:
    (combo, cell_flat, fold_flat, or_gid, wintab) -> (core [M] bool,
    cellor [C, 25] bool, cellfold [C] i32, lab0 [C] i32), all
    device-resident — the drop-in replacement for
    ops/banded.py::compiled_cellcc_unpack that additionally emits the
    chunk's first-sweep label partial (module comment above).

    Input contract is compiled_cellcc_unpack's, plus the padded wintab
    ([C, 25] int32, -1 at unoccupied slots — the same table the tail cc
    receives; the driver uploads it once and shares the handle)."""
    sentinel = jnp.int32(n_cells_pad - 1)
    inf = jnp.int32(2**31 - 1)

    def fused(combo, cell_flat, fold_flat, or_gid, wintab):
        m = cell_flat.shape[0]
        m8 = m // 8
        interp = _interpret()

        # Pallas leg 1: packed core bytes -> bits ([rows, B8] bytes ->
        # [rows, B8, 8] bits; the (B8, 8) block passes Mosaic's
        # last-two-dims rule by dimension equality)
        rows = m8 // _UNPACK_BYTES
        byte32 = combo[:m8].astype(jnp.int32)
        core_bits = pl.pallas_call(
            _unpack_core_kernel,
            grid=(rows,),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, _UNPACK_BYTES), lambda i: (i, 0, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, _UNPACK_BYTES, 8), lambda i: (i, 0, 0)
            ),
            out_shape=jax.ShapeDtypeStruct(
                (rows, _UNPACK_BYTES, 8), jnp.int32
            ),
            interpret=interp,
        )(byte32.reshape(rows, 1, _UNPACK_BYTES))
        core = core_bits.reshape(-1).astype(bool)

        # Pallas leg 2: gathered scan values -> [K, 25] window bits
        k = or_gid.shape[0]
        orvals = lax.bitcast_convert_type(
            combo[m8 : m8 + 4 * k].reshape(k, 4), jnp.int32
        )
        rows_k = k // _UNPACK_ORV
        unp = pl.pallas_call(
            _unpack_orv_kernel,
            grid=(rows_k,),
            in_specs=[
                pl.BlockSpec((1, 1, _UNPACK_ORV), lambda i: (i, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, _UNPACK_ORV, BANDED_WIN), lambda i: (i, 0, 0)
            ),
            out_shape=jax.ShapeDtypeStruct(
                (rows_k, _UNPACK_ORV, BANDED_WIN), jnp.int32
            ),
            interpret=interp,
        )(orvals.reshape(rows_k, 1, _UNPACK_ORV)).reshape(
            k, BANDED_WIN
        )

        # XLA folds (data-dependent scatters — the Mosaic-hostile part,
        # same split as the phase-1 slab gathers), fused into THIS
        # dispatch: per-cell OR partial + min-core-fold partial,
        # byte-identical to compiled_cellcc_unpack's
        cellor = (
            jnp.zeros((n_cells_pad, BANDED_WIN), jnp.int32)
            .at[or_gid]
            .max(unp, mode="drop")
            .astype(bool)
        )
        # padded or_gid positions gather REAL scan values into the
        # sentinel row: clear it (same phantom-adjacency note as the
        # split unpack — the gated sweep counts must track the graph)
        cellor = cellor.at[n_cells_pad - 1].set(False)
        valid = cell_flat != sentinel
        folds = jnp.where(core & valid, fold_flat, inf)
        cellfold = (
            jnp.full((n_cells_pad,), 2**31 - 1, jnp.int32)
            .at[cell_flat]
            .min(folds, mode="drop")
        )

        # the folded first propagation sweep (chunk-restricted
        # neighbor-min relaxation from identity labels): bits are only
        # set where an adjacent core exists, so wintab >= 0 wherever
        # cellor is True — the clip only disciplines masked junk
        tab = jnp.clip(wintab, 0, n_cells_pad - 1)
        nbr = jnp.min(jnp.where(cellor, tab, inf), axis=1)
        lab0 = jnp.minimum(
            jnp.arange(n_cells_pad, dtype=jnp.int32), nbr
        )
        return core, cellor, cellfold, lab0

    return jax.jit(fused)
