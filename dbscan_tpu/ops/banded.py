"""Grid-banded local DBSCAN engine: 2 fixed sweeps + host cell components.

The dense engine (ops/local_dbscan.py) materializes the full [B, B]
eps-adjacency — the TPU-shaped replacement for the reference's O(n^2) linear
scans (LocalDBSCANNaive.scala:72-78) — and finds components by iterated
min-label propagation. That iteration is the scaling killer: blob-shaped
partitions measured 18-49 sweeps, each recomputing the masked distance
tiles, and TPU's slow arbitrary-index gathers (~40M elem/s) rule out
cheap pointer-chasing between sweeps.

This engine removes the iteration instead of accelerating it. Points snap
to a FINE grid of side eps/sqrt(2) (binning.FINE_CELL_FACTOR): any two
points in one cell are then within eps, so all cores of a cell form a
clique sharing ONE cluster — connected components collapse from the point
graph to the (25x smaller) CELL graph, which the HOST solves exactly with
scipy/C connected-components (dbscan_tpu/parallel/cellgraph.py). The
device does only the pairwise-distance work, as a FIXED two sweeps:

  sweep 1: eps-neighbor counts -> core mask;
  sweep 2: per-point 25-bit mask over its 5x5 window cells — bit set iff
    some CORE in that cell is eps-adjacent — 1 int32 per point. Core rows'
    bits are the cell graph's edge list; non-core rows' bits give each
    candidate border point its min adjacent-core seed (all cores of a cell
    share one seed), so labels, flags, and the whole border algebra
    finalize on the host with no further device pass.

Sweeps are block-slab passes over cell-sorted points: for a block of
BANDED_BLOCK consecutive sorted points, each window row's candidate runs
union into a (near-)contiguous slab the host measures exactly
(dbscan_tpu/parallel/binning.py); the device fetches each slab with one
contiguous dynamic_slice (no gathers — XLA lowers arbitrary 1-D gathers to
scalar loops) and consumes it as a dense [T, 5, S] difference tile on the
VPU, masking each row's true run with (rel_start, span).

Correctness notes:
- label VALUES are original fold indices (reference numbering semantics,
  LocalDBSCANNaive.scala:45-64) while label POSITIONS are cell-sorted;
- clique edges asserted without a distance test are always consistent with
  the dense engine's f32 arithmetic: intra-cell distance is at most
  eps*(1-1e-5) while the difference-form rounding is ~1e-7 relative (bf16
  is rejected upstream);
- slabs may cover unrelated cells (padding, row straddles); each row masks
  its true run with (rel_start, span), so no pair is counted twice across
  the row-slabs and nothing outside the runs contributes.

Exactness vs the dense engine: the pairwise measure is the identical
difference-form arithmetic (ops/distance.py euclidean D<=4 path) and the
cell-graph components equal the point-graph components (clique + reach
guarantees, binning.FINE_CELL_FACTOR), so in f32 the two engines produce
bit-identical labels (tested).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# Block/window geometry lives host-side next to the packer that must agree
# on it.
from dbscan_tpu.ops.labels import BORDER, CORE, NOISE
from dbscan_tpu.parallel.binning import BANDED_BLOCK, BANDED_ROWS, BANDED_WIN

# Element budget for how many blocks one lax.map step may process at once
# (vmapped): bounds the fused tile transients to ~1 GB while cutting the
# sequential step count.
_BLOCK_BATCH_ELEMS = 1 << 28


def _block_batch(slab: int, n_planes: int = 2) -> int:
    # the fused tile transients scale with the coordinate plane count
    # (2 planar, 3 spherical-chord): halve the batch at D == 3. The
    # per-step transient is one SLAB CHUNK, not the full slab.
    sc = slab // _slab_chunks(slab)
    per_block = BANDED_BLOCK * BANDED_ROWS * sc * max(1, n_planes - 1)
    return max(1, min(32, _BLOCK_BATCH_ELEMS // per_block))


# Per-op slab chunk target: a [T, R, S] f32 tile at S ~ 200k is ~2 GB
# PER BUFFER — at the TPU runtime's per-buffer ceiling and, together
# with the pipeline's resident arrays, past the chip's HBM. Sweeps
# consume the slab in <= ~49k-wide chunks (ladder widths are 3*2^k, so
# a small integer divisor always exists), accumulating counts / OR-ing
# bits — bit-identical, bounded transients at any slab width.
_SLAB_CHUNK_TARGET = 49152


def _slab_chunks(slab: int, target: int | None = None) -> int:
    """Number of even chunks the [*, S] sweeps consume the slab in, the
    largest chunk width <= ``target`` (default: module _SLAB_CHUNK_TARGET;
    the Pallas engine passes its own VMEM-sized target)."""
    if target is None:
        target = _SLAB_CHUNK_TARGET
    if slab <= target:
        return 1
    # Packer slab widths ride a q*128 ladder with q in {2^k, 3*2^k}
    # (binning._ladder_width), so a divisor landing the chunk under the
    # target always exists and sits within a ~2x band of the ideal chunk
    # count. Scan only that band, and FAIL if the invariant is broken —
    # a silent full-slab fallback would reintroduce the >2^31-byte
    # transient this chunking exists to prevent.
    m = -(-slab // target)  # smallest count whose chunk fits
    while m <= 4 * (-(-slab // target)) and slab % m:
        m += 1
    if slab % m:
        raise AssertionError(
            f"slab width {slab} has no divisor with chunk <= "
            f"{target}: the packer's ladder-width invariant "
            "(q*128, q in 2^k / 3*2^k) was broken upstream"
        )
    return m


def _tile_machinery(points, mask, rel_starts, spans, slab_starts, eps, slab):
    """Shared block/slab plumbing: returns (blocks pytree for lax.map,
    slabs_of, tile_adj, nb) for [B]-plane sweeps."""
    b = points.shape[0]
    t = BANDED_BLOCK
    if b % t:
        raise ValueError(f"bucket width {b} not a multiple of {t}")
    nb = b // t
    # run tables may arrive uint16 (half the upload); widen on device
    rel_starts = rel_starts.astype(jnp.int32)
    spans = spans.astype(jnp.int32)
    eps2 = jnp.asarray(eps, dtype=points.dtype) ** 2
    sc = slab // _slab_chunks(slab)
    offs = jnp.arange(sc, dtype=jnp.int32)
    # Coordinate planes: slicing [..., D]-shaped rows would pad the minor
    # dim to the 128-lane tile on TPU; [B] planes slice cleanly. D is 2 for
    # planar runs, 3 for spherical-chord runs (ops/sphere.py) — the
    # difference-form distance generalizes as a static unrolled sum.
    planes = tuple(points[:, j] for j in range(points.shape[1]))

    blocks = (
        tuple(pl.reshape(nb, t) for pl in planes),
        mask.reshape(nb, t),
        rel_starts.reshape(nb, t, BANDED_ROWS),
        spans.reshape(nb, t, BANDED_ROWS),
        slab_starts,
    )

    def slabs_of(plane, origins, c0):
        """[B] plane, [R] origins, chunk offset -> [R, SC] slab-chunk
        rows (contiguous slices)."""
        return jnp.stack(
            [
                lax.dynamic_slice(plane, (origins[k] + c0,), (sc,))
                for k in range(BANDED_ROWS)
            ]
        )

    def tile_adj(bpl, bm, brel, bspan, borig, c0):
        """The fused [T, R, SC] adjacency tile of one block's slab chunk
        (never stored across sweeps — recomputed wherever consumed).
        ``offs + c0`` are slab-relative positions, the frame of the run
        tables, so a run spanning chunks contributes exactly its
        per-chunk segments."""
        co = offs + c0
        d2 = None
        for pl, bp in zip(planes, bpl):
            sl = slabs_of(pl, borig, c0)  # [R, SC]
            df = bp[:, None, None] - sl[None, :, :]  # [T, R, SC]
            d2 = df * df if d2 is None else d2 + df * df
        sm = slabs_of(mask, borig, c0)
        inrun = (co[None, None, :] >= brel[:, :, None]) & (
            co[None, None, :] < (brel + bspan)[:, :, None]
        )
        return inrun & sm[None, :, :] & (d2 <= eps2) & bm[:, None, None]

    return blocks, slabs_of, tile_adj, nb, _slab_chunks(slab), sc


@functools.partial(jax.jit, static_argnames=("min_points", "slab"))
def banded_phase1(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    rel_starts: jnp.ndarray,
    spans: jnp.ndarray,
    slab_starts: jnp.ndarray,
    cx: jnp.ndarray,
    eps: float,
    min_points: int,
    slab: int = 128,
):
    """Sweeps 1+2: eps-neighbor counts and the window-cell edge bitmask.

    Args:
      points: [B, D] (D in {2, 3}) coordinates in CELL-SORTED order
        (padding at the tail); B a multiple of BANDED_BLOCK. D == 3 is the
        spherical-chord payload (ops/sphere.py) — cells/runs then live in
        the projected grid space while distances are measured here.
      mask: [B] validity.
      rel_starts/spans: [B, BANDED_ROWS] int32 run starts (relative to the
        row's block slab) / lengths.
      slab_starts: [B // BANDED_BLOCK, BANDED_ROWS] int32 absolute slab
        origins; host guarantees slab_start + slab <= B and every run fits.
      cx: [B] int32 fine-grid cell column per position.
      eps, min_points: DBSCAN parameters (min_points static, self-inclusive).
      slab: static slab length S.

    Returns (counts [B] int32, core [B] bool, bits [B] int32) where bit
    k*5+j of bits[i] is set iff some CORE point in the window cell
    (dy=k-2, dx=j-2) is eps-adjacent to point i (bit 12 = own cell; for a
    core point that bit is always set via self-adjacency). Bits are
    computed for EVERY valid row: core rows' bits are the cell graph's
    edge list (host masks to core rows before building edges), non-core
    rows' bits drive the border algebra — min seed over set bits — so no
    third sweep is needed (dbscan_tpu/parallel/cellgraph.py).
    """
    blocks, slabs_of, tile_adj, nb, n_chunks, sc = _tile_machinery(
        points, mask, rel_starts, spans, slab_starts, eps, slab
    )
    batch = _block_batch(slab, points.shape[1])
    t = BANDED_BLOCK

    def count_block(args):
        def one_chunk(ci, acc):
            return acc + jnp.sum(
                tile_adj(*args, ci * sc), axis=(1, 2), dtype=jnp.int32
            )
        if n_chunks == 1:
            return one_chunk(0, jnp.zeros((t,), jnp.int32))
        return lax.fori_loop(
            0, n_chunks, one_chunk, jnp.zeros((t,), jnp.int32)
        )

    counts = lax.map(count_block, blocks, batch_size=batch).reshape(-1)
    core = (counts >= jnp.int32(min_points)) & mask

    cx_blocks = cx.reshape(nb, BANDED_BLOCK)

    def bits_block(args):
        bpl, bm, brel, bspan, borig, bcx = args

        def one_chunk(ci, acc):
            c0 = ci * sc
            adj = tile_adj(bpl, bm, brel, bspan, borig, c0)
            score = slabs_of(core, borig, c0)  # [R, SC] col core mask
            adj_cc = adj & score[None, :, :]
            scx = slabs_of(cx, borig, c0)  # [R, SC] col cell columns
            # Window column slot of each candidate: 0..4 whenever adj is
            # true (the run covers exactly cx-2..cx+2 of the row's
            # window); the clip only disciplines junk at adj-false
            # entries before the shift.
            dxm = scx[None, :, :] - bcx[:, None, None] + 2
            krow = jnp.arange(BANDED_ROWS, dtype=jnp.int32)[None, :, None]
            shift = jnp.clip(krow * 5 + dxm, 0, BANDED_WIN - 1)
            contrib = jnp.where(
                adj_cc, jnp.int32(1) << shift, jnp.int32(0)
            )
            return acc | lax.reduce(
                contrib, jnp.int32(0), lax.bitwise_or, (1, 2)
            )

        if n_chunks == 1:
            return one_chunk(0, jnp.zeros((t,), jnp.int32))
        return lax.fori_loop(
            0, n_chunks, one_chunk, jnp.zeros((t,), jnp.int32)
        )

    bits = lax.map(
        bits_block, (*blocks, cx_blocks), batch_size=batch
    ).reshape(-1)
    return counts, core, bits


# Block length of the device-side segmented-OR scan (and the alignment the
# packer's group sizes already satisfy: BANDED_BLOCK is a multiple of it).
SCAN_BLOCK = 512


@jax.jit
def banded_postpass(cores, bitses, segflags, or_idx):
    """Device-side compaction of the banded phase-1 outputs.

    The link from device to host runs at ~15 MB/s with ~0.5 s latency per
    pull (TPU-over-tunnel), so pulling the raw per-slot (core, bits) arrays
    — 5 bytes/slot across every group — dominated the whole pipeline at
    10M+ points. This pass reduces what crosses the link to three compact
    artifacts, leaving the big arrays resident in HBM:

      1. ``core_packed``: the concatenated core mask bit-packed 8x
         (np.unpackbits-compatible big-endian weights; jnp.packbits itself
         lowers to seconds-slow code here, a dot with bit weights doesn't);
      2. ``srb``: a BLOCK-LOCAL segmented bitwise-OR scan of the core rows'
         window bitmasks — segments are fine-grid cells (``segflags`` marks
         cell starts), with an implicit reset every SCAN_BLOCK slots. The
         scan value at a cell's last slot ORs its core members back to
         max(cell start, block start); the host combines the few cells that
         span blocks by also gathering the intervening block-end slots
         (parallel/cellgraph.py::cell_layout). Block-local Hillis-Steele
         unrolls to log2(SCAN_BLOCK) elementwise steps — milliseconds,
         where lax.associative_scan over the flat array took minutes;
      3. ``bits_flat``: the concatenated raw bitmasks, kept on DEVICE as
         the source for a targeted gather of border-candidate rows only.

    Args:
      cores: tuple of [P, B] bool phase-1 core masks (one per group).
      bitses: tuple of [P, B] int32 phase-1 window bitmasks.
      segflags: tuple of [P*B] bool cell-start flags in flat row-major
        order (host-computed from the packer's cell ids).
      or_idx: [G] int32 flat positions to read the scan back at (the
        per-cell OR gather plan, cellgraph.cell_layout) — gathered here
        and BITCAST onto the tail of the packed-core pull so both
        artifacts cross the link in ONE transfer (each pull costs ~0.5 s
        of latency alone).

    Returns (combo [M/8 + 4*G] uint8 — packed core bits followed by the
    little-endian bytes of the gathered int32 scan values — and bits_flat
    [M] int32, resident) over the flat concatenation of all groups (M is
    a multiple of SCAN_BLOCK: every group's P*B is).
    """
    core_flat = jnp.concatenate([c.reshape(-1) for c in cores])
    bits_flat = jnp.concatenate([b.reshape(-1) for b in bitses])
    f = jnp.concatenate(list(segflags)).reshape(-1, SCAN_BLOCK)
    v = jnp.where(core_flat, bits_flat, 0).reshape(-1, SCAN_BLOCK)
    d = 1
    while d < SCAN_BLOCK:
        fp = jnp.pad(f, ((0, 0), (d, 0)), constant_values=True)[:, :SCAN_BLOCK]
        vp = jnp.pad(v, ((0, 0), (d, 0)))[:, :SCAN_BLOCK]
        v = jnp.where(f, v, v | vp)
        f = f | fp
        d *= 2
    w = jnp.array([128, 64, 32, 16, 8, 4, 2, 1], jnp.int32)
    packed = (
        (core_flat.reshape(-1, 8).astype(jnp.int32) * w)
        .sum(axis=1)
        .astype(jnp.uint8)
    )
    orvals = v.reshape(-1)[or_idx]
    or_bytes = lax.bitcast_convert_type(orvals, jnp.uint8).reshape(-1)
    return jnp.concatenate([packed, or_bytes]), bits_flat


@jax.jit
def gather_flat(src, idx):
    """One-array device gather: compact readout of ``idx`` positions from a
    resident flat array (indices host-padded; out-of-range clamps)."""
    return src[idx]


# --- device-resident cellcc finalize ----------------------------------
#
# The host finalize (parallel/cellgraph.py) pulled each chunk's packed
# combo buffer, ran np.unpackbits/np.flatnonzero over every slot, built
# the cell-graph edge list, and solved connected components with scipy —
# 20+ s of host work on the critical path at 3M+ points
# (`cellcc_pull_core_s`). These two kernels keep all of that on device
# (the GPU-DBSCAN decomposition move, cf. the CUDA cluster merge of
# arXiv:1506.02226): `cellcc.unpack` folds each chunk's packed slabs
# into per-cell partials as the chunk flushes, and `cellcc.cc` runs the
# cell connected-components union as iterated min-label propagation +
# pointer jumping (ops/propagation.py window_cc) plus the whole border
# algebra, emitting ONLY the final valid-prefix-compacted [V] labels.
# Orchestration (uploads, pull, split, fault degrade to the host
# oracle) lives in cellgraph.finalize_device / driver.

#: chunk slots per lax.map step of the cc label pass: bounds the
#: [batch, SCAN_BLOCK, BANDED_WIN] gather/unpack transients to ~100 MB
#: while keeping enough blocks in flight to fill the VPU.
_CC_BLOCK_BATCH = 2048

_INT32_INF = 2**31 - 1  # == ops.labels.SEED_NONE: min-identity sentinel


@functools.lru_cache(maxsize=64)
def compiled_cellcc_unpack(n_cells_pad: int):
    """Build (once per padded cell count) the jitted per-chunk unpack:
    (combo, cell_flat, fold_flat, or_gid) -> (core [M] bool, cellor
    [C, 25] bool, cellfold [C] int32), all device-resident.

    combo is the banded_postpass output (packed core bits, then the
    little-endian bytes of the gathered segmented-OR scan values);
    cell_flat/fold_flat are the chunk's flat per-slot global cell id /
    fold index (invalid slots carry the sentinel ``n_cells_pad - 1``);
    or_gid maps each gathered scan value to its cell (host-padded to the
    same ladder as the postpass or_idx, padding -> sentinel). The
    per-cell OR rides a scatter-max of the unpacked scan values — a cell
    spanning SCAN_BLOCK boundaries has several gather positions, and OR
    is order-free — and the per-cell min core fold a scatter-min, so the
    partials merge across chunks elementwise (each cell lives in exactly
    one chunk; the others contribute identities).
    """
    sentinel = jnp.int32(n_cells_pad - 1)

    def unpack(combo, cell_flat, fold_flat, or_gid):
        m = cell_flat.shape[0]
        m8 = m // 8
        # np.unpackbits-compatible big-endian unpack (bit 7 first)
        shifts = jnp.arange(7, -1, -1, dtype=jnp.int32)
        core = (
            ((combo[:m8].astype(jnp.int32)[:, None] >> shifts[None, :]) & 1)
            .reshape(-1)
            .astype(bool)
        )
        k = or_gid.shape[0]
        orvals = lax.bitcast_convert_type(
            combo[m8 : m8 + 4 * k].reshape(k, 4), jnp.int32
        )
        win_iota = jnp.arange(BANDED_WIN, dtype=jnp.int32)
        unp = ((orvals[:, None] >> win_iota[None, :]) & 1).astype(jnp.int32)
        cellor = (
            jnp.zeros((n_cells_pad, BANDED_WIN), jnp.int32)
            .at[or_gid]
            .max(unp, mode="drop")
            .astype(bool)
        )
        # the padded or_gid positions gather REAL scan values (the pad
        # index is slot 0) into the sentinel row: clear it, or the
        # phantom adjacency costs one extra CC sweep whenever the pad
        # rung crosses a ladder boundary — cellcc.cc_iters must track
        # the cell graph's diameter, not the padding (it is regress-
        # gated); labels were already immune (cellfold[sentinel] = INF)
        cellor = cellor.at[n_cells_pad - 1].set(False)
        valid = cell_flat != sentinel
        folds = jnp.where(core & valid, fold_flat, jnp.int32(_INT32_INF))
        cellfold = (
            jnp.full((n_cells_pad,), _INT32_INF, jnp.int32)
            .at[cell_flat]
            .min(folds, mode="drop")
        )
        return core, cellor, cellfold

    return jax.jit(unpack)


@functools.lru_cache(maxsize=64)
def compiled_cellcc_cc(
    engine: str, out_slots: int, prop_mode: str = "iterated",
    warm: bool = False,
):
    """Build the fused device finalize: cell CC + seeds + border algebra
    + valid-prefix compaction over ALL chunks, one dispatch.

    Args (per call): wintab [C, 25] int32 (-1 = unoccupied window slot),
    then per-chunk tuples — cellors/cellfolds (the unpack partials) and
    cores/bitses/cells/folds (per-slot flat arrays, chunk order), and
    ``labs`` — the per-chunk first-sweep label partials the fused
    Pallas unpack emits (ops/pallas_banded.py; EMPTY tuple on the
    split unpack path, ``warm`` says which was traced). The label
    algebra is cellgraph.finalize_compact's, verbatim in int32:
    identical components (window_cc's min-index representative vs
    scipy's arbitrary numbering never matters — seeds are component-MIN
    folds, numbering-free), identical border adoption, so labels are
    byte-identical to the host oracle. Outputs are the valid slots'
    seeds/flags in row-major prefix order (exactly the host finalize's
    flat per-group layout, concatenated), padded to the static
    ``out_slots`` ladder, plus the CC sweep count.

    ``prop_mode`` ("unionfind"/"iterated") is part of the build key —
    the propagation knob must mint a fresh trace, or an in-process
    toggle (tests, the tuner) would silently reuse the other mode's
    compiled loop.
    """
    naive = engine == "naive"
    inf = jnp.int32(_INT32_INF)

    def cc(wintab, cellors, cellfolds, cores, bitses, cells, folds, labs):
        from dbscan_tpu.ops.propagation import window_cc

        c1 = wintab.shape[0]
        cellor = cellors[0]
        cellfold = cellfolds[0]
        for o in cellors[1:]:
            cellor = cellor | o
        for f in cellfolds[1:]:
            cellfold = jnp.minimum(cellfold, f)

        init = None
        if warm and labs:
            # per-chunk first-sweep partials merge elementwise: the full
            # cell graph's first neighbor-min sweep is the min over each
            # chunk's edge subset, so starting here is exactly "sweep 1
            # already ran" — the fixed point (and labels) are unchanged,
            # only the counted sweeps drop
            init = labs[0]
            for l in labs[1:]:
                init = jnp.minimum(init, l)
        comp, iters = window_cc(
            cellor, wintab, mode=prop_mode, init=init
        )
        # seed per component = min cell fold over member cells; comp is
        # the component-min cell index, so one scatter-min + one gather
        rootmin = (
            jnp.full((c1,), _INT32_INF, jnp.int32).at[comp].min(cellfold)
        )
        seed_of_cell = rootmin[comp]
        # per-(cell, window-slot) seed table for the border algebra:
        # junk at -1 (unoccupied) slots is masked to the min identity
        seed_win = jnp.where(
            wintab >= 0,
            seed_of_cell[jnp.clip(wintab, 0, c1 - 1)],
            inf,
        )

        cell_flat = jnp.concatenate(list(cells))
        fold_flat = jnp.concatenate(list(folds))
        bits_flat = jnp.concatenate(list(bitses))
        core_flat = jnp.concatenate(list(cores))
        win_iota = jnp.arange(BANDED_WIN, dtype=jnp.int32)

        def label_block(args):
            cb, fb, bb, kb = args
            sw = seed_win[cb]  # [T, 25] row gather
            unp = ((bb[:, None] >> win_iota[None, :]) & 1) != 0
            nbr = jnp.min(jnp.where(unp, sw, inf), axis=1)
            # NAIVE adopts a border only when the adopting expansion
            # precedes the point's own fold visit; ARCHERY adopts
            # whenever any window bit is set (nbr < inf then: a set bit
            # means an adjacent core exists, whose cell has a real seed)
            adopt = nbr < (fb if naive else inf)
            seeds = jnp.where(kb, seed_of_cell[cb], jnp.where(adopt, nbr, inf))
            flags = jnp.where(
                kb,
                jnp.int8(CORE),
                jnp.where(adopt, jnp.int8(BORDER), jnp.int8(NOISE)),
            )
            return seeds, flags

        nb = cell_flat.shape[0] // SCAN_BLOCK
        seeds, flags = lax.map(
            label_block,
            (
                cell_flat.reshape(nb, SCAN_BLOCK),
                fold_flat.reshape(nb, SCAN_BLOCK),
                bits_flat.reshape(nb, SCAN_BLOCK),
                core_flat.reshape(nb, SCAN_BLOCK),
            ),
            batch_size=min(nb, _CC_BLOCK_BATCH),
        )
        # valid-prefix compaction (the "only final labels cross the
        # link" contract): valid slots are per-row prefixes, so their
        # running count IS the compact position; invalid slots scatter
        # out of range and drop
        valid = cell_flat != jnp.int32(c1 - 1)
        pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
        tgt = jnp.where(valid, pos, jnp.int32(out_slots))
        out_seeds = (
            jnp.full((out_slots,), _INT32_INF, jnp.int32)
            .at[tgt]
            .set(seeds.reshape(-1), mode="drop")
        )
        out_flags = (
            jnp.zeros((out_slots,), jnp.int8)
            .at[tgt]
            .set(flags.reshape(-1), mode="drop")
        )
        return out_seeds, out_flags, iters

    return jax.jit(cc)
