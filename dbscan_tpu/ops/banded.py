"""Grid-banded local DBSCAN engine: O(B * slab) per partition, gather-free.

The dense engine (ops/local_dbscan.py) materializes the full [B, B]
eps-adjacency — the TPU-shaped replacement for the reference's O(n^2) linear
scans (LocalDBSCANNaive.scala:72-78). That is optimal for small partitions
but quadratic in compute AND memory, which caps usable partition sizes.

This engine exploits the spatial structure DBSCAN itself is built on: snap
points to an eps-sized grid and sort them by cell (row-major). Every
eps-neighbor of a point then lies in the 3x3 surrounding cells, which in
cell-sorted order form three contiguous runs — one per cell row. Runs are
consumed BLOCK-WISE: for a block of BANDED_BLOCK consecutive sorted points,
the union of their per-cell-row runs is (near-)contiguous, because cell-row
boundaries in query space map to adjacent positions in candidate space. The
host (dbscan_tpu/parallel/binning.py) measures the exact union slab per
(block, cell row) and a static bound S >= every slab length; the device then
processes each block as

  3 x dynamic_slice(plane, slab_start, S)       <- contiguous DMA, no gather
  dense [T, 3, S] difference tile on the VPU    <- compare vs eps^2
  per-row validity from (rel_start, span)       <- mask inside the slab

instead of all-pairs [B, B]. Two deliberate non-choices, both measured on
TPU v5e:

- no per-row windowed GATHERS: XLA lowers 1-D gathers with arbitrary index
  tensors to scalar loops (~40M elements/s — orders of magnitude under HBM
  bandwidth); contiguous dynamic slices stream at full bandwidth;
- no materialized adjacency: storing [B, 3, S] booleans makes every
  propagation sweep HBM-bound on re-reading them; recomputing the masked
  distance test fused into each sweep keeps all sweep traffic at
  O(slab) loads per block and runs ~3x faster while using O(B) memory.

Components use the shared min-label fixed point (ops/propagation.py) with
the neighbor-min computed by the block-slab sweep over label planes, and the
pointer jump routed through the sorted-position permutation. Border algebra
is the dense path's _finalize — fold indices are carried explicitly since
array order is cell-sorted, not fold order.

Correctness notes:
- the host uses a cell size slightly LARGER than eps (binning.CELL_SLACK) so
  any pair the f32 distance test could accept lies within the 3x3 ring even
  under worst-case rounding;
- slabs may cover unrelated cells (padding, row straddles); each row masks
  its true run with (rel_start, span), so no pair is counted twice across
  the three row-slabs and nothing outside the run contributes;
- label VALUES are original fold indices (reference numbering semantics,
  LocalDBSCANNaive.scala:45-64) while label POSITIONS are cell-sorted.

Exactness vs the dense engine: the pairwise measure is the identical
difference-form arithmetic (ops/distance.py euclidean D<=4 path), so in any
fixed dtype the two engines produce bit-identical labels (tested).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from dbscan_tpu.ops.labels import SEED_NONE
from dbscan_tpu.ops.local_dbscan import LocalResult, _finalize
from dbscan_tpu.ops.propagation import min_label_fixed_point

# Rows per block-slab tile; defined host-side (dbscan_tpu/parallel/
# binning.py) next to the packer that must agree on it — see there for the
# current value and its VMEM/DMA sizing rationale.
from dbscan_tpu.parallel.binning import BANDED_BLOCK

# Element budget for how many blocks one lax.map step may process at once
# (vmapped): bounds the fused tile transients to ~1 GB while cutting the
# sequential step count (per-step loop overhead measured ~20% at batch 32).
_BLOCK_BATCH_ELEMS = 1 << 28


def _block_batch(slab: int) -> int:
    return max(1, min(32, _BLOCK_BATCH_ELEMS // (BANDED_BLOCK * 3 * slab)))


@functools.partial(
    jax.jit, static_argnames=("min_points", "engine", "slab")
)
def banded_local_dbscan(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    fold_idx: jnp.ndarray,
    pos_of_fold: jnp.ndarray,
    rel_starts: jnp.ndarray,
    spans: jnp.ndarray,
    slab_starts: jnp.ndarray,
    eps: float,
    min_points: int,
    engine: str = "naive",
    slab: int = 128,
) -> LocalResult:
    """Cluster one cell-sorted, padded partition in O(B * 3 * slab).

    Args:
      points: [B, 2] coordinates in CELL-SORTED order (padding at the tail);
        B must be a multiple of BANDED_BLOCK.
      mask: [B] validity.
      fold_idx: [B] int32 original fold index per sorted position (padding
        positions hold their own position).
      pos_of_fold: [B] int32 inverse permutation: sorted position of fold
        index f.
      rel_starts: [B, 3] int32 run starts RELATIVE to the row's block slab,
        one per neighboring cell row.
      spans: [B, 3] int32 run lengths; 0 for out-of-grid rows.
      slab_starts: [B // BANDED_BLOCK, 3] int32 absolute slab origins; host
        guarantees slab_start + slab <= B and every run fits its slab.
      eps: neighborhood radius (euclidean).
      min_points: self-inclusive density threshold (static).
      engine: "naive" | "archery" (static).
      slab: static slab length S.

    Returns a :class:`LocalResult` of [B] arrays in SORTED order; seed label
    values are fold indices (densify with labels.seed_to_local_ids as usual).
    """
    if engine not in ("naive", "archery"):
        raise ValueError(f"unknown engine {engine!r}")
    b = points.shape[0]
    t = BANDED_BLOCK
    if b % t:
        raise ValueError(f"bucket width {b} not a multiple of {t}")
    nb = b // t
    none = jnp.int32(SEED_NONE)
    eps2 = jnp.asarray(eps, dtype=points.dtype) ** 2
    offs = jnp.arange(slab, dtype=jnp.int32)
    batch = _block_batch(slab)
    # Coordinate planes: slicing [..., 2]-shaped rows would pad the minor
    # dim to the 128-lane tile on TPU; [B] planes slice cleanly.
    px = points[:, 0]
    py = points[:, 1]

    px_b = px.reshape(nb, t)
    py_b = py.reshape(nb, t)
    mask_b = mask.reshape(nb, t)
    rel_b = rel_starts.reshape(nb, t, 3)
    span_b = spans.reshape(nb, t, 3)
    blocks = (px_b, py_b, mask_b, rel_b, span_b, slab_starts)

    def slabs_of(plane, origins):
        """[B] plane, [3] origins -> [3, S] slab rows (contiguous slices)."""
        return jnp.stack(
            [
                lax.dynamic_slice(plane, (origins[k],), (slab,))
                for k in range(3)
            ]
        )

    def tile_adj(bx, by, bm, brel, bspan, borig):
        """The fused [T, 3, S] adjacency tile of one block (never stored
        across sweeps — recomputed wherever it is consumed)."""
        sx = slabs_of(px, borig)  # [3, S]
        sy = slabs_of(py, borig)
        sm = slabs_of(mask, borig)
        dx = bx[:, None, None] - sx[None, :, :]  # [T, 3, S]
        dy = by[:, None, None] - sy[None, :, :]
        d2 = dx * dx + dy * dy
        inrun = (offs[None, None, :] >= brel[:, :, None]) & (
            offs[None, None, :] < (brel + bspan)[:, :, None]
        )
        return inrun & sm[None, :, :] & (d2 <= eps2) & bm[:, None, None]

    def count_block(args):
        return jnp.sum(tile_adj(*args), axis=(1, 2), dtype=jnp.int32)

    counts = lax.map(count_block, blocks, batch_size=batch).reshape(b)
    core = (counts >= jnp.int32(min_points)) & mask

    def windowed_min(labels):
        """Per row: min label over adjacent neighbors ([B] -> [B])."""

        def one(args):
            bx, by, bm, brel, bspan, borig = args
            adj = tile_adj(bx, by, bm, brel, bspan, borig)
            sl = slabs_of(labels, borig)  # [3, S]
            return jnp.min(
                jnp.where(adj, sl[None, :, :], none), axis=(1, 2)
            )

        return lax.map(one, blocks, batch_size=batch).reshape(b)

    # Components of the core-core adjacency: labels at non-core positions
    # are SEED_NONE from init and never updated (neighbor-min masked to core
    # rows), and SEED_NONE-valued neighbors are transparent to min() — so
    # the windowed min over the full adjacency restricts itself to core-core
    # edges exactly as the dense path's adj_cc does.
    init = jnp.where(core, fold_idx, none)

    def neighbor_min(labels):
        return jnp.where(core, windowed_min(labels), none)

    comp = min_label_fixed_point(init, neighbor_min, pos_of_label=pos_of_fold)

    # Min seed among eps-adjacent cores, for every point (border algebra).
    core_nbr_seed = windowed_min(comp)

    return _finalize(
        mask, core, comp, core_nbr_seed, counts, engine, own_idx=fold_idx
    )
