"""Scalar-prefetch Pallas banded engine: no XLA slab gather.

The base Pallas banded port (ops/pallas_banded.py) loses 1.5-2.1x to the
XLA engine at production sizes because Mosaic's static BlockSpec index
maps cannot express the DATA-DEPENDENT slab origins, forcing an XLA
gather to materialize [nb, R, S] slab tensors (points + mask + cx +
core) before the kernels run. This module is the VERDICT r4 item-7
attempt at Mosaic's intended mechanism for data-dependent tiling:
``PrefetchScalarGridSpec`` index maps that read per-(block, window-row)
slab origins from scalar-prefetch (SMEM) operands, so each kernel step
DMAs its slab chunk STRAIGHT from the flat per-point arrays in HBM —
the gather disappears entirely.

Alignment contract: Mosaic block indices address whole blocks, so slab
origins are aligned DOWN to the slab-chunk width on the host
(``orig_blk = ss // sc``) and the chunk walk is extended by one chunk
(``ns + 1``) to keep covering the original [ss, ss + slab) window. The
cost is the alignment padding the r4 verdict asked to measure: at most
one extra chunk per (block, row) sweep, i.e. a factor (ns + 1) / ns of
slab traffic (~1.05-2x depending on slab width), plus positions below
the true origin that the run-window test rejects. Run tables stay in
ORIGINAL slab coordinates: the kernel reconstructs absolute positions
from the aligned origin and compares against absolute run starts
(``ss + rel``), so acceptance is bit-identical to ops/banded.py — the
widened window only adds rejected candidates.

Outputs are bit-identical to ops/banded.py::banded_phase1 (pinned by
tests/test_pallas_banded.py in interpreter mode); on-chip measurement
rides ``bench.py`` BENCH_PALLAS=1 with DBSCAN_PALLAS_SP=1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dbscan_tpu.ops.banded import _slab_chunks
from dbscan_tpu.ops.pallas_banded import (
    TSUB,
    _PALLAS_SLAB_CHUNK,
    _accumulate,
    _interpret,
)
from dbscan_tpu.parallel.binning import BANDED_BLOCK, BANDED_ROWS


def _sp_block_spec(t, nsub):
    # per-point [rows, 1, T] blocks; index map must accept the two
    # scalar-prefetch refs PrefetchScalarGridSpec appends
    return pl.BlockSpec(
        (1, 1, t), lambda i, s, j, orig, ss: (i * nsub + j, 0, 0)
    )


def _sp_row_spec(sc, k):
    # one [1, SC] chunk of window row k's slab, addressed DIRECTLY in
    # the flat [1, B_pad] array at the aligned dynamic block origin —
    # this line is the whole point of the module: the index map reads
    # the data-dependent origin from SMEM, no gathered tensor exists
    return pl.BlockSpec(
        (1, sc), lambda i, s, j, orig, ss: (0, orig[i, k] + s)
    )


def _sp_eps_spec():
    return pl.BlockSpec(
        (1, 1), lambda i, s, j, orig, ss: (0, 0), memory_space=pltpu.SMEM
    )


def _sp_tile_adj(
    orig_ref, ss_ref, bl_planes, bm, brel, bspan, prow_k, mrow_k,
    offs_rel, eps2, i, s, sc, k,
):
    """[T, SC] adjacency tile of window row k from direct row slices.
    Positions are ABSOLUTE (aligned origin + chunk offset), runs are
    absolute (original origin + relative start) — acceptance identical
    to the gathered path, the alignment delta only shifts the frame."""
    pos = (orig_ref[i, k] + s) * sc + offs_rel
    start = ss_ref[i, k] + brel[0, k][:, None]
    inrun = (pos >= start) & (pos < start + bspan[0, k][:, None])
    d2 = None
    for bp, sl in zip(bl_planes, prow_k):
        df = bp[0, 0][:, None] - sl[0, :][None, :]
        d2 = df * df if d2 is None else d2 + df * df
    return (
        inrun
        & (mrow_k[0, :][None, :] > 0)
        & (d2 <= eps2)
        & (bm[0, 0][:, None] > 0)
    )


def _make_counts_kernel_sp(d: int, sc: int, nsub: int, ns: int):
    t = TSUB
    r = BANDED_ROWS

    def kernel(orig_ref, ss_ref, eps2_ref, *refs):
        bl_planes = refs[0:d]
        bm = refs[d]
        brel = refs[d + 1]
        bspan = refs[d + 2]
        k0 = d + 3
        prows = refs[k0 : k0 + d * r]  # plane-major: p0k0..p0k4, p1k0..
        mrows = refs[k0 + d * r : k0 + (d + 1) * r]
        out = refs[-2]
        acc_ref = refs[-1]
        i = pl.program_id(0)
        s = pl.program_id(1)
        offs_rel = jax.lax.broadcasted_iota(jnp.int32, (t, sc), 1)
        eps2 = eps2_ref[0, 0]
        acc = jnp.zeros((t,), jnp.int32)
        for k in range(r):
            adj = _sp_tile_adj(
                orig_ref, ss_ref, bl_planes, bm, brel, bspan,
                [prows[p * r + k] for p in range(d)], mrows[k],
                offs_rel, eps2, i, s, sc, k,
            )
            # dtype pinned: under interpret+x64 a default integer sum
            # widens to int64 and the scratch store rejects the mix
            acc = acc + jnp.sum(
                adj.astype(jnp.int32), axis=1, dtype=jnp.int32
            )
        _accumulate(out, acc_ref, acc, nsub, ns, lambda a, b: a + b)

    return kernel


def _make_bits_kernel_sp(d: int, sc: int, nsub: int, ns: int):
    t = TSUB
    r = BANDED_ROWS

    def kernel(orig_ref, ss_ref, eps2_ref, *refs):
        bl_planes = refs[0:d]
        bm = refs[d]
        brel = refs[d + 1]
        bspan = refs[d + 2]
        bcx = refs[d + 3]
        k0 = d + 4
        prows = refs[k0 : k0 + d * r]
        mrows = refs[k0 + d * r : k0 + (d + 1) * r]
        cxrows = refs[k0 + (d + 1) * r : k0 + (d + 2) * r]
        corows = refs[k0 + (d + 2) * r : k0 + (d + 3) * r]
        out = refs[-2]
        acc_ref = refs[-1]
        i = pl.program_id(0)
        s = pl.program_id(1)
        offs_rel = jax.lax.broadcasted_iota(jnp.int32, (t, sc), 1)
        eps2 = eps2_ref[0, 0]
        bits = jnp.zeros((t,), jnp.int32)
        for k in range(r):
            adj = _sp_tile_adj(
                orig_ref, ss_ref, bl_planes, bm, brel, bspan,
                [prows[p * r + k] for p in range(d)], mrows[k],
                offs_rel, eps2, i, s, sc, k,
            )
            adj_cc = adj & (corows[k][0, :][None, :] > 0)
            dxm = cxrows[k][0, :][None, :] - bcx[0, 0][:, None] + 2
            for dx in range(5):
                hit = jnp.any(adj_cc & (dxm == dx), axis=1)
                bits = bits | (
                    hit.astype(jnp.int32) << jnp.int32(k * 5 + dx)
                )
        _accumulate(out, acc_ref, bits, nsub, ns, lambda a, b: a | b)

    return kernel


def _flat_pad(a, sc):
    """[B] -> [1, B + sc] (zero tail): an aligned-down origin plus the
    extended chunk walk reads at most sc past the clamped origin+slab."""
    return jnp.pad(a, (0, sc)).reshape(1, -1)


@functools.partial(jax.jit, static_argnames=("min_points", "slab"))
def banded_phase1_pallas_sp(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    rel_starts: jnp.ndarray,
    spans: jnp.ndarray,
    slab_starts: jnp.ndarray,
    cx: jnp.ndarray,
    eps: float,
    min_points: int,
    slab: int = 128,
):
    """Drop-in replacement for banded_phase1 via scalar-prefetch tiling
    (same contract/outputs: counts [B] i32, core [B] bool, bits [B] i32).
    """
    b, d = points.shape
    t = BANDED_BLOCK
    r = BANDED_ROWS
    if b % t:
        raise ValueError(f"bucket width {b} not a multiple of {t}")
    nb = b // t
    nsub = t // TSUB
    rows = nb * nsub
    ns0 = _slab_chunks(slab, _PALLAS_SLAB_CHUNK)
    sc = slab // ns0
    ns = ns0 + 1  # one extra chunk covers the alignment shift

    planes = tuple(points[:, j].astype(jnp.float32) for j in range(d))
    m32 = mask.astype(jnp.int32)
    rel = (
        rel_starts.astype(jnp.int32)
        .reshape(rows, TSUB, r)
        .transpose(0, 2, 1)
    )
    spn = (
        spans.astype(jnp.int32).reshape(rows, TSUB, r).transpose(0, 2, 1)
    )
    ss = slab_starts.astype(jnp.int32)
    orig_blk = ss // jnp.int32(sc)  # aligned-down origin, block units
    eps2 = jnp.asarray(eps, jnp.float32).reshape(1, 1) ** 2

    blocked_specs = [
        _sp_eps_spec(),
        *[_sp_block_spec(TSUB, nsub) for _ in range(d + 1)],
        pl.BlockSpec(
            (1, r, TSUB), lambda i, s, j, orig, sr: (i * nsub + j, 0, 0)
        ),
        pl.BlockSpec(
            (1, r, TSUB), lambda i, s, j, orig, sr: (i * nsub + j, 0, 0)
        ),
    ]
    blocked_args = [
        eps2,
        *[p.reshape(rows, 1, TSUB) for p in planes],
        m32.reshape(rows, 1, TSUB),
        rel,
        spn,
    ]
    plane_flat = [_flat_pad(p, sc) for p in planes]
    mask_flat = _flat_pad(m32, sc)

    grid = (nb, ns, nsub)
    counts = pl.pallas_call(
        _make_counts_kernel_sp(d, sc, nsub, ns),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                *blocked_specs,
                *[
                    _sp_row_spec(sc, k)
                    for _p in range(d)
                    for k in range(r)
                ],
                *[_sp_row_spec(sc, k) for k in range(r)],
            ],
            out_specs=_sp_block_spec(TSUB, nsub),
            scratch_shapes=[pltpu.VMEM((nsub, TSUB), jnp.int32)],
        ),
        out_shape=jax.ShapeDtypeStruct((rows, 1, TSUB), jnp.int32),
        interpret=_interpret(),
    )(
        orig_blk, ss, *blocked_args,
        *[pf for pf in plane_flat for _k in range(r)],
        *[mask_flat for _k in range(r)],
    ).reshape(-1)

    core = (counts >= jnp.int32(min_points)) & mask
    cx32 = cx.astype(jnp.int32)
    core32 = core.astype(jnp.int32)
    cx_flat = _flat_pad(cx32, sc)
    core_flat = _flat_pad(core32, sc)

    bits = pl.pallas_call(
        _make_bits_kernel_sp(d, sc, nsub, ns),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                *blocked_specs,
                _sp_block_spec(TSUB, nsub),  # cx blocked
                *[
                    _sp_row_spec(sc, k)
                    for _p in range(d)
                    for k in range(r)
                ],
                *[_sp_row_spec(sc, k) for k in range(r)],
                *[_sp_row_spec(sc, k) for k in range(r)],
                *[_sp_row_spec(sc, k) for k in range(r)],
            ],
            out_specs=_sp_block_spec(TSUB, nsub),
            scratch_shapes=[pltpu.VMEM((nsub, TSUB), jnp.int32)],
        ),
        out_shape=jax.ShapeDtypeStruct((rows, 1, TSUB), jnp.int32),
        interpret=_interpret(),
    )(
        orig_blk, ss, *blocked_args,
        cx32.reshape(rows, 1, TSUB),
        *[pf for pf in plane_flat for _k in range(r)],
        *[mask_flat for _k in range(r)],
        *[cx_flat for _k in range(r)],
        *[core_flat for _k in range(r)],
    )

    return counts, core, bits.reshape(-1)
