"""Spherical geometry: the haversine metric's spatial decomposition.

The reference is strictly 2-D euclidean — its distance is dx*dx + dy*dy on
the raw coordinates (DBSCANPoint.scala:26-30) and its 2eps-grid
decomposition snaps those coordinates directly (DBSCAN.scala:345-356).
Great-circle workloads (lon/lat in degrees, eps in km) therefore ran as a
SINGLE partition in round 1, which caps them at toy scale. This module
supplies the metric-aware decomposition VERDICT r1 ranked first, split
into two coordinate systems with exact, auditable error bounds:

GRID SPACE — an equirectangular projection to kilometers::

    x = R * lon_rad * cos_min,   y = R * lat_rad

with ``cos_min`` the minimum cos(lat) over the data's latitude range. For
any two data points, the projected euclidean distance NEVER exceeds the
great-circle distance by more than a curvature term of relative size
~(eps/(R*cos_min))^2 (proof sketch: hav >= 2R*sqrt(sin^2(dphi/2) +
cos(phi1)cos(phi2) sin^2(dlambda/2)) >= proj * (1 - dmax^2/24) using
sin x >= x(1 - x^2/6) and cos(phi_i) >= cos_min). So the existing
integer-grid partitioner, eps-halo duplication, and merge-band machinery
run UNCHANGED on projected coordinates with eps grown by a computed slack
(``eps_spatial``): every pair the kernel can accept is covered by some
partition's grown rectangle, exactly like the euclidean case
(DBSCAN.scala:345-356 generalized).

KERNEL SPACE — centered 3-D chord coordinates::

    u = R * (cos(lat)cos(lon), cos(lat)sin(lon), sin(lat)) - centroid

Chord length and great-circle distance are both strictly increasing in
the central angle, so ``hav(p, q) <= eps  <=>  |u_p - u_q| <=
chord_eps(eps) = 2R sin(eps / 2R)`` EXACTLY — the local engines run their
euclidean machinery (difference-form f32, D <= 4) on [x, y, z] with a
rescaled threshold: no transcendental per-pair math on the device, and no
approximation in the accept test itself. Centering bounds the f32
quantization of the stored coordinates by the dataset's chord radius
instead of the earth's.

The banded engine's fine grid lives in GRID space while its distance test
runs in KERNEL space, so its two structural guarantees pick up the
projection's distortion ratio ``r = cos_max / cos_min``:

- CLIQUE (same fine cell => kernel accepts the pair) holds when the fine
  grid is built from ``grid_eps = eps * (1 - slack) / r``;
- REACH (kernel-accepted pair => within +-2 fine cells) then needs
  ``r * (1 + slack) <= sqrt(2) * (1 - 1e-5) * (1 - slack)`` — satisfied
  by every real geospatial dataset short of a ~49-degree latitude span
  (``banded_ok``); wider spans fall back to the dense kernel per
  partition, still spatially decomposed.

Datasets the projection cannot serve — points within an eps margin of
both sides of the antimeridian, or within eps of a pole — are detected
and refused (:func:`embed` returns None) and the driver keeps round 1's
single-partition behavior for them.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from dbscan_tpu.ops.distance import EARTH_RADIUS_KM

# |lat| beyond this (degrees) is "near-pole": cos(lat) < 0.0098, the
# equirectangular x-scale degenerates and lon spans blow up.
MAX_ABS_LAT_DEG = 85.0

# Reach headroom for the banded engine: r * (1 + slack) must stay under
# sqrt(2) * (1 - 1e-5) * (1 - slack); require a 1e-3 margin on top.
_REACH_LIMIT = float(np.sqrt(2.0)) * (1.0 - 1e-5) * (1.0 - 1e-3)


class SphericalEmbedding(NamedTuple):
    """Everything the driver needs to run the euclidean pipeline on
    great-circle data. All lengths in km."""

    proj: np.ndarray  # [N, 2] float64 equirectangular grid coordinates
    chord: np.ndarray  # [N, 3] float64 centered chord kernel coordinates
    eps_chord: float  # kernel accept threshold: 2R sin(eps / 2R)
    eps_spatial: float  # halo/margin growth in grid space (>= eps)
    grid_eps: float  # banded fine-grid scale (<= eps), clique-safe
    cos_ratio: float  # r = cos_max / cos_min over the data's lat range
    slack: float  # relative error budget behind the two eps above
    banded_ok: bool  # reach constraint satisfied for the banded engine


def chord_threshold(eps_km: float) -> float:
    """Chord length equivalent to great-circle distance ``eps_km``."""
    return float(
        2.0 * EARTH_RADIUS_KM * np.sin(eps_km / (2.0 * EARTH_RADIUS_KM))
    )


def embed(
    lonlat_deg: np.ndarray, eps_km: float, f32: bool = True
) -> Optional[SphericalEmbedding]:
    """Build the two-coordinate-system embedding, or None when the data
    cannot be safely projected (antimeridian wrap, near-pole points, or an
    eps so large the curvature slack collapses the margins).

    lonlat_deg: [N, 2] (longitude, latitude) in degrees — the haversine
    metric's column convention (ops/distance.py::_haversine).
    f32: kernel coordinates will be cast to float32 (default precision);
    sizes the quantization part of the slack budget.
    """
    ll = np.asarray(lonlat_deg, dtype=np.float64)[:, :2]
    if len(ll) == 0:
        return None
    # normalize longitudes to [-180, 180): changes nothing for haversine
    # (periodic in dlon) but gives the projection one consistent branch
    lon = np.mod(ll[:, 0] + 180.0, 360.0) - 180.0
    lat = ll[:, 1]
    lat_min = float(lat.min())
    lat_max = float(lat.max())
    if max(abs(lat_min), abs(lat_max)) > MAX_ABS_LAT_DEG:
        return None

    r_earth = EARTH_RADIUS_KM
    theta = eps_km / r_earth  # central angle of eps
    cos_min = float(np.cos(np.deg2rad(max(abs(lat_min), abs(lat_max)))))
    # margin (degrees of longitude) within which a point can reach across
    # the antimeridian seam
    seam_deg = np.rad2deg(theta / cos_min) * 1.01 + 1e-9
    if float(lon.max()) > 180.0 - seam_deg and float(
        lon.min()
    ) < -180.0 + seam_deg:
        return None

    abs_lo = (
        0.0
        if lat_min <= 0.0 <= lat_max
        else min(abs(lat_min), abs(lat_max))
    )
    cos_max = float(np.cos(np.deg2rad(abs_lo)))
    ratio = cos_max / cos_min

    lam = np.deg2rad(lon)
    phi = np.deg2rad(lat)
    proj = np.empty((len(ll), 2), dtype=np.float64)
    proj[:, 0] = r_earth * cos_min * lam
    proj[:, 1] = r_earth * phi
    cp = np.cos(phi)
    chord = np.empty((len(ll), 3), dtype=np.float64)
    chord[:, 0] = r_earth * cp * np.cos(lam)
    chord[:, 1] = r_earth * cp * np.sin(lam)
    chord[:, 2] = r_earth * np.sin(phi)
    chord -= chord.mean(axis=0)

    eps_chord = chord_threshold(eps_km)
    # Slack budget (relative):
    # - curvature: the sin/asin second-order terms in both direction
    #   bounds are < (dmax^2)/4 with dmax <= theta/cos_min the largest
    #   angular separation of an acceptable pair;
    # - quantization: centered kernel coordinates of magnitude E stored in
    #   f32 perturb a distance by at most ~4E*2^-24 absolute (two
    #   endpoints x three coordinates, difference form), taken relative
    #   to eps_chord with a 1.5x cushion.
    curv = (theta / cos_min) ** 2 / 4.0
    extent = float(np.abs(chord).max()) if len(chord) else 0.0
    quant = (6.0 * extent * 2.0**-24 / eps_chord) if f32 else (
        6.0 * extent * 2.0**-53 / eps_chord
    )
    slack = curv + quant + 1e-9
    if slack > 1e-2:  # margins no longer meaningfully conservative
        return None

    eps_spatial = eps_km * (1.0 + slack)
    grid_eps = eps_km * (1.0 - slack) / ratio
    banded_ok = ratio * (1.0 + slack) / (1.0 - slack) <= _REACH_LIMIT
    return SphericalEmbedding(
        proj=proj,
        chord=chord,
        eps_chord=eps_chord,
        eps_spatial=eps_spatial,
        grid_eps=grid_eps,
        cos_ratio=ratio,
        slack=slack,
        banded_ok=banded_ok,
    )
