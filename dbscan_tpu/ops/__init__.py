"""Device-side ops: geometry, distances, the local DBSCAN kernel."""
