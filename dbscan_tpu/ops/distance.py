"""Pluggable pairwise distance measures for the local DBSCAN kernel.

The reference supports exactly one metric — 2-D squared Euclidean computed
pointwise on the JVM (DBSCANPoint.scala:26-30). Here each metric is a pair of
functions:

- ``pairwise(a, b) -> [N, M]`` measure matrix, written so XLA maps the inner
  contraction onto the MXU (matmul form) instead of an elementwise O(N*M*D)
  broadcast — this is where the FLOPs are on TPU;
- ``threshold(eps) -> scalar`` mapping the user-facing ``eps`` to the measure
  scale (eps^2 for squared Euclidean, eps itself for haversine/cosine).

A point pair is eps-adjacent iff ``pairwise(a, b) <= threshold(eps)``,
matching the reference's inclusive comparison (LocalDBSCANNaive.scala:76).

All functions accept jnp or np arrays; under ``jit`` they trace to pure XLA.
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple

import jax
import jax.numpy as jnp

EARTH_RADIUS_KM = 6371.0088


class Metric(NamedTuple):
    pairwise: Callable  # (a [N,D], b [M,D]) -> [N,M] measure
    threshold: Callable  # eps -> comparable scalar


def _euclidean_sq(a, b):
    """Squared L2, matching the reference's dx*dx + dy*dy
    (DBSCANPoint.scala:26-30) for D == 2; any D supported.

    Two regimes, both chosen for eps-boundary fidelity on TPU:
    - D <= 4: direct difference form on the VPU. Exact in the input dtype —
      no matmul, so no silent bf16 accumulation (TPU matmuls default to
      bf16 inputs, which flips thousands of boundary decisions at N~4k; the
      direct form flips none vs same-dtype numpy).
    - larger D: the |a|^2 + |b|^2 - 2ab^T expansion on the MXU with
      Precision.HIGHEST (f32 accumulate), clamped at zero since the
      expansion can go slightly negative for near-identical points.
    """
    if a.shape[-1] <= 4:
        diff = a[:, None, :] - b[None, :, :]
        return jnp.sum(diff * diff, axis=-1)
    a2 = jnp.sum(a * a, axis=-1)[:, None]
    b2 = jnp.sum(b * b, axis=-1)[None, :]
    ab = jnp.matmul(a, b.T, precision=jax.lax.Precision.HIGHEST)
    d2 = a2 + b2 - 2.0 * ab
    return jnp.maximum(d2, 0.0)


def _haversine(a, b):
    """Great-circle distance in km between [.., 2] (lon_deg, lat_deg) arrays.

    For the NYC-taxi geospatial config (BASELINE.json configs[1]); eps is in
    km. Uses the numerically-stable asin(sqrt(...)) form.
    """
    lon1, lat1 = jnp.deg2rad(a[:, 0])[:, None], jnp.deg2rad(a[:, 1])[:, None]
    lon2, lat2 = jnp.deg2rad(b[:, 0])[None, :], jnp.deg2rad(b[:, 1])[None, :]
    dlat = lat2 - lat1
    dlon = lon2 - lon1
    h = (
        jnp.sin(dlat / 2.0) ** 2
        + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin(dlon / 2.0) ** 2
    )
    return 2.0 * EARTH_RADIUS_KM * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))


def _cosine(a, b):
    """Cosine distance 1 - cos_sim, one normalized matmul (MXU). For the
    embeddings config (BASELINE.json configs[2]); eps is a distance in
    [0, 2]."""
    an = a / jnp.maximum(jnp.linalg.norm(a, axis=-1, keepdims=True), 1e-30)
    bn = b / jnp.maximum(jnp.linalg.norm(b, axis=-1, keepdims=True), 1e-30)
    return 1.0 - jnp.matmul(an, bn.T, precision=jax.lax.Precision.HIGHEST)


_REGISTRY: Dict[str, Metric] = {
    "euclidean": Metric(_euclidean_sq, lambda eps: eps * eps),
    "haversine": Metric(_haversine, lambda eps: eps),
    "cosine": Metric(_cosine, lambda eps: eps),
}


def get_metric(name: str) -> Metric:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown metric {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def register_metric(name: str, pairwise: Callable, threshold: Callable) -> None:
    """Extension point for user metrics (e.g. sparse kernels)."""
    _REGISTRY[name] = Metric(pairwise, threshold)
