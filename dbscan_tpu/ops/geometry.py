"""Geometry primitives: axis-aligned rectangles + 2eps grid snapping.

TPU-native reformulation of the reference's geometry layer
(DBSCANRectangle.scala:23-54, DBSCANPoint.scala:21-31, and the grid-snapping
helpers DBSCAN.scala:345-356): rectangles are ``[..., 4]`` float arrays
``(x, y, x2, y2)`` (bottom-left, top-right) and every predicate is vectorized
over arbitrary batches of rectangles and ``[..., 2]`` point arrays, so the same
code runs on host numpy and under ``jit`` on device. No scalar objects, no
Python loops.

Semantics preserved exactly:
- ``contains_point`` is INCLUSIVE on all edges (DBSCANRectangle.scala:35-37);
- ``almost_contains`` is STRICT interior (:50-52);
- ``contains_rect`` is inclusive (:28-30);
- ``shrink(amount)`` moves every edge inward by ``amount`` (negative grows,
  :42-44);
- grid snapping maps a coordinate to the lower-left corner of its 2eps cell
  with the reference's negative-shift quirk (``shiftIfNegative`` DBSCAN.scala
  :352-356: negative coordinates are shifted down one full cell BEFORE the
  integer truncation, which both fixes truncation-toward-zero AND displaces
  cells of exact-multiple negative coordinates — we reproduce it bit-for-bit
  since partition layout depends on it).

The inclusive/strict split is load-bearing for the distributed merge: a point
with ``main.contains && !inner.almost_contains`` is a merge candidate
(DBSCAN.scala:167), and ``inner.almost_contains`` decides inner-point
membership (:304-315).
"""

from __future__ import annotations

import numpy as np

# Rectangle component indices.
X, Y, X2, Y2 = 0, 1, 2, 3


def rect(x, y, x2, y2, dtype=np.float64):
    """Build a [4] rectangle array (host-side convenience)."""
    return np.array([x, y, x2, y2], dtype=dtype)


def contains_rect(outer, inner):
    """Inclusive rect-in-rect containment (DBSCANRectangle.scala:28-30).

    outer: [..., 4], inner: [..., 4] (broadcastable). Returns bool [...].
    """
    return (
        (outer[..., X] <= inner[..., X])
        & (inner[..., X2] <= outer[..., X2])
        & (outer[..., Y] <= inner[..., Y])
        & (inner[..., Y2] <= outer[..., Y2])
    )


def contains_point(r, pts):
    """Inclusive point containment (DBSCANRectangle.scala:35-37).

    r: [..., 4], pts: [..., 2] (broadcastable leading dims). Returns bool.
    """
    px, py = pts[..., 0], pts[..., 1]
    return (
        (r[..., X] <= px)
        & (px <= r[..., X2])
        & (r[..., Y] <= py)
        & (py <= r[..., Y2])
    )


def almost_contains(r, pts):
    """Strict-interior containment (DBSCANRectangle.scala:50-52)."""
    px, py = pts[..., 0], pts[..., 1]
    return (
        (r[..., X] < px)
        & (px < r[..., X2])
        & (r[..., Y] < py)
        & (py < r[..., Y2])
    )


def shrink(r, amount):
    """Shrink every edge inward by `amount`; negative grows
    (DBSCANRectangle.scala:42-44). Works on [..., 4] stacks."""
    offs = np.asarray([amount, amount, -amount, -amount], dtype=np.float64)
    return np.asarray(r, dtype=np.float64) + offs


def snap_corner(coords, cell_size):
    """Snap coordinates to their cell's lower-left corner on a `cell_size` grid.

    Bit-for-bit port of corner/shiftIfNegative (DBSCAN.scala:352-356):
    ``corner(p) = intValue(shift(p) / cell) * cell`` where ``shift(p)`` is
    ``p - cell`` for p < 0 else p, and intValue truncates toward zero. Note
    the quirk: a negative exact multiple (p = -k*cell) lands in the cell BELOW
    itself; we reproduce that because the reference's partition layout (and
    its fixtures) depend on it.
    """
    coords = np.asarray(coords, dtype=np.float64)
    shifted = np.where(coords < 0, coords - cell_size, coords)
    return np.trunc(shifted / cell_size) * cell_size


def cell_index(points, cell_size):
    """Map [N, 2] points to integer grid-cell indices [N, 2] (int64).

    Same cell assignment as corner/shiftIfNegative (DBSCAN.scala:352-356) but
    returning the integer index instead of the float corner: all downstream
    partitioning runs in exact integer arithmetic so no cell can be lost to
    floating-point drift between accumulated cut positions and trunc-derived
    corners (a real hazard in the reference's all-double formulation — see
    tests/test_partitioner.py::test_no_points_lost_to_fp_drift).
    The float corner is recovered exactly as ``index * cell_size``.
    """
    points = np.asarray(points, dtype=np.float64)[..., :2]
    shifted = np.where(points < 0, points - cell_size, points)
    return np.trunc(shifted / cell_size).astype(np.int64)


def group_by_int_key(key, max_key=None):
    """Group integer keys: (uniq [U] int64 ascending, inverse [N], counts
    [U] int64) via ONE stable argsort — numpy's stable sort radix-sorts
    integers, measured several times faster than np.unique(+inverse) at
    10M+ elements. ``max_key`` (an exclusive upper bound, keys assumed
    nonnegative) enables the int32 fast path. ``inverse`` is an index
    array, int32 whenever the element count fits (both the native radix
    path and the numpy fallback agree), int64 above 2^31 elements."""
    key = np.asarray(key)
    if key.size == 0:
        empty = np.empty(0, np.int64)
        return empty, empty.copy(), empty.copy()
    if max_key is not None and max_key < np.iinfo(np.int32).max:
        key = key.astype(np.int32)
    from dbscan_tpu import _native

    # the native radix path sorts unsigned: nonnegative keys only (the
    # one-pass min costs ~ms and keeps the ascending-uniq contract when a
    # caller ever passes raw negative cell indices; skip it entirely when
    # the library isn't loadable)
    if _native.lib() is not None and key.min() >= 0:
        native = _native.group_by_ints(key)
        if native is not None:
            uniq, inverse, counts, _ = native
            return uniq.astype(np.int64), inverse, counts
    order = np.argsort(key, kind="stable")
    ks = key[order]
    newu = np.r_[True, ks[1:] != ks[:-1]]
    firsts = np.flatnonzero(newu)
    uniq = ks[firsts].astype(np.int64)
    inv_dtype = np.int32 if len(ks) < np.iinfo(np.int32).max else np.int64
    inverse = np.empty(len(ks), dtype=inv_dtype)
    inverse[order] = np.cumsum(newu) - 1
    counts = np.diff(np.r_[firsts, len(ks)])
    return uniq, inverse, counts


def cell_histogram_int(points, cell_size):
    """Unique integer cells + counts (the aggregateByKey pass,
    DBSCAN.scala:91-97, in exact arithmetic).

    Returns (cells [C, 2] int64 lower-left indices, counts [C] int64,
    inverse [N] integer index array mapping points to cell rows — int32
    whenever N fits, int64 above 2^31 points).
    """
    from dbscan_tpu import _native

    pts2 = np.asarray(points, dtype=np.float64)[..., :2]
    if pts2.shape[0] == 0:
        return (
            np.empty((0, 2), np.int64),
            np.empty(0, np.int64),
            np.empty(0, np.int64),
        )
    nk = _native.cell_keys(pts2, cell_size)
    if nk is not None:
        # fused native pass: snap + bounds + composite key in one sweep
        key, mnx, mny, _span_x, span_y = nk
        res = _native.group_by_ints(key)
        if res is not None:
            uk, inverse, counts, _ = res
            uk = uk.astype(np.int64)
            uniq = np.stack(
                [uk // span_y + mnx, uk % span_y + mny], axis=1
            )
            return uniq, counts, inverse
    idx = cell_index(points, cell_size)
    # Composite 1-D key: np.unique(axis=0) goes through a void-view sort
    # that is ~20x slower than a flat int64 sort at millions of points.
    mn = idx.min(axis=0)
    span_y = int(idx[:, 1].max()) - int(mn[1]) + 1
    span_x = int(idx[:, 0].max()) - int(mn[0]) + 1
    if span_x * span_y < 2**62:
        key = (idx[:, 0] - mn[0]) * span_y + (idx[:, 1] - mn[1])
        uk, inverse, counts = group_by_int_key(key, max_key=span_x * span_y)
        uniq = np.stack([uk // span_y + mn[0], uk % span_y + mn[1]], axis=1)
    else:  # astronomically sparse grid: fall back to the exact 2-D unique
        uniq, inverse, counts = np.unique(
            idx, axis=0, return_inverse=True, return_counts=True
        )
    return uniq, counts.astype(np.int64), inverse.astype(np.int64)


def int_rects_to_float(rects_int, cell_size):
    """Convert [..., 4] integer cell-unit rectangles to float rects.

    Each corner is an exact product index * cell_size, matching what
    snap_corner produces for the same grid — so float containment tests
    against point coordinates are consistent everywhere.
    """
    return np.asarray(rects_int, dtype=np.float64) * cell_size


def points_to_cells(points, cell_size):
    """Map [N, 2] points to their minimum bounding grid cells as [N, 4] rects.

    Port of toMinimumBoundingRectangle (DBSCAN.scala:345-350): each point's
    cell is the 2eps x 2eps rectangle whose lower-left corner is the snapped
    coordinate.
    """
    points = np.asarray(points, dtype=np.float64)[..., :2]
    corners = snap_corner(points, cell_size)  # [N, 2]
    return np.concatenate([corners, corners + cell_size], axis=-1)


def cell_histogram(points, cell_size):
    """Unique cells + counts: the reference's aggregateByKey-then-collect pass
    (DBSCAN.scala:91-97), done as one vectorized host pass.

    Thin float view over cell_histogram_int (single source of truth for the
    grouping); corners are the exact index * cell_size products the
    partitioner emits. Returns (cells [C, 4] float64, counts [C] int64,
    cell_index [N] int64 mapping each point to its row in `cells`).
    """
    idx, counts, inverse = cell_histogram_int(points, cell_size)
    cells = (
        # host grid corners are f64 by design (reference merge
        # precision) and never ship to a kernel. The literal-only
        # dtype-drift rule needed a suppression here; the flow-based
        # dtype-flow-drift successor tracks np-vs-jnp provenance and
        # proves this astype host-side on its own.
        np.concatenate([idx, idx + 1], axis=-1)
        .astype(np.float64)
        * cell_size
    )
    return cells, counts, inverse


def bounding_rect_of_cells(cells):
    """Fold min/max over cell rects (EvenSplitPartitioner.scala:183-209)."""
    cells = np.asarray(cells)
    return np.array(
        [
            cells[:, X].min(),
            cells[:, Y].min(),
            cells[:, X2].max(),
            cells[:, Y2].max(),
        ],
        dtype=cells.dtype,
    )


def pairwise_sq_dists(a, b):
    """Squared Euclidean distances [N, M] between [N, 2] and [M, 2] (host).

    Device-side distances live in dbscan_tpu.ops.distance; this numpy helper
    backs the host oracles and predict(). Matches DBSCANPoint.distanceSquared
    (DBSCANPoint.scala:26-30): only the first two coordinates participate.
    """
    a = np.asarray(a, dtype=np.float64)[:, :2]
    b = np.asarray(b, dtype=np.float64)[:, :2]
    diff = a[:, None, :] - b[None, :, :]
    return np.einsum("nmd,nmd->nm", diff, diff)
