"""Point flag / cluster-label constants.

Mirrors the reference's labeled-point data model (DBSCANLabeledPoint.scala:24-47)
but as plain integer codes suitable for device arrays instead of a mutable JVM
object: the reference's ``Flag`` enumeration {Border, Core, Noise, NotFlagged}
(:28-31) and the ``Unknown = 0`` cluster sentinel (:26).

Cluster-label conventions used throughout this package:

- "seed labels" (device-internal): a cluster is identified by the minimum row
  index of its core points within one partition buffer; ``SEED_NONE`` marks
  noise / padding. Seed labels are canonical and order-free.
- "local ids" (reference-compatible): 1-based dense ranks of the sorted seed
  values, exactly reproducing the sequential numbering the reference's fold
  produces (LocalDBSCANNaive.scala:45-64 assigns cluster k to the k-th seed in
  input order). 0 == UNKNOWN == noise, as in the reference.
"""

import numpy as np

# Flags (int8 device codes).
NOT_FLAGGED = np.int8(0)  # reference Flag.NotFlagged
CORE = np.int8(1)  # reference Flag.Core
BORDER = np.int8(2)  # reference Flag.Border
NOISE = np.int8(3)  # reference Flag.Noise

# Cluster sentinel (reference DBSCANLabeledPoint.scala:26).
UNKNOWN = 0

# Device-internal sentinel for "no seed" (noise / invalid); any value larger
# than every row index works because labels only ever shrink via min().
SEED_NONE = np.int32(2**31 - 1)

FLAG_NAMES = {
    int(NOT_FLAGGED): "NotFlagged",
    int(CORE): "Core",
    int(BORDER): "Border",
    int(NOISE): "Noise",
}


def seed_to_local_ids(seed_labels: np.ndarray) -> np.ndarray:
    """Convert seed labels to the reference's 1-based sequential numbering.

    The reference assigns cluster ids 1,2,3,... in fold order of the first
    core point ("seed") of each cluster (LocalDBSCANNaive.scala:45-64). Sorted
    seed row-indices ARE fold order, so dense-ranking them reproduces the
    reference numbering exactly. Noise (SEED_NONE) maps to UNKNOWN (0).
    """
    seed_labels = np.asarray(seed_labels)
    out = np.zeros(seed_labels.shape, dtype=np.int32)
    mask = seed_labels != SEED_NONE
    if mask.any():
        uniq, inv = np.unique(seed_labels[mask], return_inverse=True)
        out[mask] = (inv + 1).astype(np.int32)
    return out
