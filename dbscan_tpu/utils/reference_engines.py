"""Sequential numpy oracles reproducing the reference's two local engines.

These are test oracles, NOT production code paths: straight-line Python/numpy
implementations of the documented semantics of LocalDBSCANNaive.scala:37-118
and LocalDBSCANArchery.scala:36-112, used to check the vectorized TPU kernel
bit-for-bit on arbitrary inputs. Iteration order is input order (the reference
Naive folds input order; Archery iterates R-tree entry order — border cluster
CHOICE is order-dependent in DBSCAN, so our oracles fix input order and the
kernel matches that).

Semantics captured:
- neighborhoods are inclusive of the query point and use d^2 <= eps^2
  (LocalDBSCANNaive.scala:72-78);
- a cluster is seeded by the first (fold-order) unvisited core point; cluster
  ids count up from 1 (fit fold, :45-64);
- NAIVE: a point already visited as noise is NEVER adopted as Border — the
  re-labeling code at :108-111 sits inside the !visited branch, after cluster
  was already assigned at :97, so it is dead;
- ARCHERY: the adoption check sits OUTSIDE the !visited branch
  (LocalDBSCANArchery.scala:103-106), so visited noise IS adopted as Border
  by the first expansion that reaches it.
"""

from __future__ import annotations

from collections import deque
from typing import Tuple

import numpy as np

from dbscan_tpu.ops import geometry as geo
from dbscan_tpu.ops.labels import BORDER, CORE, NOISE, NOT_FLAGGED


def _fit(
    points: np.ndarray,
    eps: float,
    min_points: int,
    adopt_visited_noise: bool,
    metric: str = "euclidean",
):
    pts = np.asarray(points, dtype=np.float64)
    n = len(pts)
    if metric == "euclidean":
        pts2 = pts[:, :2]
        d2 = geo.pairwise_sq_dists(pts2, pts2)
        thr = float(eps) * float(eps)
    else:
        # float64 measure straight from the metric registry (the jnp
        # formulas run fine on host numpy under x64 — test-only path)
        from dbscan_tpu.ops.distance import get_metric

        m = get_metric(metric)
        d2 = np.asarray(m.pairwise(pts, pts), dtype=np.float64)
        thr = float(m.threshold(eps))
    nbr_lists = [np.flatnonzero(d2[i] <= thr) for i in range(n)]

    visited = np.zeros(n, dtype=bool)
    flags = np.full(n, NOT_FLAGGED, dtype=np.int8)
    cluster = np.zeros(n, dtype=np.int32)  # 0 == Unknown == noise

    c = 0
    for i in range(n):
        if visited[i]:
            continue
        visited[i] = True
        nbrs = nbr_lists[i]
        if len(nbrs) < min_points:
            flags[i] = NOISE
            continue
        c += 1
        flags[i] = CORE
        cluster[i] = c
        queue = deque([nbrs])
        while queue:
            for j in queue.popleft():
                if not visited[j]:
                    visited[j] = True
                    cluster[j] = c
                    nn = nbr_lists[j]
                    if len(nn) >= min_points:
                        flags[j] = CORE
                        queue.append(nn)
                    else:
                        flags[j] = BORDER
                elif adopt_visited_noise and cluster[j] == 0:
                    cluster[j] = c
                    flags[j] = BORDER
    return cluster, flags


def naive_fit(
    points, eps, min_points, metric="euclidean"
) -> Tuple[np.ndarray, np.ndarray]:
    """Oracle for the Naive engine (no adoption of visited noise)."""
    return _fit(
        points, eps, min_points, adopt_visited_noise=False, metric=metric
    )


def archery_fit(
    points, eps, min_points, metric="euclidean"
) -> Tuple[np.ndarray, np.ndarray]:
    """Oracle for the Archery/textbook engine (visited noise adopted as
    Border), with exact d^2 <= eps^2 range queries (we do not reproduce the
    reference's Float-truncated R-tree bounding boxes,
    LocalDBSCANArchery.scala:118-124, which can drop boundary-exact
    neighbors by rounding)."""
    return _fit(
        points, eps, min_points, adopt_visited_noise=True, metric=metric
    )
