"""Adjusted Rand Index, implemented from scratch (sklearn is not available
in this environment). Used by tests and the benchmark harness to compare
clusterings permutation-invariantly — the reference's own end-to-end test
already needs a hand-built label correspondence map (DBSCANSuite.scala:28);
ARI is the principled version of that."""

from __future__ import annotations

import numpy as np


def contingency(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a = np.asarray(a)
    b = np.asarray(b)
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    n_a = ai.max() + 1 if ai.size else 0
    n_b = bi.max() + 1 if bi.size else 0
    table = np.zeros((n_a, n_b), dtype=np.int64)
    np.add.at(table, (ai, bi), 1)
    return table


def adjusted_rand_index(a: np.ndarray, b: np.ndarray) -> float:
    """ARI in [-1, 1]; 1.0 iff the two labelings are identical up to
    permutation. Noise is treated as an ordinary label (as scikit-learn's
    adjusted_rand_score does)."""
    a = np.asarray(a).ravel()
    b = np.asarray(b).ravel()
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch: {a.shape} vs {b.shape}")
    n = a.size
    if n < 2:
        return 1.0
    table = contingency(a, b)

    def comb2(x):
        x = np.asarray(x, dtype=np.float64)
        return x * (x - 1.0) / 2.0

    sum_ij = comb2(table).sum()
    sum_a = comb2(table.sum(axis=1)).sum()
    sum_b = comb2(table.sum(axis=0)).sum()
    total = comb2(n)
    expected = sum_a * sum_b / total if total else 0.0
    max_index = 0.5 * (sum_a + sum_b)
    denom = max_index - expected
    if denom == 0.0:
        return 1.0
    return float((sum_ij - expected) / denom)


def exact_match_up_to_permutation(a: np.ndarray, b: np.ndarray, noise_a=0, noise_b=0) -> bool:
    """True iff labelings agree exactly after the optimal label bijection,
    with noise required to map to noise."""
    a = np.asarray(a).ravel()
    b = np.asarray(b).ravel()
    if a.shape != b.shape:
        return False
    if not np.array_equal(a == noise_a, b == noise_b):
        return False
    mapping = {}
    used = set()
    for la, lb in zip(a, b):
        if la == noise_a:
            continue
        if la in mapping:
            if mapping[la] != lb:
                return False
        else:
            if lb in used:
                return False
            mapping[la] = lb
            used.add(lb)
    return True
