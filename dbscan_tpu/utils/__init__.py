"""Host utilities: IO, metrics, logging, reference oracles."""
