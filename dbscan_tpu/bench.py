"""Knob autotuner: ``python -m dbscan_tpu.bench --tune``.

The repo carries a registry of typed execution knobs (``config.ENV_VARS``)
and an append-only perf history (``bench/history.jsonl``) — but until now
nothing SEARCHED the knob space: every capture ran whatever the operator
exported. This module closes that loop with a successive-halving search
over the DECLARED tunable space (``config.TUNABLES`` — slot budgets,
pull-pipeline depths, ladder caps, the propagation/fused-kernel modes),
under one hard constraint and one hard contract:

- **HBM pre-dispatch constraint**: every candidate is priced against
  graftshape's ``FAMILY_MODELS`` knob-bounded worst cases
  (lint/shapes.py) BEFORE it runs — a config predicted to breach the
  device budget is never dispatched, the same envelope the lint-time
  ``hbm-over-budget`` gate and the serve admission controller price.
- **tuned-vs-default floor**: the default config is always a tournament
  entrant, and the committed profile's ``tuned_vs_default_speedup``
  (default wall / winner wall, from the SAME tournament measurements)
  is hard-floored at 1.0 by ``obs/regress.py`` — a committed profile
  that loses to defaults is a red gate.

The winner lands in ``bench/profiles/<backend>_<workload>.json`` (a
``config.Profile``: tuned DEFAULTS — explicit env exports still win),
which ``cli.py --profile`` and the root ``bench.py`` (``BENCH_PROFILE``)
load, and the tune capture is gate-then-appended to the bench history
like every other capture.

Search discipline (successive halving): round r gives every surviving
candidate ``reps * 2**r`` timed runs (after one warm-up run per
candidate — the jit cache is part of what the knobs move, so each
candidate pays its own compiles outside the timed window) and keeps the
best half by minimum wall, until one survives or the wall budget runs
out. Deterministic: candidates are sampled with a seeded RNG from the
declared choices, so a re-run reproduces the same tournament.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from dbscan_tpu import config
from dbscan_tpu.lint import shapes as shapes_mod


def hbm_ok(
    values: Dict[str, object],
    budget: Optional[int] = None,
) -> Tuple[bool, List[str]]:
    """Price a candidate knob assignment against every FAMILY_MODELS
    knob-bounded worst case; returns ``(fits, breaches)``. This is the
    tuner's HARD pre-dispatch constraint — a config predicted to breach
    is never run (the same static envelope the lint gate evaluates
    against the live env)."""
    budget = (
        budget if budget is not None else shapes_mod.DEFAULT_HBM_BYTES
    )

    def env_fn(name: str):
        if name in values:
            return values[name]
        return config.env(name)

    breaches = []
    for family in sorted(shapes_mod.FAMILY_MODELS):
        worst = shapes_mod.FAMILY_MODELS[family].static_worst(env_fn)
        if worst is not None and worst > budget:
            breaches.append(
                f"{family}: {worst / 2**30:.2f} GiB > "
                f"{budget / 2**30:.0f} GiB"
            )
    return (not breaches), breaches


def sample_candidates(
    n: int, seed: int, budget: Optional[int] = None
) -> List[Dict[str, object]]:
    """Deterministic candidate assignments over config.TUNABLES: the
    DEFAULT config (empty dict) is always entrant 0 — it is the
    speedup denominator and represents what already runs today, so it
    is not re-filtered — then up to ``n-1`` distinct random
    combinations that pass the HBM constraint. A rejected
    (predicted-to-breach) sample is resampled, never run."""
    import random

    rng = random.Random(seed)
    out: List[Dict[str, object]] = [{}]
    seen = {()}
    attempts = 0
    while len(out) < n and attempts < 50 * n:
        attempts += 1
        cand: Dict[str, object] = {}
        for t in config.TUNABLES:
            # half the knobs stay at their default per candidate: the
            # search should move a few dials at a time, not teleport
            if rng.random() < 0.5:
                value = rng.choice(t.choices)
                if value == config.env(t.name):
                    # sampling a knob's CURRENT effective value is
                    # entrant 0 wearing a costume — dropping it keeps
                    # the dedup semantic, so the budget buys coverage
                    continue
                cand[t.name] = value
        key = tuple(sorted(cand.items()))
        if key in seen:
            continue
        seen.add(key)
        fits, _breaches = hbm_ok(cand, budget)
        if not fits:
            continue
        out.append(cand)
    return out


# --- workloads ---------------------------------------------------------


def _headline_workload(n: int):
    """The tuner's stand-in for the bench headline shape: clustered
    blobs + noise over a wide area (spatial partitioning engages, the
    banded engine routes), seed-deterministic."""
    rng = np.random.default_rng(42)
    n_clusters = max(4, n // 5000)
    centers = rng.uniform(-60, 60, size=(n_clusters, 2))
    per = (n * 9 // 10) // n_clusters
    pts = np.concatenate(
        [rng.normal(c, 0.8, size=(per, 2)) for c in centers]
        + [rng.uniform(-70, 70, size=(n - per * n_clusters, 2))]
    )
    rng.shuffle(pts)
    kw = dict(
        eps=0.35,
        min_points=10,
        max_points_per_partition=4096,
        neighbor_backend="banded",
    )
    return pts, kw


WORKLOADS = {"headline": _headline_workload}


# --- evaluation --------------------------------------------------------


def _apply_env(values: Dict[str, object]) -> Dict[str, Optional[str]]:
    """Export a candidate assignment; returns the previous raw values
    for exact restore (the tuner owns its process env while it runs)."""
    prev: Dict[str, Optional[str]] = {}
    for name, value in values.items():
        prev[name] = os.environ.get(name)
        os.environ[name] = str(value)
    return prev


def _restore_env(prev: Dict[str, Optional[str]]) -> None:
    for name, raw in prev.items():
        if raw is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = raw


def _evaluate(values: Dict[str, object], pts, kw, reps: int) -> float:
    """Best-of-``reps`` timed train wall under the candidate env (one
    untimed warm-up first: the knobs move jit signatures, and every
    candidate must pay its own compiles outside the timed window)."""
    from dbscan_tpu import train

    prev = _apply_env(values)
    try:
        train(pts, **kw)
        best = float("inf")
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            train(pts, **kw)
            best = min(best, time.perf_counter() - t0)
        return best
    finally:
        _restore_env(prev)


def tune(
    workload: str = "headline",
    n: int = 20000,
    candidates: int = 8,
    reps: int = 1,
    rounds: int = 2,
    budget_s: float = 600.0,
    seed: int = 0,
    hbm_budget: Optional[int] = None,
) -> dict:
    """Run the successive-halving tournament; returns the result dict
    (winner values, walls, speedup, per-round trace). Pure search — the
    CLI owns profile/history writes."""
    import jax

    pts, kw = WORKLOADS[workload](n)
    cands = sample_candidates(candidates, seed, hbm_budget)
    walls: Dict[int, float] = {}
    t_start = time.monotonic()
    trace: List[dict] = []
    alive = list(range(len(cands)))
    r = 0
    while len(alive) > 1 and r < rounds:
        round_reps = max(1, reps) * (1 << r)
        # walls are only comparable WITHIN a round (best-of-more-reps is
        # stochastically smaller): each round re-measures every survivor
        # fresh, and a budget expiry mid-round discards the partial
        # round instead of ranking best-of-1 against best-of-2N walls —
        # unless no round ever completed, where the partial prefix (all
        # at the SAME rep count) is the only measurement there is
        round_walls: Dict[int, float] = {}
        complete = True
        for i in alive:
            if time.monotonic() - t_start > budget_s:
                complete = False
                break
            round_walls[i] = _evaluate(cands[i], pts, kw, round_reps)
        if not complete:
            if not walls:
                walls = round_walls
            break
        walls = round_walls
        measured = sorted(walls, key=lambda i: walls[i])
        keep = max(1, len(measured) // 2)
        # the default (candidate 0) is never eliminated: the speedup
        # denominator must come from the same tournament measurements
        alive = sorted(set(measured[:keep]) | {0})
        trace.append(
            {
                "round": r,
                "reps": round_reps,
                "alive": list(alive),
                "walls": {str(i): round(walls[i], 4) for i in measured},
            }
        )
        r += 1
    if 0 not in walls:
        # a one-candidate field (or rounds=0) never enters the loop:
        # measure the default once — it is both the winner and the
        # denominator, and "measure what runs today" is a valid ask
        walls[0] = _evaluate(cands[0], pts, kw, max(1, reps))
    ranked = sorted((i for i in alive if i in walls), key=lambda i: walls[i])
    winner = ranked[0] if ranked else 0
    default_wall = walls.get(0)
    winner_wall = walls.get(winner)
    if default_wall is None or winner_wall is None:
        raise RuntimeError(
            "tune: the budget expired before the default config was "
            "measured — raise --budget-s or shrink --n"
        )
    return {
        "workload": workload,
        "backend": jax.default_backend(),
        "n": int(n),
        "winner": dict(cands[winner]),
        "default_wall_s": round(default_wall, 4),
        "tuned_wall_s": round(winner_wall, 4),
        # >= 1.0 by construction: the default is a tournament entrant
        # and the winner beat (or is) it under the SAME measurement
        "tuned_vs_default_speedup": round(
            default_wall / max(winner_wall, 1e-9), 4
        ),
        "candidates": len(cands),
        "rounds": trace,
        "wall_s": round(time.monotonic() - t_start, 2),
    }


# --- CLI ---------------------------------------------------------------


def profile_path(out_dir: str, backend: str, workload: str) -> str:
    return os.path.join(out_dir, f"{backend}_{workload}.json")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.bench",
        description="Knob autotuner: successive-halving search over "
        "the declared tunable space (config.TUNABLES) under the "
        "graftshape HBM constraint; commits the per-(backend, "
        "workload) winner to bench/profiles/ and gates "
        "tuned_vs_default_speedup in the bench history.",
    )
    p.add_argument(
        "--tune", action="store_true",
        help="run the tuning tournament (the only mode today)",
    )
    p.add_argument(
        "--workload", default="headline", choices=sorted(WORKLOADS),
        help="workload generator to tune against (default headline)",
    )
    p.add_argument(
        "--n", type=int, default=20000,
        help="workload points (default 20000 — small on purpose: the "
        "knobs being tuned shape per-dispatch behavior, not data "
        "volume; raise it for production captures)",
    )
    p.add_argument(
        "--candidates", type=int, default=8,
        help="tournament entrants incl. the default config (default 8)",
    )
    p.add_argument(
        "--reps", type=int, default=1,
        help="round-0 timed reps per candidate (doubles per round)",
    )
    p.add_argument(
        "--rounds", type=int, default=2,
        help="successive-halving rounds (default 2)",
    )
    p.add_argument(
        "--budget-s", type=float, default=600.0,
        help="wall budget for the whole tournament (default 600)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--out-dir", default=os.path.join("bench", "profiles"),
        help="profile directory (default bench/profiles)",
    )
    p.add_argument(
        "--history", default=os.path.join("bench", "history.jsonl"),
        help="bench history to gate-then-append the tune capture to "
        "(default bench/history.jsonl; --no-history skips)",
    )
    p.add_argument(
        "--no-history", action="store_true",
        help="skip the history gate/append (smoke runs)",
    )
    args = p.parse_args(argv)
    if not args.tune:
        p.error("--tune is required (see --help)")

    result = tune(
        workload=args.workload,
        n=args.n,
        candidates=args.candidates,
        reps=args.reps,
        rounds=args.rounds,
        budget_s=args.budget_s,
        seed=args.seed,
    )

    from dbscan_tpu.obs import bench_history

    rev = bench_history.git_rev()
    prof = config.Profile(
        backend=result["backend"],
        workload=result["workload"],
        values=result["winner"],
        meta={
            "tuned_vs_default_speedup": result[
                "tuned_vs_default_speedup"
            ],
            "default_wall_s": result["default_wall_s"],
            "tuned_wall_s": result["tuned_wall_s"],
            "n": result["n"],
            "candidates": result["candidates"],
            "rev": rev,
        },
    )
    os.makedirs(args.out_dir, exist_ok=True)
    path = profile_path(args.out_dir, prof.backend, prof.workload)
    prof.save(path)
    result["profile"] = path

    if not args.no_history:
        from dbscan_tpu.obs import regress as obs_regress

        # the walls are n-dependent: key them per (workload, n) so a
        # future production tune at a larger --n trends against ITS OWN
        # population instead of red-gating on a smaller run's baseline
        # (the n-free speedup ratio is the scale-free gated figure)
        wall_key = f"tune_{result['workload']}_n{result['n']}"
        capture = {
            "metric": "tune",
            "backend": result["backend"],
            "workload": result["workload"],
            "tuned_vs_default_speedup": result[
                "tuned_vs_default_speedup"
            ],
            f"{wall_key}_default_wall_s": result["default_wall_s"],
            f"{wall_key}_tuned_wall_s": result["tuned_wall_s"],
        }
        records = bench_history.normalize_capture(
            capture, f"tune_{int(time.time())}", rev
        )
        verdict = obs_regress.compare(
            records, bench_history.load_history(args.history)
        )
        if verdict["regressions"]:
            for e in verdict["regressions"]:
                sys.stderr.write(
                    f"tune: {obs_regress.format_regression(e)}\n"
                )
            sys.stderr.write(
                "tune: capture NOT appended (regression gate failed) — "
                "the committed profile still reflects the tournament\n"
            )
            print(json.dumps(result))
            return 1
        added, _ = bench_history.append_records(records, args.history)
        sys.stderr.write(
            f"tune: {added} record(s) appended to {args.history}\n"
        )

    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
