"""Command-line driver: the reference sample app, parameterized.

The reference's only executable is a test-tree ``main`` with hardcoded
Windows paths and hyperparameters (DBSCANSample.scala:13-38: textFile ->
train(eps=0.1, minPoints=3, maxPointsPerPartition=400) -> saveAsTextFile).
This CLI exposes the same flow with real flags, structured logging instead
of the fork's driver-side println taps (DBSCAN.scala:139,202 — defects we
deliberately do not reproduce), and optional device-mesh fan-out.

Usage:
  python -m dbscan_tpu.cli --input pts.csv --output labeled.csv \
      --eps 0.3 --min-points 10 [--max-points-per-partition 250] \
      [--engine naive|archery] [--metric euclidean|haversine|cosine] \
      [--precision f32|f64|bf16] [--use-pallas] [--mesh-devices N] \
      [--embed [--embed-sample-frac F]] \
      [--stats] [--trace trace.json] [--metrics-summary] \
      [--log-level INFO]
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time
from typing import Optional, Sequence

from dbscan_tpu import io as io_mod
from dbscan_tpu.config import Engine, Precision


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dbscan_tpu",
        description="Distributed TPU-native DBSCAN (train + label a point set).",
    )
    p.add_argument(
        "--input",
        help="points file (csv/parquet/npy/npz); required unless --serve",
    )
    p.add_argument("--output", help="labeled output file (csv/parquet/npz)")
    p.add_argument("--input-format", choices=["csv", "parquet", "numpy"])
    p.add_argument("--output-format", choices=["csv", "parquet", "numpy"])
    p.add_argument("--delimiter", default=",", help="csv delimiter (default ',')")
    p.add_argument(
        "--eps", type=float, help="neighborhood radius (required unless --serve)"
    )
    p.add_argument(
        "--min-points", type=int,
        help="min self-inclusive neighborhood size for a core point "
        "(required unless --serve)",
    )
    p.add_argument(
        "--embed", action="store_true",
        help="treat the input as [N, D] embeddings and run the "
        "high-dimensional cosine engine (dbscan_tpu/embed: LSH "
        "binning + spill-tree fallback + blocked MXU neighbor "
        "kernel) instead of the spatial train() pipeline; --eps is "
        "the cosine distance threshold",
    )
    p.add_argument(
        "--embed-sample-frac", type=float, default=None,
        help="with --embed: opt into the subsampled-edge mode at this "
        "edge-keep probability (accuracy contract in PARITY.md; "
        "equivalent env: DBSCAN_EMBED_SAMPLE_FRAC)",
    )
    p.add_argument(
        "--serve", action="store_true",
        help="run the resident ClusterService against a synthetic "
        "stream (concurrent ingest + queries + the tenancy batch leg) "
        "and print health/QPS — the python -m dbscan_tpu.serve demo",
    )
    p.add_argument(
        "--serve-updates", type=int, default=4,
        help="with --serve: synthetic micro-batches to ingest",
    )
    p.add_argument(
        "--serve-batch", type=int, default=1000,
        help="with --serve: points per synthetic micro-batch",
    )
    p.add_argument(
        "--max-points-per-partition", type=int, default=None,
        help="best-effort per-partition point bound (default 250 for "
        "train, as the reference's DBSCAN.train default position; the "
        "--serve demo keeps its own default unless this is set "
        "explicitly)",
    )
    p.add_argument(
        "--engine", choices=[e.value for e in Engine], default=Engine.NAIVE.value,
        help="border-adoption semantics: naive = distributed-driver parity, "
        "archery = textbook DBSCAN (default naive)",
    )
    p.add_argument(
        "--metric", default=None,
        help="distance metric: euclidean/haversine/cosine (default "
        "euclidean; --embed is cosine-only and rejects a conflicting "
        "explicit metric)",
    )
    p.add_argument(
        "--precision", choices=[e.value for e in Precision],
        default=Precision.F32.value,
    )
    p.add_argument(
        "--use-pallas", action="store_true",
        help="route the local kernel through the streaming Pallas sweeps",
    )
    p.add_argument(
        "--neighbor-backend", choices=["auto", "dense", "banded"],
        default="auto",
        help="per-partition neighbor engine: auto routes by width, "
        "banded forces the grid-banded sweeps (+ the cellcc finalize) "
        "at any size, dense forces the [B, B] adjacency engine",
    )
    p.add_argument(
        "--mesh-devices", type=int, default=0,
        help="fan partitions out over this many devices (0 = single device)",
    )
    p.add_argument(
        "--stats", action="store_true",
        help="print run statistics as JSON to stdout",
    )
    p.add_argument(
        "--checkpoint-dir",
        help="persist the pre-merge state here; a re-run with the same "
        "data and parameters resumes at the merge phase",
    )
    p.add_argument(
        "--fault-retries", type=int, default=3,
        help="bounded retries per supervised device dispatch before the "
        "degradation decision (default 3; DBSCAN_FAULT_RETRIES overrides)",
    )
    p.add_argument(
        "--no-fault-cpu-fallback", action="store_true",
        help="abort on a retries-exhausted device fault instead of "
        "degrading the failing group to the CPU engine (the abort still "
        "flushes the current compact chunk first)",
    )
    p.add_argument(
        "--platform", choices=["cpu", "tpu", "gpu"],
        help="pin the JAX platform (wins over JAX_PLATFORMS, which "
        "site-level plugin registration can override)",
    )
    p.add_argument(
        "--profile", metavar="PATH",
        help="load a tuned knob profile (bench/profiles/*.json, "
        "written by python -m dbscan_tpu.bench --tune) and apply it "
        "as tuned DEFAULTS — explicitly exported DBSCAN_* variables "
        "still win (config.Profile)",
    )
    p.add_argument(
        "--trace", metavar="PATH",
        help="write a span trace of the run to PATH: Chrome-trace JSON "
        "(chrome://tracing / Perfetto) by default, JSONL records when "
        "PATH ends in .jsonl (equivalent env: DBSCAN_TRACE=PATH)",
    )
    p.add_argument(
        "--metrics-summary", action="store_true",
        help="print the top spans and counters after the run (enables "
        "the in-memory observability registry even without --trace)",
    )
    p.add_argument("--log-level", default="WARNING")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.profile:
        # applied FIRST so every leg (train, --embed, --serve) reads
        # the tuned defaults through config.env
        from dbscan_tpu.config import Profile

        try:
            Profile.load(args.profile).apply()
        except (OSError, ValueError, KeyError) as e:
            parser.error(f"--profile {args.profile}: {e}")
    if args.serve:
        from dbscan_tpu.serve.__main__ import main as serve_main

        serve_argv = [
            "--updates", str(args.serve_updates),
            "--batch", str(args.serve_batch),
        ]
        if args.eps is not None:
            serve_argv += ["--eps", str(args.eps)]
        if args.min_points is not None:
            serve_argv += ["--min-points", str(args.min_points)]
        if args.max_points_per_partition is not None:
            serve_argv += [
                "--max-points-per-partition",
                str(args.max_points_per_partition),
            ]
        if args.checkpoint_dir:
            serve_argv += ["--checkpoint-dir", args.checkpoint_dir]
        if args.stats:
            serve_argv += ["--json"]
        return serve_main(serve_argv)
    if args.input is None or args.eps is None or args.min_points is None:
        parser.error("--input, --eps, and --min-points are required "
                     "(unless --serve)")
    if args.embed:
        # an accepted flag that silently does nothing is a bug, not a
        # mode: the embed engine is cosine-only and has no pallas/mesh
        # fan-out — reject explicit conflicting flags instead of
        # discarding them
        if args.metric not in (None, "cosine"):
            parser.error(
                f"--embed clusters by cosine distance; --metric "
                f"{args.metric} conflicts"
            )
        if args.use_pallas:
            parser.error("--embed does not support --use-pallas")
        if args.mesh_devices:
            parser.error("--embed does not support --mesh-devices")
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    log = logging.getLogger("dbscan_tpu.cli")

    # observability enable/disable is exception-safe: whatever the body
    # raises, the finally block flushes the trace captured SO FAR (a
    # partial trace of a crashed run is exactly when you want one) and
    # disables — but only a state WE created, so an in-process caller
    # (test harness, notebook) that enabled obs first keeps its live
    # registry (the no-clobber contract in obs/__init__.py).
    obs_on = bool(args.trace or args.metrics_summary)
    was_active = False
    if obs_on:
        from dbscan_tpu import obs

        # if a harness already enabled obs, this call only adopts the
        # --trace path — and the finally block must then leave the
        # harness's registries alive (we disable only what WE enabled)
        was_active = obs.active()
        obs.enable(trace_path=args.trace)
    try:
        return _run(args, log)
    finally:
        if obs_on:
            from dbscan_tpu import obs

            try:
                written = obs.flush()
                if written:
                    log.info("trace written to %s", written)
            finally:
                if not was_active:
                    obs.disable()


def _run(args, log) -> int:
    points = io_mod.load_points(args.input, args.input_format, args.delimiter)
    log.info("loaded %d points (%d columns) from %s", len(points), points.shape[1], args.input)

    if args.embed:
        return _run_embed(args, log, points)

    mesh = None
    if args.mesh_devices > 0:
        import jax

        from dbscan_tpu.parallel.mesh import make_mesh

        devices = jax.devices()
        if len(devices) < args.mesh_devices:
            log.error(
                "requested %d devices, have %d", args.mesh_devices, len(devices)
            )
            return 2
        mesh = make_mesh(devices[: args.mesh_devices])

    from dbscan_tpu import train

    t0 = time.perf_counter()
    model = train(
        points,
        eps=args.eps,
        min_points=args.min_points,
        max_points_per_partition=(
            250
            if args.max_points_per_partition is None
            else args.max_points_per_partition
        ),
        engine=Engine(args.engine),
        metric=args.metric or "euclidean",
        precision=Precision(args.precision),
        use_pallas=args.use_pallas,
        neighbor_backend=args.neighbor_backend,
        fault_max_retries=args.fault_retries,
        fault_cpu_fallback=not args.no_fault_cpu_fallback,
        mesh=mesh,
        checkpoint_dir=args.checkpoint_dir,
    )
    seconds = time.perf_counter() - t0
    log.info("clustered in %.3fs: %d clusters", seconds, model.n_clusters)

    # supervised-dispatch fault summary (dbscan_tpu/faults.py): say when
    # the run survived device faults — a degraded-but-complete run looks
    # identical from the labels alone, and an operator retrying a flaky
    # worker needs the retry/fallback counts to see it
    fa = model.stats.get("faults") or {}
    if fa.get("retries") or fa.get("fallbacks"):
        log.warning(
            "device faults survived: %d retried dispatch(es), %d "
            "group(s) degraded to CPU, %d budget halving(s), %.2fs "
            "backoff",
            fa.get("retries", 0),
            fa.get("fallbacks", 0),
            fa.get("budget_halvings", 0),
            fa.get("backoff_s", 0.0),
        )

    # observability summary (dbscan_tpu/obs): where the run's wall went
    # — the span/counter analog of the fault block above, printed as
    # text next to it (the machine-readable record stays the trace
    # file, which main()'s finally block flushes even on error)
    if args.metrics_summary:
        _print_metrics_summary()

    if args.output:
        io_mod.save_labeled(
            args.output,
            model.points,
            model.clusters,
            model.flags,
            args.output_format,
            args.delimiter,
        )
        log.info("wrote %s", args.output)

    if args.stats:
        _print_stats(len(points), int(model.n_clusters), seconds, model.stats)
    return 0


def _as_stats_json(v):
    """Plain-JSON coercion for stats values, shared by the train and
    --embed legs (the two copies had already drifted on string stats
    like the embed engine's ``embed_degraded`` marker)."""
    if isinstance(v, dict):
        return {k: _as_stats_json(x) for k, x in v.items()}
    if isinstance(v, str):
        return v
    return float(v) if isinstance(v, float) else int(v)


def _print_stats(n_points, n_clusters, seconds, stats) -> None:
    print(
        json.dumps(
            {
                "n_points": int(n_points),
                "n_clusters": int(n_clusters),
                "seconds": round(seconds, 4),
                **{k: _as_stats_json(v) for k, v in stats.items()},
            }
        )
    )


def _print_metrics_summary() -> None:
    """The --metrics-summary text block, shared by the train and
    --embed legs (an accepted flag that silently prints nothing is a
    bug, not a mode)."""
    from dbscan_tpu import obs

    summ = obs.summary(top=10)
    print("== metrics summary ==")
    print("top spans (total_s x count):")
    for name, cnt, total in summ["spans"]:
        print(f"  {name:<28} {total:>10.3f}s x {cnt}")
    print("counters:")
    for name, value in sorted(summ["counters"].items()):
        if isinstance(value, float):
            value = round(value, 6)
        print(f"  {name:<28} {value}")
    # gauges ride the summary next to the counters (HBM watermarks,
    # pull.inflight/queue_depth) — set-last-wins values, so this is
    # the run's END state; pinned by tests/test_flight.py
    gauges = summ.get("gauges") or {}
    if gauges:
        print("gauges:")
        for name, value in sorted(gauges.items()):
            print(f"  {name:<28} {value}")
    from dbscan_tpu.obs import flight

    if flight.active():
        print(f"flight recorder: on (dump -> {flight._default_path()})")


def _run_embed(args, log, points) -> int:
    """The --embed leg: the high-dimensional cosine engine over the
    loaded [N, D] rows, with the same output/stats surface as train."""
    from dbscan_tpu import embed_dbscan

    stats: dict = {}
    t0 = time.perf_counter()
    clusters, flags = embed_dbscan(
        points,
        eps=args.eps,
        min_points=args.min_points,
        engine=args.engine,
        max_points_per_partition=(
            4096
            if args.max_points_per_partition is None
            else args.max_points_per_partition
        ),
        sample_frac=args.embed_sample_frac,
        stats_out=stats,
    )
    seconds = time.perf_counter() - t0
    n_clusters = int(stats.get("n_clusters", len(set(clusters[clusters > 0].tolist()))))
    log.info("embed-clustered in %.3fs: %d clusters", seconds, n_clusters)
    if args.metrics_summary:
        _print_metrics_summary()
    if args.output:
        io_mod.save_labeled(
            args.output, points, clusters, flags,
            args.output_format, args.delimiter,
        )
        log.info("wrote %s", args.output)
    if args.stats:
        _print_stats(len(points), n_clusters, seconds, stats)
    return 0


if __name__ == "__main__":
    sys.exit(main())
