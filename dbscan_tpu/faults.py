"""Supervised device dispatch: fault classification, bounded retry with
exponential backoff + jitter, per-group CPU degradation, and a
deterministic fault-injection registry.

The reference delegates ALL fault tolerance to Spark lineage — a lost
executor silently replays the same expensive work (DBSCAN.scala:59-60).
Our checkpoint module (parallel/checkpoint.py) already beats lineage for
cross-process resume, but in-process we were strictly worse: any device
fault raised at the offending group's dispatch site and aborted the
whole run, discarding every healthy group's finished work. This module
closes that gap with the supervised-dispatch shape parallel-DBSCAN
systems assume from their runtime (Wang et al., arXiv:1912.06255):

- :func:`supervised` wraps one device dispatch. Transient device errors
  retry with exponential backoff + deterministic jitter; a
  RESOURCE_EXHAUSTED halves the dispatch's batch/chunk budget before
  retrying (a narrower lax.map batch is the one knob that shrinks the
  peak HBM transient without changing results); a persistent failure
  degrades THAT group to the caller-supplied CPU fallback — the CPU
  ``local_dbscan`` engine for kernel groups — instead of aborting.
- :func:`classify` maps raw exceptions to fault kinds. Only
  device-runtime errors are supervised; programming errors (ValueError,
  TypeError, trace-time failures) re-raise immediately — retrying those
  can never succeed and would bury the real traceback.
- :class:`FaultRegistry` injects deterministic faults from
  ``DBSCAN_FAULT_SPEC`` (see :func:`parse_fault_spec`) so the whole
  retry/degrade story stays testable in CI without real hardware
  faults.
- :class:`FaultCounters` accumulates structured accounting (attempts,
  retries, fallbacks, backoff seconds) that the driver surfaces through
  ``TrainOutput.stats["faults"]`` and the CLI summary.

Async caveat: jax dispatch is asynchronous, so a REAL device fault
normally surfaces at the consuming pull, not at the dispatch site.
When supervision needs to attribute faults per group — a fault spec is
active, or ``DBSCAN_FAULT_SYNC=1`` — :func:`supervised` blocks on the
dispatch's outputs before returning, trading pack/compute overlap for
group-granular retry. With no spec and no env override the dispatch
stays async and supervision covers the synchronously-raised class
(compile/launch/injection faults); pull-site faults then abort as
before, but the driver's abort path now flushes the current compact
chunk first so even that resumes from the last completed group.
"""

from __future__ import annotations

import dataclasses
import logging
import re
import time
import zlib
from typing import Callable, Optional, Tuple

import numpy as np

from dbscan_tpu import config, obs
from dbscan_tpu.lint import faultcheck as _faultcheck
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import flight as _obs_flight
from dbscan_tpu.obs import live as _obs_live
from dbscan_tpu.obs import memory as _obs_memory

logger = logging.getLogger(__name__)

# fault kinds (also the spec grammar's kind tokens)
TRANSIENT = "TRANSIENT"
RESOURCE_EXHAUSTED = "RESOURCE_EXHAUSTED"
PERSISTENT = "PERSISTENT"
_KINDS = (TRANSIENT, RESOURCE_EXHAUSTED, PERSISTENT)

# dispatch-site labels (the spec grammar's site tokens; "*" matches any)
SITE_DISPATCH = "dispatch"  # dense/resident kernel group fan-out
SITE_BANDED = "banded"  # banded phase-1 group fan-out
SITE_SPILL = "spill"  # spill-tree device ops (spill_device.py)
SITE_SPILL_LEVEL = "spill_level"  # level-synchronous spill-tree dispatch
SITE_STREAM = "stream"  # streaming per-batch update step
SITE_PULL = "pull"  # pipelined compact-chunk pull (parallel/pipeline.py)
SITE_CELLCC = "cellcc_cc"  # device cellcc finalize (cellgraph.finalize_device)
SITE_CAMPAIGN = "campaign"  # campaign worker lease (dbscan_tpu/campaign.py)
SITE_SERVE = "serve"  # ClusterService ingest/query steps (dbscan_tpu/serve)
SITE_SERVE_REPLICA = "serve_replica"  # router query replicas (serve/router.py)
SITE_EMBED = "embed"  # embed engine hash/neighbor dispatches (dbscan_tpu/embed)
SITE_DENSITY_CORE = "density_core"  # density core-distance chunks (density/)
SITE_DENSITY_BORUVKA = "density_boruvka"  # density Borůvka MST rounds
@dataclasses.dataclass(frozen=True)
class SiteSpec:
    """One declared fault site — the obs/schema.py registration idiom
    applied to the fault plane. The row IS the contract graftfault
    (lint/faultsurface.py) enforces: ``owner`` is the consuming module,
    ``unit`` says what one injection ordinal spans, ``degrade`` is the
    documented degradation ladder in order, and ``handler`` names the
    mode(s) through which the ladder is reached —

    - ``fallback-arg``: every supervised call passes ``fallback=``
      (possibly conditionally None — presence marks the ladder);
    - ``caller-except``: the call sits inside a degrading try/except
      (the spill tree's per-node device->host teardown);
    - ``propagate:<module>``: the FatalDeviceFault escapes to the named
      module, which catches it (or counts via ``note_degrade``).
    """

    site: str
    owner: str
    unit: str
    degrade: Tuple[str, ...]
    handler: Tuple[str, ...]
    doc: str


def _site_table(*rows: SiteSpec) -> dict:
    return {r.site: r for r in rows}


SITES = _site_table(
    SiteSpec(
        SITE_DISPATCH, "parallel.driver",
        "one dense/resident kernel group dispatch",
        ("retry", "budget-halving", "cpu-tier"), ("fallback-arg",),
        "Dense partition-group fan-out; persistent faults degrade THAT "
        "group to the CPU local_dbscan engine.",
    ),
    SiteSpec(
        SITE_BANDED, "parallel.driver",
        "one banded phase-1 group dispatch",
        ("retry", "budget-halving", "cpu-tier"), ("fallback-arg",),
        "Banded phase-1 fan-out; same per-group CPU degradation as "
        "the dense site.",
    ),
    SiteSpec(
        SITE_SPILL, "parallel.spill_device",
        "one spill-tree device op (upload/gather/pivots/screen/"
        "membership/leader-cover)",
        ("retry", "host-spill"),
        ("caller-except", "propagate:dbscan_tpu.parallel.spill"),
        "Per-node spill device ops; the tree tears the node down to "
        "the host recursion itself (note_degrade).",
    ),
    SiteSpec(
        SITE_SPILL_LEVEL, "parallel.spill_device",
        "one level-synchronous spill-tree dispatch",
        ("retry", "host-spill"),
        ("propagate:dbscan_tpu.parallel.spill",),
        "Level-synchronous build; a persistent fault degrades the "
        "WHOLE build to the host recursion.",
    ),
    SiteSpec(
        SITE_STREAM, "streaming",
        "one streaming micro-batch update",
        ("retry", "cpu-tier"), ("fallback-arg",),
        "Whole-batch supervision over train_arrays (pure function of "
        "host state — idempotent by construction).",
    ),
    SiteSpec(
        SITE_PULL, "parallel.driver",
        "one pipelined compact-chunk pull",
        ("retry", "abort-flush-resume"),
        ("propagate:dbscan_tpu.parallel.driver",),
        "Chunk pulls on the pipeline worker; exhaustion aborts through "
        "the driver's chunk-flush path and resumes from checkpoint.",
    ),
    SiteSpec(
        SITE_CELLCC, "parallel.driver",
        "one device cellcc finalize dispatch",
        ("retry", "host-oracle"), ("fallback-arg",),
        "Device cell-CC finalize; persistent faults degrade the whole "
        "finalize to the host oracle.",
    ),
    SiteSpec(
        SITE_CAMPAIGN, "campaign",
        "one campaign worker lease",
        ("lease-requeue", "worker-retire"),
        ("propagate:dbscan_tpu.campaign",),
        "Campaign lease consumption (direct ordinal draw, no "
        "supervised wrap); the harness requeues the lease and retires "
        "the worker on a fatal.",
    ),
    SiteSpec(
        SITE_SERVE, "serve.service",
        "one service ingest update",
        ("retry", "serve-last-epoch"),
        ("propagate:dbscan_tpu.serve.service",),
        "Service ingest; a fatal marks the service degraded and the "
        "query side keeps serving the last good epoch.",
    ),
    SiteSpec(
        SITE_SERVE_REPLICA, "serve.router",
        "one replica query dispatch",
        ("retry", "replica-evict-failover"),
        ("propagate:dbscan_tpu.serve.router",),
        "Router replica queries; a fatal evicts the replica and fails "
        "the query over to a healthy one.",
    ),
    SiteSpec(
        SITE_EMBED, "embed",
        "one embed hash/neighbor dispatch",
        ("retry", "host-oracle"),
        ("fallback-arg", "propagate:dbscan_tpu.embed.engine"),
        "Embed-engine dispatches; bucket faults degrade per-bucket to "
        "the oracle, hash faults degrade the whole run.",
    ),
    SiteSpec(
        SITE_DENSITY_CORE, "density.core",
        "one core-distance chunk dispatch",
        ("retry", "host-oracle"),
        ("fallback-arg", "propagate:dbscan_tpu.density",),
        "Density core-distance chunks; per-chunk host fallback, or the "
        "engine's whole-run oracle degrade.",
    ),
    SiteSpec(
        SITE_DENSITY_BORUVKA, "density.boruvka",
        "one Borůvka MST round dispatch",
        ("retry", "host-oracle"),
        ("propagate:dbscan_tpu.density",),
        "Borůvka rounds; a persistent fault degrades the whole MST "
        "build to the host oracle.",
    ),
)

_SITES = tuple(SITES) + ("*",)


def sites_self_check() -> list:
    """Registry invariants, schema.self_check()-style: a list of error
    strings (empty = healthy). Pinned by tests/test_faults.py."""
    errors = []
    known_modes = ("fallback-arg", "caller-except")
    for site, spec in SITES.items():
        if site != spec.site:
            errors.append(f"SITES key {site!r} != spec.site {spec.site!r}")
        if not re.fullmatch(r"[a-z][a-z0-9_]*", site):
            errors.append(f"site token {site!r} is not a lowercase id")
        if not spec.degrade:
            errors.append(f"site {site!r} declares no degrade ladder")
        if not spec.handler:
            errors.append(f"site {site!r} declares no handler mode")
        for mode in spec.handler:
            if mode not in known_modes and not mode.startswith(
                "propagate:"
            ):
                errors.append(
                    f"site {site!r}: unknown handler mode {mode!r}"
                )
        if not spec.doc.strip():
            errors.append(f"site {site!r} has no doc")
    return errors


def shard_site(base: str, shard=None) -> str:
    """The namespaced site token for ``base`` on shard/replica
    ``shard``: ``base@<shard>`` for shard >= 1, ``base`` itself for
    shard 0 or None. Shard 0 NORMALIZES to the bare token, so an
    existing single-process spec (``serve#3:...``) addresses — and an
    unsharded service consumes — exactly the ordinal stream it always
    did (regression-pinned)."""
    if not shard:
        return base
    return f"{base}@{int(shard)}"


class FaultInjected(Exception):
    """Deterministic injected device fault (``DBSCAN_FAULT_SPEC``)."""

    def __init__(self, site: str, ordinal: int, kind: str):
        super().__init__(f"injected {kind} fault at {site}#{ordinal}")
        self.site = site
        self.ordinal = ordinal
        self.kind = kind


class FatalDeviceFault(RuntimeError):
    """A supervised dispatch exhausted its retries with no degradation
    path. Carries the site/ordinal so abort handlers (the driver's
    chunk flush, the bench harness) can report WHERE the run died."""

    def __init__(self, site: str, ordinal: int, attempts: int, cause):
        super().__init__(
            f"{site}#{ordinal} failed after {attempts} "
            f"attempt(s): {type(cause).__name__}: {cause}"
        )
        self.site = site
        self.ordinal = ordinal
        self.attempts = attempts
        self.cause = cause


@dataclasses.dataclass(frozen=True)
class FaultClause:
    site: str  # (possibly @shard-namespaced) site token, or "*"
    ordinal: int  # 0-based per-site dispatch ordinal ("*": global)
    kind: str
    count: int  # consecutive failing attempts (ignored for PERSISTENT)


_CLAUSE_RE = re.compile(
    r"^(?P<site>[a-z_*]+)(?:@(?P<shard>\d+))?#(?P<ord>\d+):(?P<kind>[A-Z_]+)"
    r"(?:\*(?P<count>\d+))?$"
)


def parse_fault_spec(spec: str) -> Tuple[FaultClause, ...]:
    """Parse ``DBSCAN_FAULT_SPEC``.

    Grammar: semicolon-separated clauses
    ``site[@shard]#ordinal:KIND[*count]``:

    - ``site``: ``dispatch`` | ``banded`` | ``spill`` | ``spill_level``
      | ``stream`` | ``pull`` | ``cellcc_cc`` | ``campaign`` | ``serve``
      | ``serve_replica`` | ``embed`` | ``*`` (any supervised site,
      ordinal counted globally). The sharded serving sites accept an
      ``@<shard>`` namespace — ``serve@2#0:TRANSIENT`` is the first
      supervised step on ingest shard 2, ``serve_replica@1#0:PERSISTENT``
      kills query replica 1's first routed dispatch — each namespaced
      token owning its OWN deterministic ordinal stream, so a drill
      stays reproducible across a fleet of shard threads whose global
      interleaving is not. ``@0`` normalizes to the bare token: bare
      ``serve#N`` means shard 0, and an existing single-process spec
      consumes ordinals exactly as before (regression-pinned). The
      ``embed`` site is consumed per embed-engine device
      dispatch (the hash pass, then one ordinal per bucket neighbor
      dispatch, dbscan_tpu/embed): transients heal with backoff, a
      PERSISTENT neighbor fault degrades that bucket to the numpy host
      oracle, and a persistent hash fault degrades the whole run to the
      oracle (small-N capped). The
      ``serve`` site is consumed per ClusterService ingest step and
      query dispatch (dbscan_tpu/serve), opt-in like ``pull``; the
      ``campaign``
      site is consumed per LEASE by the campaign driver
      (dbscan_tpu/campaign.py), not per device dispatch: ``TRANSIENT``
      kills the leased worker after it banks one chunk (steal/resume
      drill), ``PERSISTENT`` wedges it (its lease must heartbeat-expire
      and be restolen), ``RESOURCE_EXHAUSTED`` degrades the worker to
      the CPU tier before the lease runs;
    - ``ordinal``: 0-based index of the supervised dispatch at that
      site (each :func:`supervised` call consumes one ordinal);
    - ``KIND``: ``TRANSIENT`` (fails ``count`` attempts, then heals),
      ``RESOURCE_EXHAUSTED`` (same, but classified so the budget
      halves), ``PERSISTENT`` (every attempt fails — forces the CPU
      degradation path, or a :class:`FatalDeviceFault` without one);
    - ``count``: consecutive failing attempts, default 1.

    Example — "fail dispatch #3 twice with RESOURCE_EXHAUSTED":
    ``DBSCAN_FAULT_SPEC="dispatch#3:RESOURCE_EXHAUSTED*2"``.
    """
    clauses = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        m = _CLAUSE_RE.match(raw)
        if m is None:
            raise ValueError(
                f"bad DBSCAN_FAULT_SPEC clause {raw!r}: expected "
                "site#ordinal:KIND[*count], e.g. "
                "'dispatch#3:RESOURCE_EXHAUSTED*2'"
            )
        site = m.group("site")
        kind = m.group("kind")
        if site not in _SITES:
            raise ValueError(
                f"bad DBSCAN_FAULT_SPEC site {site!r}: one of {_SITES}"
            )
        shard = m.group("shard")
        if shard is not None and site == "*":
            raise ValueError(
                "bad DBSCAN_FAULT_SPEC clause: '*' matches every site "
                "and cannot take an @shard namespace"
            )
        if kind not in _KINDS:
            raise ValueError(
                f"bad DBSCAN_FAULT_SPEC kind {kind!r}: one of {_KINDS}"
            )
        clauses.append(
            FaultClause(
                site=shard_site(site, int(shard or 0)),
                ordinal=int(m.group("ord")),
                kind=kind,
                count=int(m.group("count") or 1),
            )
        )
    return tuple(clauses)


class FaultRegistry:
    """Deterministic per-process fault injection: counts supervised
    dispatches per site and raises :class:`FaultInjected` exactly where
    the parsed spec says. Ordinals are process-lifetime counters (a
    clause fires once); tests reset between runs via
    :func:`reset_registry`."""

    def __init__(self, spec: str = ""):
        self.clauses = parse_fault_spec(spec)
        self._counts: dict = {}
        # pull-site supervision runs on the pipeline worker while the
        # dispatch sites run on the main thread; ordinal consumption is
        # a read-modify-write, so it must be locked or a mixed
        # pull+dispatch spec could lose updates and shift every later
        # global ("*") ordinal
        self._lock = _tsan.lock("faults.registry")

    @property
    def active(self) -> bool:
        return bool(self.clauses)

    def next_ordinal(self, site: str) -> Tuple[int, int]:
        """Consume one dispatch ordinal at ``site``; returns (per-site
        ordinal, global ordinal) — the latter is what ``*`` clauses
        match."""
        with self._lock:
            _tsan.access("faults.registry")
            n = self._counts.get(site, 0)
            self._counts[site] = n + 1
            g = self._counts.get("*", 0)
            self._counts["*"] = g + 1
        return n, g

    def check(
        self, site: str, ordinal: int, global_ordinal: int, attempt: int
    ) -> None:
        """Raise the injected fault for attempt ``attempt`` of dispatch
        ``ordinal`` at ``site``, if any clause covers it."""
        for c in self.clauses:
            hit = (c.site == site and c.ordinal == ordinal) or (
                c.site == "*" and c.ordinal == global_ordinal
            )
            if not hit:
                continue
            if c.kind == PERSISTENT or attempt < c.count:
                raise FaultInjected(site, ordinal, c.kind)


_registry: Optional[FaultRegistry] = None
_registry_spec: Optional[str] = None
# get_registry runs on the pull-engine worker too (supervised pull
# jobs): the check-then-rebuild of the singleton is a read-modify-write,
# and an unguarded race could hand the two threads DIFFERENT registries
# whose ordinal streams both start at 0 — double-firing one-shot fault
# clauses. Found by graftcheck race-unlocked-shared (PR 6).
_registry_lock = _tsan.lock("faults.registry_state")


def get_registry() -> FaultRegistry:
    """The process registry for the CURRENT ``DBSCAN_FAULT_SPEC`` value
    (re-parsed — with fresh ordinal counters — whenever the env value
    changes, so tests can monkeypatch the spec per test). Thread-safe:
    the worker's supervised pull jobs land here concurrently with the
    main thread's dispatches."""
    global _registry, _registry_spec
    spec = config.env("DBSCAN_FAULT_SPEC")
    with _registry_lock:
        _tsan.access("faults.registry_state")
        if _registry is None or spec != _registry_spec:
            _registry = FaultRegistry(spec)
            _registry_spec = spec
        return _registry


def reset_registry() -> None:
    """Drop the registry (ordinal counters restart at 0 on next use)."""
    global _registry, _registry_spec
    with _registry_lock:
        _tsan.access("faults.registry_state")
        _registry = None
        _registry_spec = None


def pull_site_active() -> bool:
    """True when the active fault spec names the ``pull`` site
    explicitly. The pipelined pull wraps its job in :func:`supervised`
    ONLY then: an unconditional wrap would consume registry ordinals
    for every chunk pull and shift the global (``*``-clause) ordinal
    stream every existing spec was written against — and interleave it
    nondeterministically, since pull ordinals are consumed on the
    engine worker while dispatch ordinals are consumed on the main
    thread. Real (un-injected) async device faults keep today's path
    either way: they surface at the consuming wait and hit the
    driver's abort guard."""
    return any(c.site == SITE_PULL for c in get_registry().clauses)


def campaign_site_active() -> bool:
    """True when the active fault spec names the ``campaign`` site
    explicitly. The campaign driver consumes one ``campaign`` ordinal
    per granted lease ONLY then — the same opt-in discipline as
    :func:`pull_site_active`: an unconditional consume would shift the
    global (``*``-clause) ordinal stream every existing spec was
    written against, and would interleave nondeterministically, since
    leases are granted on campaign worker threads."""
    return any(c.site == SITE_CAMPAIGN for c in get_registry().clauses)


def serve_site_active() -> bool:
    """True when the active fault spec names the ``serve`` site
    explicitly (shard 0's bare token — sharded services check their own
    namespaced token via :func:`site_active`). The ClusterService
    consumes one ``serve`` ordinal per ingest step and per query
    dispatch ONLY then — the same opt-in discipline as
    :func:`pull_site_active`: an unconditional consume
    would shift the global (``*``-clause) ordinal stream, and would
    interleave nondeterministically, since ingest ordinals are consumed
    on the service's ingest thread while query ordinals are consumed on
    whatever reader thread asked."""
    return site_active(SITE_SERVE)


def site_active(site: str) -> bool:
    """True when the active fault spec names exactly this (possibly
    ``@shard``-namespaced) site token. The sharded serving sites
    (``serve@<shard>``, ``serve_replica@<replica>``) opt in per token:
    a drill naming shard 1 makes ONLY shard 1 consume ordinals, so
    every shard's stream stays deterministic regardless of how the
    shard threads interleave."""
    return any(c.site == site for c in get_registry().clauses)


class FaultCounters:
    """Structured failure accounting, accumulated process-wide; callers
    snapshot at run start and report the delta (one run's counters).
    Increments go through :meth:`add` — supervised pull jobs run on the
    pipeline worker concurrently with main-thread dispatches, and an
    unlocked ``+=`` could lose updates and break the documented
    field-for-field equality with the (locked) obs ``faults.*``
    counters."""

    _FIELDS = (
        "attempts",
        "retries",
        "fallbacks",
        "budget_halvings",
        "injected",
        "backoff_s",
    )

    def __init__(self):
        self._lock = _tsan.lock("faults.counters")
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.attempts = 0  # supervised attempts started
            self.retries = 0  # attempts re-run after supervised failure
            self.fallbacks = 0  # groups/steps degraded to the CPU path
            self.budget_halvings = 0  # RESOURCE_EXHAUSTED reductions
            self.injected = 0  # injected (vs real) faults observed
            self.backoff_s = 0.0  # total backoff slept

    def add(self, field: str, value=1) -> None:
        with self._lock:
            _tsan.access("faults.counters")
            setattr(self, field, getattr(self, field) + value)

    def snapshot(self) -> dict:
        with self._lock:
            _tsan.access("faults.counters", write=False)
            return {f: getattr(self, f) for f in self._FIELDS}

    def delta(self, snap: dict) -> dict:
        # diff against a LOCKED snapshot: a raw field-by-field read
        # could tear across a worker-thread add (retries moved,
        # backoff_s not yet) and break the field-for-field equality
        # with the obs faults.* counters
        out = {
            f: v - snap.get(f, 0) for f, v in self.snapshot().items()
        }
        out["backoff_s"] = round(out["backoff_s"], 6)
        return out


counters = FaultCounters()


def classify(exc: BaseException) -> Optional[str]:
    """Map an exception from a device dispatch to a fault kind, or None
    for non-device errors (programming/shape/trace failures) that must
    re-raise unretried.

    Device-runtime errors are recognized structurally (XlaRuntimeError
    and jaxlib-raised RuntimeErrors) rather than by import, so the
    module stays importable without a live backend. Within that class,
    RESOURCE_EXHAUSTED/OOM messages classify as budget faults; all
    other device-runtime failures count as transient — the dispatch is
    idempotent (pure function of host inputs), so a retry is always
    safe and the tunneled-TPU failure mode this serves (worker dies,
    channel resets) presents as UNAVAILABLE/INTERNAL noise."""
    if isinstance(exc, FaultInjected):
        return exc.kind
    if isinstance(exc, FatalDeviceFault):
        return None  # already supervised once; never re-wrap
    name = type(exc).__name__
    mod = type(exc).__module__ or ""
    is_device = name == "XlaRuntimeError" or (
        isinstance(exc, RuntimeError)
        and ("jaxlib" in mod or "jax" in mod.split(".")[:1])
    )
    if not is_device:
        return None
    msg = str(exc)
    if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg or "OOM" in msg:
        return RESOURCE_EXHAUSTED
    return TRANSIENT


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry policy for one supervised dispatch.

    ``max_retries`` bounds RE-runs (total attempts = max_retries + 1).
    Backoff for retry ``k`` is ``base * 2**k`` capped at ``max_s``,
    times a deterministic jitter in [1, 1 + jitter] seeded from
    (seed, site, ordinal) — retries desynchronize across groups without
    making reruns irreproducible."""

    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.25
    seed: int = 0

    @classmethod
    def from_config(cls, cfg) -> "RetryPolicy":
        """Policy from DBSCANConfig fault knobs, with env overrides
        (``DBSCAN_FAULT_RETRIES`` / ``DBSCAN_FAULT_BACKOFF_S`` — the
        retry-harness knobs, same spirit as DBSCAN_COMPACT_CHUNK_SLOTS).
        ``cfg`` may be None (sites with no config in scope): dataclass
        defaults apply, env overrides still win."""
        retries = int(
            config.env(
                "DBSCAN_FAULT_RETRIES",
                default=getattr(cfg, "fault_max_retries", 3),
            )
        )
        base = float(
            config.env(
                "DBSCAN_FAULT_BACKOFF_S",
                default=getattr(cfg, "fault_backoff_base_s", 0.05),
            )
        )
        return cls(
            max_retries=max(0, retries),
            backoff_base_s=max(0.0, base),
            backoff_max_s=float(getattr(cfg, "fault_backoff_max_s", 2.0)),
            seed=int(config.env("DBSCAN_FAULT_SEED")),
        )

    def backoff(self, attempt: int, rng: np.random.Generator) -> float:
        base = min(
            self.backoff_max_s, self.backoff_base_s * (2.0**attempt)
        )
        return float(base * (1.0 + self.jitter * rng.random()))


def _site_seed(
    policy: RetryPolicy, site: str, ordinal: int
) -> np.random.Generator:
    return np.random.default_rng(
        [policy.seed, zlib.crc32(site.encode()), ordinal]
    )


def sync_mode(registry: Optional[FaultRegistry] = None) -> bool:
    """True when supervised dispatches must block on their outputs so
    faults surface AT the dispatch site (group-granular retry): any
    fault spec active, or ``DBSCAN_FAULT_SYNC=1``."""
    reg = registry if registry is not None else get_registry()
    return reg.active or bool(config.env("DBSCAN_FAULT_SYNC"))


def supervised(
    site: str,
    attempt_fn: Callable[[Optional[int]], object],
    *,
    policy: Optional[RetryPolicy] = None,
    budget: Optional[int] = None,
    fallback: Optional[Callable[[], object]] = None,
    label: str = "",
):
    """Run one device dispatch under fault supervision.

    ``attempt_fn(budget)`` performs one attempt; ``budget`` is the
    dispatch's batch/chunk knob (lax.map batch size for the kernel
    fan-outs) and is halved — never below 1 — before retrying a
    RESOURCE_EXHAUSTED fault. ``fallback()`` is the CPU degradation for
    this group; invoked once retries are exhausted (or immediately on a
    PERSISTENT injected fault). With no fallback, exhaustion raises
    :class:`FatalDeviceFault` for the caller's abort path to handle.

    Returns whatever ``attempt_fn`` (or ``fallback``) returns. In sync
    mode (see :func:`sync_mode`) the attempt's outputs are blocked on
    before returning, so async device faults attribute to this site.
    """
    reg = get_registry()
    ordinal, global_ordinal = reg.next_ordinal(site)
    block = sync_mode(reg)
    what = f"{site}#{ordinal}" + (f" ({label})" if label else "")
    # policy/rng construction is deferred to the first FAILURE: the
    # spill sites route hundreds of per-node gathers through here, and
    # the fault-free hot path shouldn't pay env parsing + seeded
    # Generator setup it never consumes
    pol = policy
    rng = None
    last: Optional[BaseException] = None
    attempts = 0
    attempt = 0
    while True:
        attempts += 1
        counters.add("attempts")
        obs.count("faults.attempts")
        try:
            reg.check(site, ordinal, global_ordinal, attempt)
            # graftfault cross-check window: fingerprint the shared-
            # state writes the attempt makes (one truthiness check
            # when the checker is off — the tsan/obs discipline)
            if _faultcheck._rt is not None:
                _faultcheck.begin(site)
                try:
                    out = attempt_fn(budget)
                finally:
                    _faultcheck.end(site)
            else:
                out = attempt_fn(budget)
            if block and out is not None:
                import jax

                jax.block_until_ready(out)
            return out
        except Exception as e:  # noqa: BLE001 — classify() re-raises
            kind = classify(e)
            if kind is None:
                raise
            if isinstance(e, FaultInjected):
                counters.add("injected")
                obs.count("faults.injected")
            # one live tick per CLASSIFIED fault (injected or real) —
            # the fault_rate SLO's windowed numerator (obs/slo.py)
            _obs_live.bump("faults.events")
            last = e
            if kind == PERSISTENT:
                # every attempt would fail identically: stop burning
                # backoff and go straight to the degradation decision
                break
            if pol is None:
                # no explicit policy (the spill/stream sites have no
                # cfg in scope): still honor the DBSCAN_FAULT_RETRIES /
                # DBSCAN_FAULT_BACKOFF_S env knobs, so every supervised
                # site obeys the advertised overrides
                pol = RetryPolicy.from_config(None)
            if attempt >= pol.max_retries:
                break
            if (
                kind == RESOURCE_EXHAUSTED
                and budget is not None
                and budget > 1
            ):
                budget = max(1, budget // 2)
                counters.add("budget_halvings")
                obs.count("faults.budget_halvings")
                # record the HBM occupancy that (presumably) triggered
                # the exhaustion: until now the halving was blind — a
                # capture could not say whether the chip was really at
                # its limit or the fault was fragmentation/transients.
                # None (and omitted) when obs is off or the backend has
                # no allocator stats.
                hbm = _obs_memory.sample("fault.resource_exhausted")
                obs.event(
                    "fault.budget_halved",
                    site=site,
                    ordinal=ordinal,
                    budget=budget,
                    **(
                        {"hbm_bytes_in_use": int(hbm)}
                        if hbm is not None
                        else {}
                    ),
                )
                logger.warning(
                    "%s: RESOURCE_EXHAUSTED — halving batch budget to "
                    "%d before retry",
                    what,
                    budget,
                )
            if rng is None:
                rng = _site_seed(pol, site, ordinal)
            delay = pol.backoff(attempt, rng)
            counters.add("retries")
            counters.add("backoff_s", delay)
            obs.count("faults.retries")
            obs.count("faults.backoff_s", delay)
            obs.event(
                "fault.retry",
                site=site,
                ordinal=ordinal,
                kind=kind,
                attempt=attempt + 1,
                delay_s=round(delay, 6),
                error=f"{type(e).__name__}"[:80],
            )
            logger.warning(
                "%s attempt %d/%d failed (%s: %s); retrying in %.2fs",
                what,
                attempt + 1,
                pol.max_retries + 1,
                type(e).__name__,
                e,
                delay,
            )
            if delay > 0:
                time.sleep(delay)
            attempt += 1
    if fallback is not None:
        counters.add("fallbacks")
        obs.count("faults.fallbacks")
        obs.event(
            "fault.fallback",
            site=site,
            ordinal=ordinal,
            attempts=attempts,
            error=f"{type(last).__name__}"[:80],
        )
        logger.warning(
            "%s failed after %d attempt(s) (%s: %s); degrading this "
            "group to the CPU engine",
            what,
            attempts,
            type(last).__name__,
            last,
        )
        if _faultcheck._rt is not None:
            _faultcheck.begin(site)
            try:
                return fallback()
            finally:
                _faultcheck.end(site)
        return fallback()
    obs.event(
        "fault.fatal",
        site=site,
        ordinal=ordinal,
        attempts=attempts,
        error=f"{type(last).__name__}"[:80],
    )
    # the run is about to die with no degradation path: leave the
    # flight-recorder postmortem (the ring's tail + this abort site)
    # BEFORE raising, so even a caller with no abort handler of its own
    # (spill/stream sites) gets a dump; the driver's abort guard dumps
    # again after checkpoint.note_abort with the banked-chunk context —
    # same file, atomically rewritten, strictly more information.
    _obs_flight.dump_on_fault(site, ordinal, f"{type(last).__name__}: {last}")
    raise FatalDeviceFault(site, ordinal, attempts, last)


def note_degrade() -> None:
    """Record a host-path degradation decided by the CALLER — the spill
    tree keeps its own device->host fallback structure (per-node state
    to tear down), so it counts the degrade itself after
    :func:`supervised` exhausts the retries."""
    counters.add("fallbacks")
    obs.count("faults.fallbacks")
    obs.event("fault.degrade_host")
