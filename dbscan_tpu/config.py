"""Configuration for tpu-dbscan.

The reference has no config system at all — three positional hyperparameters
(reference DBSCAN.scala:40-44) and hardcoded sample paths
(DBSCANSample.scala:18,35). We fix that with one explicit dataclass that every
entry point takes, covering the algorithm knobs plus the TPU-execution knobs
that have no Spark counterpart (bucketing, precision, mesh shape).
"""

from __future__ import annotations

import dataclasses
import enum
import os


class Engine(str, enum.Enum):
    """Which local-engine semantics to emulate.

    The reference ships two engines whose border-adoption semantics diverge
    (SURVEY.md section 3.2/3.3):

    - ``NAIVE``: reference LocalDBSCANNaive.scala:80-118 — a point visited as
      noise before any cluster expansion reaches it is NEVER adopted as Border
      (dead re-labeling code at :108-111). This is what the distributed driver
      actually runs (DBSCAN.scala:154).
    - ``ARCHERY``: reference LocalDBSCANArchery.scala:71-112 — textbook DBSCAN;
      visited noise points ARE adopted as Border (:103-106).

    Both reduce to vectorizable rules on TPU: with connected-component labels
    equal to the minimum core-point row index ("seed index"), a non-core point
    with a core neighbor is Border-with-cluster = min adjacent seed (both
    engines agree on the cluster), and under NAIVE it additionally requires
    that min adjacent seed < its own row index (else it stays Noise).
    """

    NAIVE = "naive"
    ARCHERY = "archery"


class Precision(str, enum.Enum):
    """Compute dtype for the distance kernel.

    The reference computes squared distances in float64 on the JVM
    (DBSCANPoint.scala:26-30). TPUs natively prefer f32/bf16; eps-boundary
    decisions (d^2 <= eps^2) can flip under f32, so parity runs use F64 (CPU
    or x64 mode) while throughput runs use F32.
    """

    F32 = "f32"
    F64 = "f64"
    BF16 = "bf16"


@dataclasses.dataclass(frozen=True)
class DBSCANConfig:
    """All knobs for a distributed DBSCAN run.

    Attributes:
      eps: max distance between two points to be in the same eps-neighborhood
        (reference DBSCAN.scala:41-43).
      min_points: minimum neighborhood size (self-inclusive, matching
        LocalDBSCANNaive.scala:72-78 where the query point is its own
        neighbor) to be a core point.
      max_points_per_partition: best-effort upper bound on points per spatial
        partition (reference DBSCAN.scala:53-56).
      engine: local-engine semantics, see :class:`Engine`.
      precision: distance-kernel dtype, see :class:`Precision`.
      metric: distance metric name registered in dbscan_tpu.ops.distance
        ("euclidean", "haversine", "cosine"). The reference supports only
        2-D Euclidean (DBSCANPoint.scala:26-30); extra metrics per
        BASELINE.json configs.
      bucket_multiple: partition buffers are padded to a multiple of this
        (sublane*lane friendly) to bound recompilation across runs.
      use_pallas: route the per-partition kernel through the Pallas tiled
        implementation instead of plain XLA ops.
      neighbor_backend: "auto" | "dense" | "banded" — how the per-partition
        engine finds eps-neighbors. "dense" materializes the [B, B]
        adjacency; "banded" sorts each partition by an eps-cell grid and
        sweeps only the 3-row candidate windows (O(B * window),
        dbscan_tpu/ops/banded.py; euclidean 2-D grids, plus haversine via
        the equirectangular grid + chord kernel). "auto" picks banded for
        partitions large enough that the windows pay off. With use_pallas,
        euclidean runs may use any backend (large buckets ride the banded
        Pallas port either way), while haversine REQUIRES "banded" — the
        dense streaming Pallas kernel is 2-D-only.
      auto_maxpp: when the densest single 2eps cell holds so many points
        that max_points_per_partition under-fits it (the partitioner
        cannot cut inside a cell, so partitions degenerate to near-single-
        cell rectangles whose eps-halo bands duplicate heavily — measured
        dup 2.37 on a 50M hotspot run at maxpp=32768), raise the
        EFFECTIVE partition bound to a small multiple of that pileup
        (capped, reported in stats["effective_maxpp"]). The cluster
        structure is partitioning-independent (global ids renumber with
        partition order; pinned up-to-permutation by the cross-maxpp
        tests), so this is purely a layout/perf adjustment — but it does
        change partition counts/shapes, so it is opt-in; the under-fit
        regime is always WARNED about either way (reference analog: the
        silent cannot-split-further path,
        EvenSplitPartitioner.scala:85-92).
      static_partition_pad: pad each bucket group's PARTITION axis up a
        geometric ladder instead of to the exact mesh multiple. A
        data-dependent partition count mints a fresh jit signature per
        run; the ladder makes group shapes recur, which is what lets
        streaming micro-batches (streaming.py, which sets this) hit the
        compile cache at steady state. Costs up to ~1.5x padded (masked,
        cheaply skipped but still swept) partitions per group, so
        one-shot batch runs keep it off.
      fault_max_retries: bounded retries per supervised device dispatch
        (dbscan_tpu/faults.py): a transient device fault re-runs the
        dispatch up to this many extra times with exponential backoff
        before the degradation decision. The reference has no in-process
        story at all — Spark lineage replays the whole partition
        (DBSCAN.scala:59-60); here a flaky dispatch costs one group's
        retry. Env override DBSCAN_FAULT_RETRIES.
      fault_backoff_base_s: base of the exponential backoff between
        retries (doubles per attempt, deterministic jitter on top,
        capped at fault_backoff_max_s). Env override
        DBSCAN_FAULT_BACKOFF_S.
      fault_backoff_max_s: backoff ceiling per retry.
      fault_cpu_fallback: when a dispatch exhausts its retries, run
        THAT group on the CPU local_dbscan engine (labels identical —
        same algebra, host backend) instead of aborting the run. Off:
        retries-exhausted faults raise, after the driver flushes the
        current compact chunk so the abort still resumes from the last
        completed group. Forced off in multi-process runs (a one-host
        degradation would desynchronize the collective sequence).
    """

    eps: float
    min_points: int
    max_points_per_partition: int = 250
    engine: Engine = Engine.NAIVE
    precision: Precision = Precision.F32
    metric: str = "euclidean"
    bucket_multiple: int = 128
    use_pallas: bool = False
    neighbor_backend: str = "auto"
    auto_maxpp: bool = False
    static_partition_pad: bool = False
    # Supervised-dispatch fault policy (dbscan_tpu/faults.py). Excluded
    # from the checkpoint fingerprint: retries/degradation never change
    # the instance tables (the CPU engine computes the same algebra).
    fault_max_retries: int = 3
    fault_backoff_base_s: float = 0.05
    fault_backoff_max_s: float = 2.0
    fault_cpu_fallback: bool = True
    # Monotone shape-ratchet state for streaming micro-batches (see
    # binning._ratchet): a mutable dict the SAME config object carries
    # across updates — rungs pinned here only grow, so steady-state
    # batches reuse exact jit signatures. None (default) disables; owned
    # and installed by streaming.StreamingDBSCAN. Excluded from the
    # checkpoint fingerprint (streaming runs don't checkpoint).
    shape_floors: dict = dataclasses.field(default=None, compare=False)

    @property
    def eps_sq(self) -> float:
        return float(self.eps) * float(self.eps)

    @property
    def minimum_rectangle_size(self) -> float:
        """Grid cell size = 2*eps (reference DBSCAN.scala:289)."""
        return 2.0 * float(self.eps)

    def validate(self) -> "DBSCANConfig":
        if not self.eps > 0:
            raise ValueError(f"eps must be > 0, got {self.eps}")
        if self.min_points < 1:
            raise ValueError(f"min_points must be >= 1, got {self.min_points}")
        if self.max_points_per_partition < 1:
            raise ValueError(
                "max_points_per_partition must be >= 1, got "
                f"{self.max_points_per_partition}"
            )
        if self.bucket_multiple < 1:
            raise ValueError(
                f"bucket_multiple must be >= 1, got {self.bucket_multiple}"
            )
        if self.fault_max_retries < 0:
            raise ValueError(
                "fault_max_retries must be >= 0, got "
                f"{self.fault_max_retries}"
            )
        if self.fault_backoff_base_s < 0 or self.fault_backoff_max_s < 0:
            raise ValueError(
                "fault backoff seconds must be >= 0, got "
                f"base={self.fault_backoff_base_s} "
                f"max={self.fault_backoff_max_s}"
            )
        if self.neighbor_backend not in ("auto", "dense", "banded"):
            raise ValueError(
                'neighbor_backend must be "auto", "dense", or "banded", got '
                f"{self.neighbor_backend!r}"
            )
        if self.neighbor_backend == "banded" and self.metric not in (
            "euclidean",
            "haversine",
        ):
            raise ValueError(
                "neighbor_backend='banded' supports the euclidean metric "
                "(eps-cell grids) and haversine (equirectangular grid + "
                f"chord kernel, ops/sphere.py), got {self.metric!r}"
            )
        return self


# --- environment-variable registry ------------------------------------
#
# Every ``DBSCAN_*`` environment read in the package goes through
# :func:`env` against this table — the one place a knob's name, type,
# default, and doc live. The static analyzer (``dbscan_tpu.lint``, rule
# family ``env-*``) rejects any direct ``os.environ``/``os.getenv`` read
# of a ``DBSCAN_*`` name outside this module and any :func:`env` call
# naming an undeclared variable, and requires every declared name to
# have its table row in PARITY.md (regenerate that table with
# ``python -m dbscan_tpu.lint --env-table``).


@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One declared environment knob.

    ``kind``: "bool" (true iff the value is 1/true/yes/on,
    case-insensitive; anything else including empty is false),
    "int", "float", or "str". ``default`` is the parsed-type value
    used when the variable is unset (may be None for pure-optional
    strings like DBSCAN_TRACE).
    """

    name: str
    kind: str
    default: object
    doc: str


_TRUE = ("1", "true", "yes", "on")


def _env_table(*rows: EnvVar) -> dict:
    return {r.name: r for r in rows}


ENV_VARS = _env_table(
    EnvVar(
        "DBSCAN_TPU_NATIVE", "bool", True,
        "Enable the compiled native host runtime (_native.py); 0 forces "
        "the numpy fallbacks.",
    ),
    EnvVar(
        "DBSCAN_TPU_NO_COMPILE_CACHE", "bool", False,
        "Opt out of the persistent XLA compilation cache the package "
        "configures at import.",
    ),
    EnvVar(
        "DBSCAN_TPU_COMPILE_CACHE_DIR", "str",
        "~/.cache/dbscan_tpu_xla",
        "Directory for the persistent XLA compilation cache (used only "
        "when no cache is already configured).",
    ),
    EnvVar(
        "DBSCAN_GROUP_SLOTS", "int", 1 << 26,
        "Padded-slot budget per dispatch group (binning packer and the "
        "checkpoint chunk tags).",
    ),
    EnvVar(
        "DBSCAN_COMPACT_CHUNK_SLOTS", "int", 1 << 26,
        "Padded slots per compact phase-1 device chunk; clamped to "
        "[2^16, 2^28] (driver warns on clamp). Saved chunks are stamped "
        "with the value, so changing it invalidates prior checkpoints.",
    ),
    EnvVar(
        "DBSCAN_INFLIGHT_SLOTS", "int", 1 << 27,
        "Dispatched-but-unretired slot budget (dispatch backpressure); "
        "1 = fully synchronous dispatch.",
    ),
    EnvVar(
        "DBSCAN_PALLAS_SP", "bool", False,
        "Route banded phase 1 through the scalar-prefetch Pallas "
        "kernels (ops/pallas_banded_sp.py).",
    ),
    EnvVar(
        "DBSCAN_RESIDENT_CACHE", "bool", True,
        "Resident-payload device cache across runs (driver); 0 disables "
        "— every run re-uploads its payload.",
    ),
    EnvVar(
        "DBSCAN_TIME_DEVICE", "bool", False,
        "Spans/timings block on device outputs at phase boundaries so "
        "walls attribute to the dispatch that did the work.",
    ),
    EnvVar(
        "DBSCAN_NO_COMPACT", "bool", False,
        "Disable the compact phase-1 chunk path for banded runs "
        "(debugging aid).",
    ),
    EnvVar(
        "DBSCAN_EAGER_PULL", "bool", False,
        "Pull each compact chunk to host as soon as it flushes instead "
        "of at the postdispatch tail.",
    ),
    EnvVar(
        "DBSCAN_PULL_PIPELINE", "bool", True,
        "Pipelined pull engine (parallel/pipeline.py): D2H transfers "
        "and host finalize run on a background worker, overlapping "
        "remaining device dispatch; 0 restores the serial pull paths "
        "byte-for-byte.",
    ),
    EnvVar(
        "DBSCAN_PULL_INFLIGHT", "int", 2,
        "Pull-pipeline depth: compact chunks with copy_to_host_async "
        "issued ahead of the host finalize (the pull.inflight gauge "
        "never exceeds it).",
    ),
    EnvVar(
        "DBSCAN_PULL_INFLIGHT_BYTES", "int", 1 << 30,
        "Byte budget across in-flight pipelined pulls, so HBM-resident "
        "chunks are not all materialized host-side at once (a single "
        "oversized chunk still runs, alone).",
    ),
    EnvVar(
        "DBSCAN_CELLCC_DEVICE", "bool", True,
        "Device-resident cellcc finalize for banded runs: per-chunk "
        "unpack + one fused on-device cell connected-components / "
        "border-algebra dispatch, so only final labels cross the link. "
        "0 keeps the host unpack/scipy finalize as the parity oracle; "
        "checkpointed, multi-process, DBSCAN_EAGER_PULL, and "
        "pull-fault-clause (DBSCAN_FAULT_SPEC pull#N) runs use the "
        "host path regardless (their per-chunk artifacts/ordinals must "
        "materialize host-side).",
    ),
    EnvVar(
        "DBSCAN_PROP_UNIONFIND", "str", "auto",
        "Propagation mode of the shared min-label fixed point "
        "(ops/propagation.py): 'auto'/'1' route every window_cc "
        "consumer (banded cellcc, dense, embed neighbors, halo merge) "
        "through the single-pass union-find variant — scatter-min edge "
        "relaxation plus aggressive pointer doubling per sweep, the "
        "arXiv:1912.06255 structure — which collapses the O(diameter) "
        "sweep count; '0' keeps the classic iterated path as the "
        "parity oracle (labels are byte-identical either way; only the "
        "gated sweep counts move).",
    ),
    EnvVar(
        "DBSCAN_CELLCC_FUSED", "str", "auto",
        "Fused Pallas unpack+fold+propagate for the device cellcc "
        "finalize (ops/pallas_banded.py): each chunk's packed-slab "
        "unpack, per-cell scatter-fold, AND the first propagation "
        "sweep run as ONE cellcc.fused dispatch at flush time, so the "
        "tail cellcc.cc starts one sweep warm. 'auto' engages it on "
        "Pallas-capable (TPU) backends only; '1' forces it anywhere "
        "(interpreter mode keeps the CPU suite honest); '0' keeps the "
        "split unpack/cc pair. DBSCAN_CELLCC_DEVICE semantics (fault "
        "site, degrade ladder, residency cap) are unchanged.",
    ),
    EnvVar(
        "DBSCAN_CELLCC_DEVICE_SLOTS", "int", 1 << 28,
        "Staged-slot budget of the device cellcc finalize: it keeps "
        "~13 B/slot of chunk metadata/partials resident until the tail "
        "CC dispatch (the host path frees per chunk), so a run whose "
        "chunks exceed this degrades the finalize to the host oracle "
        "mid-run, freeing the staged HBM; labels are unchanged.",
    ),
    EnvVar(
        "DBSCAN_MESH_MERGE", "bool", True,
        "Collective halo-merge on multi-device meshes "
        "(parallel/halo.py): the cross-partition border union runs as "
        "a shard_map fixed point with ppermute/psum-style neighbor "
        "collectives instead of the driver-side union-find; 0 keeps "
        "the host union-find as the parity oracle (labels are "
        "byte-identical either way).",
    ),
    EnvVar(
        "DBSCAN_MESH_SHAPE", "str", None,
        "2-D mesh factorization for make_mesh2d as 'PARTSxHALO' (e.g. "
        "4x2); unset picks the most-square factorization of the device "
        "count.",
    ),
    EnvVar(
        "DBSCAN_MESH_RESHARD", "bool", True,
        "Chip-drop degradation for sharded runs "
        "(campaign.train_resharded): a retries-exhausted device fault "
        "re-shards the run onto a smaller mesh (halving the device "
        "count, eventually single-device) instead of dying; 0 lets the "
        "fault propagate.",
    ),
    EnvVar(
        "DBSCAN_SPILL_DEVICE", "str", "auto",
        "Spill-tree device passes: 1 forces the accelerator path, 0 "
        "forces host BLAS, auto uses the device when a non-CPU backend "
        "is live.",
    ),
    EnvVar(
        "DBSCAN_SPILL_DEVICE_TREE", "bool", True,
        "Level-synchronous device spill-tree build (one fused dispatch "
        "per tree level over all open nodes); engages wherever the "
        "device passes are live. 0 keeps the node-recursive host build "
        "as the parity oracle.",
    ),
    EnvVar(
        "DBSCAN_SPILL_LEVEL_SLOTS", "int", 1 << 28,
        "Instance*pivot element budget per level dispatch of the "
        "device spill tree: the pivot-slot rung is halved until "
        "instances * pivot_slots fits, bounding the level's [M, m] "
        "working set.",
    ),
    EnvVar(
        "DBSCAN_COMPILE_STORM_THRESHOLD", "int", 12,
        "Compiles per dispatch family past which obs/compile.py logs a "
        "once-per-family recompile-storm warning; <=0 disables.",
    ),
    EnvVar(
        "DBSCAN_TRACE", "str", None,
        "Path that activates observability at the pipeline entry points "
        "and receives the trace (Chrome JSON, or JSONL for .jsonl). "
        "Multi-process runs write per-process shards <path>.<i>, merged "
        "by python -m dbscan_tpu.obs.analyze --merge.",
    ),
    EnvVar(
        "DBSCAN_FLIGHTREC", "bool", True,
        "Always-on flight recorder (obs/flight.py): a bounded ring of "
        "the most recent spans/events/counters, dumped as JSON on a "
        "fatal fault, SIGTERM, SIGUSR1, or obs.flight.dump(); 0 "
        "restores the strict no-op hook path.",
    ),
    EnvVar(
        "DBSCAN_FLIGHTREC_PATH", "str", None,
        "Flight-recorder dump path (multi-process runs shard it as "
        "<path>.<process_index>, like DBSCAN_TRACE). Unset (the "
        "default), dumps go to a run-scoped file under the system tmp "
        "dir — dbscan-flightrec.<pid>.json — so unconfigured runs "
        "never litter the working directory.",
    ),
    EnvVar(
        "DBSCAN_FLIGHTREC_EVENTS", "int", 2048,
        "Flight-recorder ring capacity: the dump carries at least this "
        "many trailing spans/instants (floor 64).",
    ),
    EnvVar(
        "DBSCAN_DEVTIME", "bool", False,
        "Ready-sync device-timeline brackets (obs/devtime.py): every "
        "tracked dispatch blocks on its outputs and records devtime.* "
        "counters plus a devtime.<family> span — the always-available "
        "device-busy measurement (serializes the dispatch tail; bench "
        "enables it around its timed reps).",
    ),
    EnvVar(
        "DBSCAN_PROFILE_WINDOW", "int", 0,
        "When >0, open one jax.profiler capture window spanning this "
        "many tracked dispatches (closed automatically; atexit guard "
        "stops an abandoned session). One window per process.",
    ),
    EnvVar(
        "DBSCAN_PROFILE_DIR", "str", "dbscan_profile",
        "Log directory the DBSCAN_PROFILE_WINDOW capture writes to "
        "(TensorBoard profile layout; obs.devtime.convert_profile "
        "turns any emitted trace.json[.gz] into our Chrome format).",
    ),
    EnvVar(
        "DBSCAN_PULL_STALL_S", "float", 30.0,
        "Seconds a pull-pipeline consumer may block on one job before "
        "a pull.stall event (with queue depth) is emitted — the "
        "wedged-engine mark the flight recorder captures; <=0 disables.",
    ),
    EnvVar(
        "DBSCAN_TRACE_MAX_SPANS", "int", 200000,
        "Span retention bound: past it the tracer drops the OLDEST half "
        "and reports dropped_spans in the export.",
    ),
    EnvVar(
        "DBSCAN_CAMPAIGN_WORKERS", "int", 2,
        "Worker fleet size for chunk-leased campaigns "
        "(dbscan_tpu/campaign.py Campaign; python -m dbscan_tpu.campaign).",
    ),
    EnvVar(
        "DBSCAN_CAMPAIGN_LEASE_S", "float", 30.0,
        "Campaign lease heartbeat expiry: a leased worker that banks no "
        "chunk (and sends no heartbeat) for this long has its chunks "
        "requeued and restolen by the rest of the fleet.",
    ),
    EnvVar(
        "DBSCAN_CAMPAIGN_MIN_CHUNK", "int", 1,
        "Floor of the fault-rate-aware lease size ladder: a worker "
        "whose leases keep faulting halves its chunk batch down to "
        "this many chunks per lease.",
    ),
    EnvVar(
        "DBSCAN_CAMPAIGN_MAX_CHUNK", "int", 8,
        "Cap of the fault-rate-aware lease size ladder: sustained "
        "healthy leases double the batch back up to this many chunks "
        "per lease.",
    ),
    EnvVar(
        "DBSCAN_SERVE_QUEUE", "int", 8,
        "Ingest-queue bound of the resident ClusterService "
        "(dbscan_tpu/serve): micro-batches submitted past this depth "
        "block (or are rejected with block=False) — the service's "
        "backpressure signal, surfaced as the serve.queue_depth gauge.",
    ),
    EnvVar(
        "DBSCAN_SERVE_QUERY_SLOTS", "int", 4096,
        "Padded query-point slots per serve.query dispatch: a query "
        "batch larger than this is split into consecutive dispatches "
        "(each padded up the ladder), bounding the [Q, K] measure "
        "working set.",
    ),
    EnvVar(
        "DBSCAN_SERVE_JOB_SLOTS", "int", 2048,
        "Per-job padded point cap of the multi-tenant JobBatcher: a "
        "job with more points than this is rejected at admission "
        "(small-job batching is the wrong tool past it — run "
        "train/streaming instead).",
    ),
    EnvVar(
        "DBSCAN_SERVE_BATCH_JOBS", "int", 64,
        "Max jobs stacked into one serve.jobs batched dispatch; also "
        "the J bound the lint-time HBM gate evaluates the serve.jobs "
        "family model at.",
    ),
    EnvVar(
        "DBSCAN_SERVE_HEADROOM_BYTES", "int", 1 << 34,
        "HBM headroom budget the serve admission controller prices "
        "serve.jobs dispatches against (graftshape FAMILY_MODELS "
        "prediction): a batch whose predicted footprint exceeds this "
        "is split/queued, and a single job that alone breaches it is "
        "rejected.",
    ),
    EnvVar(
        "DBSCAN_SERVE_REPLICAS", "int", 2,
        "Query replica count of the serving failover router "
        "(dbscan_tpu/serve/router.py): each published consistent cut "
        "broadcasts its ladder-padded skeletons to this many read "
        "replicas, and queries hash across the live set; a replica "
        "evicted by a persistent fault shrinks the set (re-route, "
        "never an error) until it is empty and the host oracle "
        "answers.",
    ),
    EnvVar(
        "DBSCAN_SERVE_READ_TIMEOUT_S", "float", 30.0,
        "Seqlock read starvation bound of the serving layer: a reader "
        "spinning on a publish that never completes (wedged writer — "
        "odd epoch that never returns to even) raises after this many "
        "seconds with the stale shard named, instead of spinning "
        "forever.",
    ),
    EnvVar(
        "DBSCAN_SERVE_SHED_P99_MS", "float", 0.0,
        "Declared p99 latency bound of the serving router's load "
        "shedder: while the query p99 — the LIVE sliding-window "
        "figure (obs/live.py) when the live plane is on, the rolling "
        "in-router sample otherwise — exceeds this many milliseconds, "
        "the router admits only batches whose serve.query family-model "
        "price fits the proportionally shrunk admission headroom and "
        "sheds the rest (serve.router.shed). The same bound shrinks "
        "the tenancy AdmissionController's effective headroom. 0 (the "
        "default) disables shedding.",
    ),
    EnvVar(
        "DBSCAN_OBS_LIVE", "bool", True,
        "Live telemetry plane (obs/live.py): mergeable log-bucketed "
        "sliding-window latency histograms + windowed counter rates "
        "feeding health(), the expo file, the live console, and the "
        "SLO engine. 0 restores the strict no-op hook path (shedding "
        "then falls back to the router's rolling sample).",
    ),
    EnvVar(
        "DBSCAN_OBS_WINDOW_S", "float", 60.0,
        "Width in seconds of the live sliding windows (the SLO "
        "engine's FAST burn window; the slow window is 6x this). "
        "Memory is bounded per series: DBSCAN_OBS_SLICES slices of "
        "128 int64 buckets.",
    ),
    EnvVar(
        "DBSCAN_OBS_SLICES", "int", 12,
        "Time slices per live sliding window (floor 2): observations "
        "land in epoch-stamped slices of WINDOW_S/SLICES seconds, so "
        "expiry is O(1) zeroing on touch — no timestamps retained.",
    ),
    EnvVar(
        "DBSCAN_OBS_EXPO", "str", None,
        "Prometheus-style text exposition path: when set, the live "
        "plane atomically (tmp+rename) rewrites this file with the "
        "current window snapshot on health() polls, at most once per "
        "DBSCAN_OBS_EXPO_PERIOD_S; python -m dbscan_tpu.obs.live "
        "tails it as a top-style console.",
    ),
    EnvVar(
        "DBSCAN_OBS_EXPO_PERIOD_S", "float", 2.0,
        "Minimum seconds between exposition-file rewrites (write "
        "throttle for hot health()/record paths).",
    ),
    EnvVar(
        "DBSCAN_SLO_QUERY_P99_MS", "float", 0.0,
        "Query-latency SLO bound: a serve query slower than this many "
        "milliseconds is a bad event for the query_p99 SLO "
        "(objective: DBSCAN_SLO_OBJECTIVE good fraction). 0 (the "
        "default) leaves the SLO undeclared.",
    ),
    EnvVar(
        "DBSCAN_SLO_OBJECTIVE", "float", 0.99,
        "Good-event objective shared by the declared SLOs (error "
        "budget = 1 - objective; burn rate = bad fraction / budget).",
    ),
    EnvVar(
        "DBSCAN_SLO_SHED_FRAC", "float", 0.0,
        "Shed-fraction SLO bound: the windowed shed fraction "
        "(shed / (shed + routed)) this fleet may sustain before the "
        "shed_frac SLO burns (burn = windowed frac / bound). 0 (the "
        "default) leaves the SLO undeclared.",
    ),
    EnvVar(
        "DBSCAN_SLO_STALENESS_S", "float", 0.0,
        "Epoch-staleness SLO bound: seconds since the last snapshot/"
        "cut publish before the staleness SLO burns (burn = staleness "
        "/ bound). 0 (the default) leaves the SLO undeclared.",
    ),
    EnvVar(
        "DBSCAN_SLO_FAULT_RATE", "float", 0.0,
        "Fault-rate SLO bound: windowed supervised-failure events per "
        "second this fleet may sustain before the fault_rate SLO "
        "burns (burn = windowed rate / bound). 0 (the default) leaves "
        "the SLO undeclared.",
    ),
    EnvVar(
        "DBSCAN_SLO_BURN_PAGE", "float", 8.0,
        "Page-severity burn-rate threshold: when an SLO's fast AND "
        "slow window burn both exceed this, a slo.burn event fires at "
        "page severity and the flight recorder dumps on demand.",
    ),
    EnvVar(
        "DBSCAN_SLO_BURN_TICKET", "float", 2.0,
        "Ticket-severity burn-rate threshold (fires slo.burn at "
        "ticket severity; also the recovery line an alerting SLO must "
        "drop back under for slo.recover).",
    ),
    EnvVar(
        "DBSCAN_SLO_EVAL_PERIOD_S", "float", 1.0,
        "Minimum seconds between SLO engine evaluations (piggybacked "
        "on the serving record/publish paths — no dedicated thread).",
    ),
    EnvVar(
        "DBSCAN_EMBED_SAMPLE_FRAC", "float", 0.0,
        "Opt-in subsampled-edge mode of the embed engine "
        "(dbscan_tpu/embed): each candidate edge survives a "
        "deterministic symmetric coin with this probability and the "
        "core threshold scales to match (SNG-DBSCAN style); 0 (the "
        "default) runs the exact path. The accuracy contract — "
        "reported ARI vs the exact path, declared floor, regression "
        "gate — is in PARITY.md.",
    ),
    EnvVar(
        "DBSCAN_EMBED_BITS", "int", 16,
        "Hyperplanes per SRP hash table of the embed engine's LSH "
        "front-end; the primary table's planes drive the exact "
        "boundary-spill binning, so more bits = deeper available "
        "splits before the spill-tree fallback.",
    ),
    EnvVar(
        "DBSCAN_EMBED_TABLES", "int", 4,
        "SRP hash tables computed by the embed.hash dispatch; tables "
        "past the first feed the multi-table candidate diagnostics "
        "(recall vs the Goemans-Williamson bound), not the exact "
        "partitioner.",
    ),
    EnvVar(
        "DBSCAN_EMBED_SHARD", "bool", True,
        "Shard embed_dbscan over a passed device mesh: the hash "
        "dispatch runs row-sharded, each chip owns a contiguous band "
        "of buckets (instance-balanced), bucket dispatches run "
        "chip-local, and the finalize routes through the collective "
        "halo-merge. Off = single-device dispatch even when a mesh is "
        "passed (labels byte-identical either way, PARITY.md 'Sharded "
        "embed contract').",
    ),
    EnvVar(
        "DBSCAN_EMBED_QUANTIZER", "str", "srp",
        "Embed binning front-end: 'srp' (hyperplane boundary-spill "
        "over the primary LSH table) or 'ivf' (IVF-style coarse "
        "quantizer — the spill tree's farthest-point/Lloyd kernels "
        "with k-means cells replacing SRP planes; exact r_c+halo "
        "bands, ARI-gated like the sampled mode).",
    ),
    EnvVar(
        "DBSCAN_EMBED_IVF_CELLS", "int", 0,
        "Coarse-quantizer cell count of the embed engine's 'ivf' "
        "front-end (ladder-quantized on device); 0 (the default) "
        "auto-sizes to ~2x the payload/maxpp ratio.",
    ),
    EnvVar(
        "DBSCAN_EMBED_BAND", "int", 0,
        "Buckets per bucket-band chunk of an embed campaign "
        "(checkpoint_dir banking grain: one band = one durable "
        "restart point / one frontier-leg lease unit); 0 (the "
        "default) auto-sizes to ~8 bands per run.",
    ),
    EnvVar(
        "DBSCAN_DENSITY_CHUNK", "int", 512,
        "Packing-window chunk rows per density.core dispatch of the "
        "density engine (dbscan_tpu/density): each chunk is one "
        "[chunk, n_pad] core-distance slab, so this prices the "
        "per-dispatch HBM slab against dispatch count (clamped to the "
        "padded payload).",
    ),
    EnvVar(
        "DBSCAN_DENSITY_ORACLE_MAX", "int", 100000,
        "Largest point count the density engine will degrade whole to "
        "the numpy host HDBSCAN*/OPTICS oracle after a persistent "
        "density_boruvka fault; larger payloads re-raise instead of "
        "running an O(n^2) host MST.",
    ),
    EnvVar(
        "DBSCAN_DENSITY_AUTO_SAMPLE", "int", 4096,
        "Subsample cap of the eps='auto' k-distance probe (plain "
        "DBSCAN): an evenly-strided deterministic sample of at most "
        "this many points feeds the per-strip knee selection.",
    ),
    EnvVar(
        "DBSCAN_DENSITY_AUTO_PARTS", "int", 8,
        "Coordinate strips the eps='auto' probe splits its subsample "
        "into (the per-partition proxy); eps is the median of the "
        "per-strip k-distance knees.",
    ),
    EnvVar(
        "DBSCAN_FAULT_SPEC", "str", "",
        "Deterministic fault-injection spec, semicolon-separated "
        "site#ordinal:KIND[*count] clauses (faults.parse_fault_spec).",
    ),
    EnvVar(
        "DBSCAN_FAULT_RETRIES", "int", 3,
        "Override of DBSCANConfig.fault_max_retries for every "
        "supervised dispatch site.",
    ),
    EnvVar(
        "DBSCAN_FAULT_BACKOFF_S", "float", 0.05,
        "Override of DBSCANConfig.fault_backoff_base_s (exponential "
        "backoff base seconds).",
    ),
    EnvVar(
        "DBSCAN_FAULT_SEED", "int", 0,
        "Seed for the deterministic backoff jitter.",
    ),
    EnvVar(
        "DBSCAN_FAULT_SYNC", "bool", False,
        "Force supervised dispatches to block on their outputs so async "
        "device faults attribute to the dispatch site.",
    ),
    EnvVar(
        "DBSCAN_FAULTCHECK", "bool", False,
        "graftfault runtime cross-check (lint/faultcheck.py): every "
        "faults.supervised window fingerprints the shared-state "
        "mutations actually observed (via the tsan site hooks) and "
        "asserts containment in the static effect model "
        "(lint/effects.py); violations surface in "
        "faultcheck.report()/assert_clean().",
    ),
    EnvVar(
        "DBSCAN_FAULTCHECK_REPORT", "str", None,
        "With DBSCAN_FAULTCHECK=1: path receiving the cross-check's "
        "JSON report at process exit (how the tier-1 rerun of the "
        "fault/pipeline suites is asserted violation-free from outside "
        "the process).",
    ),
    EnvVar(
        "DBSCAN_SHAPECHECK", "bool", False,
        "graftshape runtime cross-check (lint/shapecheck.py): every "
        "tracked dispatch validates its concrete arg shapes/dtypes "
        "against the static symbolic model (lint/shapes.py "
        "FAMILY_MODELS) and, where allocator stats exist, its HBM "
        "growth against the static footprint prediction; violations "
        "surface in shapecheck.report()/assert_clean().",
    ),
    EnvVar(
        "DBSCAN_SHAPECHECK_REPORT", "str", None,
        "With DBSCAN_SHAPECHECK=1: path receiving the cross-check's "
        "JSON report at process exit (how the tier-1 rerun of the "
        "distributed/streaming suites is asserted violation-free from "
        "outside the process).",
    ),
    EnvVar(
        "DBSCAN_TSAN", "bool", False,
        "graftcheck runtime thread sanitizer (lint/tsan.py): registered "
        "locks and shared-state sites record cross-thread access "
        "locksets and lock-acquisition order; races/inversions surface "
        "in tsan.report()/assert_clean().",
    ),
    EnvVar(
        "DBSCAN_TSAN_REPORT", "str", None,
        "With DBSCAN_TSAN=1: path receiving the sanitizer's JSON report "
        "at process exit (how the tier-1 rerun of the pipeline/fault "
        "suites is asserted race-free from outside the process).",
    ),
)


def env(name: str, default: object = None):
    """Typed read of a declared ``DBSCAN_*`` environment variable.

    ``default`` (when not None) overrides the table default for callers
    whose fallback is contextual (e.g. a DBSCANConfig field). Raises
    KeyError on an undeclared name — adding the table row (and its
    PARITY.md line) IS the registration step the linter enforces.

    Precedence: a set (non-empty) environment variable wins; otherwise
    an applied :class:`Profile` overlay (``apply_profile``) supplies
    the value; otherwise the default. Profiles are tuned DEFAULTS, so
    an operator's explicit export always overrides a committed profile.
    """
    spec = ENV_VARS[name]
    raw = os.environ.get(name)
    if default is None:
        default = spec.default
    if raw is None or raw.strip() == "":
        # exported-but-empty means "use the default", matching the
        # pre-registry call sites (an empty DBSCAN_TPU_NATIVE must not
        # silently disable the native runtime)
        if name in _profile_overlay:
            return _profile_overlay[name]
        return default
    if spec.kind == "bool":
        return raw.strip().lower() in _TRUE
    try:
        if spec.kind == "int":
            return int(raw)
        if spec.kind == "float":
            return float(raw)
    except ValueError as e:
        raise ValueError(
            f"{name}={raw!r} is not a valid {spec.kind}: {e}"
        ) from None
    return raw


# --- tunable-knob registry + profiles ---------------------------------
#
# The autotuner (``python -m dbscan_tpu.bench --tune``) searches ONLY
# the knobs declared here — typed ranges/steps next to the ENV_VARS
# rows they tune, so the search space is as pinned as the registry
# itself. The linter's ``env-tunable-undeclared`` rule rejects any
# Tunable whose name is missing from ENV_VARS, whose kind disagrees
# with the declared row, or whose range is empty: declaring BOTH rows
# is the registration step.


@dataclasses.dataclass(frozen=True)
class Tunable:
    """One searchable knob: ``choices`` is the full ordered candidate
    set (ints for slot/ladder budgets — powers of two so jit shapes
    recur; strings for mode knobs). ``kind`` must match the ENV_VARS
    row."""

    name: str
    kind: str
    choices: tuple
    doc: str


def _pow2(lo: int, hi: int) -> tuple:
    return tuple(1 << k for k in range(lo, hi + 1))


TUNABLES = (
    Tunable(
        "DBSCAN_GROUP_SLOTS", "int", _pow2(20, 26),
        "dispatch-group padded-slot budget (pack/compute overlap grain)",
    ),
    Tunable(
        "DBSCAN_COMPACT_CHUNK_SLOTS", "int", _pow2(20, 26),
        "compact p1 chunk grain (flush/pull frequency vs residency)",
    ),
    Tunable(
        "DBSCAN_INFLIGHT_SLOTS", "int", _pow2(24, 27),
        "dispatched-but-unretired slot window (backpressure depth)",
    ),
    Tunable(
        "DBSCAN_PULL_INFLIGHT", "int", (1, 2, 3, 4),
        "pull-pipeline depth (chunks with D2H issued ahead)",
    ),
    Tunable(
        "DBSCAN_PULL_INFLIGHT_BYTES", "int", _pow2(28, 30),
        "byte budget across in-flight pipelined pulls",
    ),
    Tunable(
        "DBSCAN_CELLCC_DEVICE_SLOTS", "int", _pow2(26, 28),
        "device cellcc finalize staged-residency ladder cap",
    ),
    Tunable(
        "DBSCAN_SPILL_LEVEL_SLOTS", "int", _pow2(26, 28),
        "spill-tree level-dispatch element ladder cap",
    ),
    Tunable(
        "DBSCAN_PROP_UNIONFIND", "str", ("auto", "1", "0"),
        "propagation mode: single-pass union-find vs iterated",
    ),
    Tunable(
        "DBSCAN_CELLCC_FUSED", "str", ("auto", "1", "0"),
        "fused Pallas unpack+fold+propagate vs split unpack/cc",
    ),
    Tunable(
        "DBSCAN_EMBED_QUANTIZER", "str", ("srp", "ivf"),
        "embed binning front-end: SRP hyperplanes vs IVF k-means cells",
    ),
    Tunable(
        "DBSCAN_EMBED_IVF_CELLS", "int", (0, 16, 32, 64, 128),
        "IVF coarse-quantizer cell count (0 = auto ~2x n/maxpp)",
    ),
)


#: applied-profile overlay read by :func:`env` when the variable is
#: unset: name -> typed value. One profile at a time; module-global on
#: purpose (a profile is process-wide tuning state, like the env).
_profile_overlay: dict = {}


@dataclasses.dataclass(frozen=True)
class Profile:
    """One tuned knob profile: the per-(backend, workload) winner the
    autotuner commits to ``bench/profiles/`` and ``cli.py --profile`` /
    ``bench.py`` (BENCH_PROFILE) load. ``values`` maps declared knob
    names to typed values; ``meta`` carries the tuning provenance
    (tuned_vs_default_speedup, walls, rev) verbatim."""

    backend: str
    workload: str
    values: dict
    meta: dict = dataclasses.field(default_factory=dict)

    def validate(self) -> "Profile":
        declared = {t.name: t for t in TUNABLES}
        for name, value in self.values.items():
            t = declared.get(name)
            if t is None:
                raise ValueError(
                    f"profile knob {name!r} is not a declared Tunable "
                    "(config.TUNABLES) — the search space and the "
                    "loadable profile surface are the same registry"
                )
            if value not in t.choices:
                raise ValueError(
                    f"profile value {name}={value!r} outside the "
                    f"declared choices {t.choices}"
                )
        return self

    def apply(self) -> None:
        """Install as the process overlay (tuned defaults: a set env
        var still wins, see :func:`env`)."""
        self.validate()
        _profile_overlay.clear()
        _profile_overlay.update(self.values)

    def save(self, path: str) -> None:
        import json

        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "backend": self.backend,
                    "workload": self.workload,
                    "values": self.values,
                    "meta": self.meta,
                },
                f,
                indent=1,
                sort_keys=True,
            )
            f.write("\n")
        os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> "Profile":
        import json

        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
        return Profile(
            backend=str(obj.get("backend", "unknown")),
            workload=str(obj.get("workload", "unknown")),
            values=dict(obj.get("values") or {}),
            meta=dict(obj.get("meta") or {}),
        ).validate()


def clear_profile() -> None:
    """Drop the applied overlay (tests / between tuner candidates)."""
    _profile_overlay.clear()


def active_profile_values() -> dict:
    """Snapshot of the applied overlay (empty when no profile)."""
    return dict(_profile_overlay)


def parity_env_table() -> str:
    """The PARITY.md environment-variable table, generated from
    :data:`ENV_VARS` (``python -m dbscan_tpu.lint --env-table``
    prints it)."""
    lines = [
        "| Variable | Type | Default | Effect |",
        "|---|---|---|---|",
    ]
    for name in sorted(ENV_VARS):
        v = ENV_VARS[name]
        if v.default is None:
            default = "unset"
        elif v.kind == "bool":
            default = "on" if v.default else "off"
        elif (
            v.kind == "int"
            and v.default >= 1 << 16
            and v.default & (v.default - 1) == 0
        ):
            default = f"2^{v.default.bit_length() - 1}"
        else:
            default = str(v.default)
        lines.append(f"| `{name}` | {v.kind} | {default} | {v.doc} |")
    return "\n".join(lines)

