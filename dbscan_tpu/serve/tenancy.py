"""Multi-tenant batched small-job dispatch: pad-and-stack tenancy.

"Millions of users" is not one 100M-point campaign — it is thousands
of SMALL independent clustering jobs (one user's session, one
document's mentions, one store's day of orders). Dispatching each as
its own ``train`` pays a full driver walk and, worse, a fresh jit
signature per job size. This module batches them the way the rest of
the package batches partitions: pad every job's point axis up the
recurring ladder, stack up to ``DBSCAN_SERVE_BATCH_JOBS`` jobs into
one ``[J, S, D]`` tensor, and run ONE vmapped kernel dispatch
(``serve.jobs`` family) whose per-job eps/min_points ride as traced
``[J]`` arrays — so a fully mixed tenant stream (different sizes,
different eps, different density thresholds) compiles ZERO new kernels
at steady state (the ladder/ratchet discipline of
parallel/binning.py, pinned by tests/test_serve.py).

Admission control: before anything is stacked, each job — and each
candidate batch — is PRICED with graftshape's declared symbolic model
(``lint/shapes.FAMILY_MODELS["serve.jobs"]``: exact input bytes plus
the [S, S] per-job adjacency temps) against
``DBSCAN_SERVE_HEADROOM_BYTES``. A batch whose stacked price would
breach the headroom is split (the remainder queues for the next
dispatch, ``serve.admit_splits``); a single job that alone breaches
it — or exceeds ``DBSCAN_SERVE_JOB_SLOTS`` points — is REJECTED at
submit (:class:`AdmissionRejected`, ``serve.jobs_rejected``), because
no schedule can make it fit. This is the graftshape HBM contract run
FORWARD: predict, then dispatch, instead of dispatch-and-hope.
Under latency pressure the gate also TIGHTENS: with
``DBSCAN_SERVE_SHED_P99_MS`` declared and the live windowed query p99
(obs/live.py — the router's shed signal) over that bound, the
effective headroom shrinks by ``bound / p99``, so flushes split
smaller and queue instead of stacking wider into an overloaded fleet.

Results are exact: each job's labels equal a standalone
``ops.local_dbscan`` run of that job (same adjacency algebra, same
seed-index components, 1-based per-job numbering via
``labels.seed_to_local_ids``) — pinned against the per-job oracle.
"""

from __future__ import annotations

import functools
from collections import deque
from typing import List, NamedTuple, Optional

import numpy as np

from dbscan_tpu import config, obs
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.obs import live as obs_live
from dbscan_tpu.ops import distance as dist_mod
from dbscan_tpu.ops.labels import seed_to_local_ids
from dbscan_tpu.parallel import pipeline as pipe_mod
from dbscan_tpu.parallel.binning import _ladder_width, _ratchet

JOBS_FAMILY = "serve.jobs"

#: job-count ladder quantum (8 keeps J rungs sparse without padding a
#: 3-job flush to 64) and point-axis quantum (sublane-friendly)
_J_PAD = 8
_S_PAD = 128


class AdmissionRejected(ValueError):
    """A job the admission controller provably cannot schedule: its
    HBM price alone breaches the headroom, or it exceeds the per-job
    point cap. Carries the pricing so the tenant can be told why."""

    def __init__(self, reason: str, predicted_bytes: int, headroom: int):
        super().__init__(
            f"{reason} (predicted {predicted_bytes} B vs headroom "
            f"{headroom} B)"
        )
        self.reason = reason
        self.predicted_bytes = int(predicted_bytes)
        self.headroom = int(headroom)


class JobResult(NamedTuple):
    job_id: int
    clusters: np.ndarray  # [n] int32 1-based per-job cluster ids; 0 noise
    flags: np.ndarray  # [n] int8 Core/Border/Noise
    n_clusters: int


class AdmissionController:
    """Prices candidate ``serve.jobs`` dispatch shapes with the
    declared graftshape family model and gates them on the configured
    HBM headroom."""

    def __init__(self, headroom_bytes: Optional[int] = None):
        self.headroom = int(
            headroom_bytes
            if headroom_bytes is not None
            else config.env("DBSCAN_SERVE_HEADROOM_BYTES")
        )

    def price(self, jobs: int, slots: int, d: int) -> int:
        """Predicted dispatch bytes for a padded [jobs, slots, d]
        batch: the family model's exact input bytes + symbolic temp/
        output overhead, evaluated at the candidate shape — the same
        arithmetic the lint-time gate and the DBSCAN_SHAPECHECK=1
        runtime cross-check apply to the dispatch after the fact."""
        from dbscan_tpu.lint.shapes import FAMILY_MODELS

        model = FAMILY_MODELS[JOBS_FAMILY]
        binding = {"J": int(jobs), "S": int(slots), "D": int(d)}
        expr = model.input_expr() + model.overhead
        return int(expr.substitute(binding).evaluate(binding))

    def effective_headroom(self) -> int:
        """The byte budget :meth:`admit` actually gates on. Normally
        the configured headroom; under latency pressure — the LIVE
        windowed query p99 (obs/live.py, the same windowed figure the
        router sheds on) over the declared
        ``DBSCAN_SERVE_SHED_P99_MS`` bound — it shrinks
        proportionally (``headroom * bound / p99``), so batch flushes
        split smaller and queue work instead of stacking wider while
        the fleet is already missing its latency objective. Reads one
        windowed quantile; the full headroom is restored as soon as
        the window drains back under the bound."""
        bound = float(config.env("DBSCAN_SERVE_SHED_P99_MS"))
        if bound > 0:
            p99 = obs_live.quantile("serve.query_ms", 0.99)
            if p99 is not None and p99 > bound:
                return max(1, int(self.headroom * (bound / p99)))
        return self.headroom

    def admit(self, jobs: int, slots: int, d: int) -> bool:
        return self.price(jobs, slots, d) <= self.effective_headroom()


def _jobs_builder(engine: str, metric: str):
    # propagation mode resolved BEFORE the cache key (ops/propagation.py
    # contract for cached builders): an in-process knob flip re-traces
    from dbscan_tpu.ops.propagation import prop_mode

    return _jobs_builder_cached(engine, metric, prop_mode())


@functools.lru_cache(maxsize=None)
def _jobs_builder_cached(engine: str, metric: str, mode: str):
    """One compiled pad-and-stack kernel per (engine, metric,
    propagation mode): a vmap of the shared adjacency->labels tail over
    the job axis, with per-job eps / min_points as traced scalars."""
    import jax
    import jax.numpy as jnp

    from dbscan_tpu.ops.local_dbscan import cluster_from_adjacency

    def one(pts, mask, eps, min_points):
        m = dist_mod.get_metric(metric)
        measure = m.pairwise(pts, pts)
        thr = m.threshold(jnp.asarray(eps, measure.dtype))
        adj = (measure <= thr) & mask[None, :] & mask[:, None]
        adj = adj | (jnp.eye(pts.shape[0], dtype=bool) & mask[:, None])
        res = cluster_from_adjacency(adj, mask, min_points, engine, mode)
        return res.seed_labels, res.flags

    return jax.jit(jax.vmap(one))


class _Pending(NamedTuple):
    job_id: int
    pts: np.ndarray  # [n, D] float64
    eps: float
    min_points: int
    slots: int  # this job's own ladder rung


class JobBatcher:
    """Pad-and-stack batcher for small independent clustering jobs.

    One batcher per (engine, metric, D) tenant class; eps/min_points
    vary freely per job. ``submit`` applies per-job admission and
    queues; ``flush`` forms admitted batches in submission order and
    dispatches each as one ``serve.jobs`` kernel call, returning
    results in submission order.
    """

    def __init__(
        self,
        *,
        engine: str = "archery",
        metric: str = "euclidean",
        admission: Optional[AdmissionController] = None,
        max_job_points: Optional[int] = None,
        max_jobs: Optional[int] = None,
        shape_floors: Optional[dict] = None,
    ):
        if engine not in ("naive", "archery"):
            raise ValueError(f"unknown engine {engine!r}")
        self.engine = engine
        self.metric = metric
        self.admission = admission or AdmissionController()
        self.max_job_points = int(
            max_job_points
            if max_job_points is not None
            else config.env("DBSCAN_SERVE_JOB_SLOTS")
        )
        self.max_jobs = max(
            1,
            int(
                max_jobs
                if max_jobs is not None
                else config.env("DBSCAN_SERVE_BATCH_JOBS")
            ),
        )
        self._floors = shape_floors if shape_floors is not None else {}
        self._pending: deque = deque()
        self._next_id = 0
        self._d: Optional[int] = None

    def submit(self, points: np.ndarray, eps: float, min_points: int) -> int:
        """Admit and queue one job; returns its job id. Raises
        :class:`AdmissionRejected` when the job provably cannot be
        scheduled (too many points, or its single-job HBM price alone
        breaches the headroom)."""
        pts = np.asarray(points, dtype=np.float64)
        if pts.ndim != 2 or pts.shape[1] < 2:
            raise ValueError(f"job points must be [n, >=2], got {pts.shape}")
        if self._d is None:
            self._d = int(pts.shape[1])
        elif int(pts.shape[1]) != self._d:
            raise ValueError(
                f"job has D={pts.shape[1]}; this batcher's tenant class "
                f"is D={self._d}"
            )
        if not eps > 0 or min_points < 1:
            raise ValueError(
                f"bad job parameters eps={eps} min_points={min_points}"
            )
        n = len(pts)
        headroom = self.admission.headroom
        if n > self.max_job_points:
            obs.count("serve.jobs_rejected")
            obs.event(
                "serve.admit_reject",
                reason="oversized",
                points=int(n),
                headroom=int(headroom),
            )
            raise AdmissionRejected(
                f"job of {n} points exceeds DBSCAN_SERVE_JOB_SLOTS="
                f"{self.max_job_points}",
                0,
                headroom,
            )
        slots = _ladder_width(max(n, 1), _S_PAD)
        single = self.admission.price(_ladder_width(1, _J_PAD), slots, self._d)
        if single > headroom:
            obs.count("serve.jobs_rejected")
            obs.event(
                "serve.admit_reject",
                reason="hbm_price",
                predicted_bytes=int(single),
                headroom=int(headroom),
            )
            raise AdmissionRejected(
                f"single job of {n} points cannot fit the admission "
                "headroom", single, headroom,
            )
        job_id = self._next_id
        self._next_id += 1
        self._pending.append(
            _Pending(job_id, pts, float(eps), int(min_points), slots)
        )
        return job_id

    @property
    def pending(self) -> int:
        return len(self._pending)

    def _preview_shape(self, n_jobs: int, slots: int) -> tuple:
        """The (jp, sp) shape a batch of ``n_jobs`` jobs with max job
        rung ``slots`` would ACTUALLY dispatch at — ladder rungs lifted
        to the current ratchet floors, without mutating them. Admission
        must price THIS shape, not the raw candidate: the floors are
        monotone across flushes, so a tiny batch after a wide one pads
        up to the combined floor (a pre-ratchet price could admit a
        shape the dispatch then inflates past the headroom)."""
        sp_cap = _ladder_width(self.max_job_points, _S_PAD)
        jp_cap = _ladder_width(self.max_jobs, _J_PAD)
        sp = min(
            sp_cap,
            max(slots, int(self._floors.get("serve_jobs_s", 0))),
        )
        jp = min(
            jp_cap,
            max(
                _ladder_width(n_jobs, _J_PAD),
                int(self._floors.get("serve_jobs_j", 0)),
            ),
        )
        return jp, sp

    def flush(self) -> List[JobResult]:
        """Dispatch every queued job; returns results in submission
        order. Batches are cut at ``max_jobs`` or where the stacked
        admission price — of the POST-ratchet padded shape — would
        breach the headroom (``serve.admit_splits`` counts the splits,
        the 'queues jobs' half of reject-or-queue).
        """
        results: List[JobResult] = []
        while self._pending:
            batch: List[_Pending] = [self._pending.popleft()]
            slots = batch[0].slots
            while self._pending and len(batch) < self.max_jobs:
                nxt = self._pending[0]
                cand_slots = max(slots, nxt.slots)
                jp, sp = self._preview_shape(len(batch) + 1, cand_slots)
                if not self.admission.admit(jp, sp, self._d):
                    obs.count("serve.admit_splits")
                    break
                batch.append(self._pending.popleft())
                slots = cand_slots
            results.extend(self._dispatch(batch, slots))
        return results

    def _dispatch(self, batch: List[_Pending], slots: int) -> List[JobResult]:
        d = self._d
        # ratchet both padded axes so a mixed job stream re-uses exact
        # signatures after warm-up (the zero-recompile pin) — UNLESS
        # the ratcheted shape would breach the admission headroom
        # (floors inflated by an earlier wide batch): then this batch
        # dispatches at its own un-ratcheted rungs, paying a possible
        # recompile instead of un-admitted HBM. The headroom is the
        # hard contract; the ratchet is best-effort.
        jp, sp = self._preview_shape(len(batch), slots)
        if self.admission.admit(jp, sp, d):
            sp = _ratchet(
                self._floors, "serve_jobs_s", sp,
                cap=_ladder_width(self.max_job_points, _S_PAD),
            )
            jp = _ratchet(
                self._floors, "serve_jobs_j", jp,
                cap=_ladder_width(self.max_jobs, _J_PAD),
            )
        else:
            sp = min(slots, _ladder_width(self.max_job_points, _S_PAD))
            jp = min(
                _ladder_width(len(batch), _J_PAD),
                _ladder_width(self.max_jobs, _J_PAD),
            )
        pts = np.zeros((jp, sp, d), np.float64)
        mask = np.zeros((jp, sp), bool)
        eps = np.zeros(jp, np.float64)
        mp = np.ones(jp, np.int32)
        for i, job in enumerate(batch):
            n = len(job.pts)
            pts[i, :n] = job.pts
            mask[i, :n] = True
            eps[i] = job.eps
            mp[i] = job.min_points
        with obs.span(
            "serve.job_batch",
            jobs=int(len(batch)),
            padded_jobs=int(jp),
            slots=int(sp),
        ):
            fn = _jobs_builder(self.engine, self.metric)
            seeds_d, flags_d = obs_compile.tracked_call(
                JOBS_FAMILY, fn, pts, mask, eps, mp
            )

            def work():
                return np.asarray(seeds_d), np.asarray(flags_d)

            eng = pipe_mod.get_engine()
            if eng is None:
                seeds, flags = work()
            else:

                def on_start():
                    for a in (seeds_d, flags_d):
                        start = getattr(a, "copy_to_host_async", None)
                        if start is not None:
                            start()

                job_h = eng.submit(
                    work,
                    on_start=on_start,
                    bytes_hint=int(jp * sp * 5),
                    label=f"serve.jobs x{len(batch)}",
                )
                seeds, flags = eng.settle(job_h, serial_fallback=work)
        out = []
        for i, job in enumerate(batch):
            n = len(job.pts)
            clusters = seed_to_local_ids(seeds[i, :n])
            out.append(
                JobResult(
                    job_id=job.job_id,
                    clusters=clusters,
                    flags=np.asarray(flags[i, :n]),
                    n_clusters=int(clusters.max()) if n else 0,
                )
            )
        obs.count("serve.job_batches")
        obs.count("serve.jobs_done", len(batch))
        return out
