"""Online point->cluster queries against the resident stream skeleton.

The streaming engine (dbscan_tpu/streaming.py) answers "cluster this
batch" but not the serving question — "which cluster is THIS point in,
right now?" — without re-running a whole micro-batch update. This
module is the thin read path: one batched device dispatch per query
batch against the service's published snapshot (window core points +
their resolved stream ids), shaped so a steady query stream compiles
ZERO new kernels.

Query semantics (the serving contract, PARITY.md):

- a query point's neighbors are the snapshot's skeleton core points
  within ``eps`` (the same subsampled-probe shape SNG-DBSCAN's
  similarity queries take against a retained structure,
  arXiv:2006.06743 — the skeleton IS the density summary the stream
  retains);
- ``gid`` = the MINIMUM resolved stream id among those neighbors
  ("elder id wins", the stream's own tie rule), 0 when it has none
  (noise/unseen space);
- ``core_flag`` = whether the point's self-inclusive neighbor count
  within the skeleton reaches ``min_points`` — would this point be a
  core point of the resident density structure. Border points of the
  live stream report ``gid > 0`` with ``core_flag`` False.

Queries are read-only: they never densify the stream (a query is not
an ingest), and they are answered against exactly one published epoch
(serve/service.py's seqlock), never a half-merged update.

Shape discipline: the skeleton is padded ONCE per published snapshot
(:func:`pad_skeleton`, ladder widths + the streaming shape ratchet),
and each query batch pads its own [Q] axis the same way — after
warm-up every dispatch reuses an exact jit signature. Batches larger
than ``DBSCAN_SERVE_QUERY_SLOTS`` split into consecutive dispatches,
bounding the [Q, K] measure working set. Results come back through
the process PullEngine (parallel/pipeline.py) as one thin label pull
per batch.

Fault surface: when ``DBSCAN_FAULT_SPEC`` names the ``serve`` site,
each query dispatch consumes one ``serve`` ordinal under
:func:`faults.supervised` with the numpy host oracle
(:func:`query_host`) as the degradation path — same opt-in discipline
as the ``pull`` site (ordinals are consumed on reader threads, so an
unconditional consume would interleave nondeterministically with the
dispatch sites' streams).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.ops import distance as dist_mod
from dbscan_tpu.parallel import pipeline as pipe_mod
from dbscan_tpu.parallel.binning import _ladder_width, _ratchet

QUERY_FAMILY = "serve.query"

#: min-fold identity for "no adjacent skeleton id" (ids are positive)
_NO_SID = np.int32(np.iinfo(np.int32).max)

#: ladder quantum for the query/skeleton axes (sublane-friendly, same
#: spirit as the bucket_multiple default)
_PAD = 128


@functools.lru_cache(maxsize=None)
def _query_builder(min_points: int, metric: str):
    """One compiled query kernel per (min_points, metric) — the
    driver's compiled-builder idiom, so ``tracked_call`` sees a real
    jit object (compile accounting + shapecheck + devtime hooks)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(qpts, spts, sids, eps):
        m = dist_mod.get_metric(metric)
        measure = m.pairwise(qpts, spts)
        thr = m.threshold(jnp.asarray(eps, measure.dtype))
        valid = sids > 0  # padding rows carry sid 0
        adj = (measure <= thr) & valid[None, :]
        counts = jnp.sum(adj, axis=1, dtype=jnp.int32)
        core = (counts + 1) >= jnp.int32(min_points)  # self-inclusive
        gid = jnp.min(
            jnp.where(adj, sids[None, :], jnp.int32(_NO_SID)), axis=1
        )
        gid = jnp.where(gid == jnp.int32(_NO_SID), jnp.int32(0), gid)
        return gid, core.astype(jnp.int8), counts

    return fn


class QueryAnswer(NamedTuple):
    """One answered query batch, aligned with the input row order."""

    gids: np.ndarray  # [N] int64 resolved stream ids; 0 = noise
    core: np.ndarray  # [N] int8 would-be-core flag vs the skeleton
    counts: np.ndarray  # [N] int32 skeleton neighbors (self exclusive)


def pad_skeleton(
    spts: np.ndarray,
    sids: np.ndarray,
    floors: Optional[dict] = None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Ladder-pad one snapshot's skeleton (points + resolved ids) for
    the query dispatches: returns ``(spts_padded, sids_padded_i32,
    k_valid)``. Done once per PUBLISHED snapshot (the write side), so
    queries only ever pad their own [Q] axis. Padding rows carry sid 0
    (excluded in-kernel) and zero coordinates. Ids are narrowed to
    int32 for the device (the stream allocates ids densely from 1;
    the service asserts the stream stays below 2**31)."""
    spts = np.asarray(spts, np.float64)
    sids = np.asarray(sids)
    k = len(spts)
    if sids.size and int(sids.max()) >= int(_NO_SID):
        raise ValueError(
            "stream ids exceeded int32 range; the query kernel's "
            "device ids are i32"
        )
    kp = _ratchet(floors, "serve_k", _ladder_width(max(k, 1), _PAD))
    d = spts.shape[1] if spts.ndim == 2 else 2
    out_p = np.zeros((kp, d), np.float64)
    out_i = np.zeros(kp, np.int32)
    if k:
        out_p[:k] = spts
        out_i[:k] = sids.astype(np.int32)
    return out_p, out_i, k


def query_host(
    qpts: np.ndarray,
    spts: np.ndarray,
    sids: np.ndarray,
    eps: float,
    min_points: int,
    metric: str,
) -> QueryAnswer:
    """Host-path oracle (numpy, same algebra): the degradation target
    of a persistently-faulting query dispatch, and the reference the
    device path is pinned against."""
    qpts = np.asarray(qpts, np.float64)
    spts = np.asarray(spts, np.float64)
    sids = np.asarray(sids, np.int64)
    n = len(qpts)
    gids = np.zeros(n, np.int64)
    core = np.zeros(n, np.int8)
    counts = np.zeros(n, np.int32)
    valid = sids > 0
    if n == 0:
        return QueryAnswer(gids, core, counts)
    # the metric registry's pairwise runs eagerly on host arrays —
    # one algebra, evaluated outside any jit
    m = dist_mod.get_metric(metric)
    measure = np.asarray(m.pairwise(qpts, spts))
    thr = float(np.asarray(m.threshold(np.float64(eps))))
    adj = (measure <= thr) & valid[None, :]
    counts[:] = adj.sum(axis=1)
    core[:] = ((counts + 1) >= int(min_points)).astype(np.int8)
    big = np.int64(np.iinfo(np.int64).max)
    nbr = np.where(adj, sids[None, :], big).min(axis=1)
    gids[:] = np.where(nbr == big, 0, nbr)
    return QueryAnswer(gids, core, counts)


def _dispatch_one(
    qp: np.ndarray,
    spts: np.ndarray,
    sids: np.ndarray,
    eps: float,
    min_points: int,
    metric: str,
    q: int,
    label: str,
    engine: Optional[pipe_mod.PullEngine] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One padded query dispatch + its thin label pull (PullEngine when
    live); returns host arrays sliced to the valid prefix ``q``.

    ``engine``: the PullEngine the label pull rides. The service passes
    its OWN dedicated instance: the process-global engine executes jobs
    in strict submission order, so a query pull submitted there would
    queue behind the ingest train's chunk pulls and host finalize —
    coupling read latency to write batch size, exactly what the
    epoch-snapshot design exists to avoid. None falls back to the
    process engine (standalone/offline use)."""
    fn = _query_builder(int(min_points), metric)
    gid_d, core_d, cnt_d = obs_compile.tracked_call(
        QUERY_FAMILY, fn, qp, spts, sids, float(eps)
    )

    def work():
        return (
            np.asarray(gid_d)[:q].astype(np.int64),
            np.asarray(core_d)[:q],
            np.asarray(cnt_d)[:q],
        )

    eng = engine if engine is not None else pipe_mod.get_engine()
    if eng is None:
        return work()

    def on_start():
        for a in (gid_d, core_d, cnt_d):
            start = getattr(a, "copy_to_host_async", None)
            if start is not None:
                start()

    job = eng.submit(
        work,
        on_start=on_start,
        bytes_hint=int(len(qp) * 9),
        label=label,
    )
    return eng.settle(job, serial_fallback=work)


def batched_query(
    qpts: np.ndarray,
    spts: np.ndarray,
    sids: np.ndarray,
    eps: float,
    min_points: int,
    metric: str,
    floors: Optional[dict] = None,
    engine: Optional[pipe_mod.PullEngine] = None,
    site: str = faults.SITE_SERVE,
    host_fallback: bool = True,
) -> QueryAnswer:
    """Answer one query batch against a (pre-padded) skeleton snapshot.

    ``spts``/``sids`` come from :func:`pad_skeleton` (the service pads
    at publish time); ``qpts`` is any [N, D] host array with the
    snapshot's clustering columns. Batches past
    ``DBSCAN_SERVE_QUERY_SLOTS`` split into consecutive dispatches.
    ``engine``: see :func:`_dispatch_one`. ``site``: the fault-spec
    token this read leg consumes ordinals at when named — a sharded
    service passes its ``serve@<shard>`` namespace, the router its
    ``serve_replica@<replica>`` one, so each shard/replica drill owns a
    deterministic stream (faults.shard_site). ``host_fallback``: when
    True (default) a PERSISTENT fault degrades in place to
    :func:`query_host`; the router passes False so the fault RAISES
    ``FatalDeviceFault`` instead — a dead replica is evicted and the
    query fails over, it does not silently degrade one shard's slice.
    """
    qpts = np.asarray(qpts, np.float64)
    n = len(qpts)
    gids = np.zeros(n, np.int64)
    core = np.zeros(n, np.int8)
    counts = np.zeros(n, np.int32)
    if n == 0:
        return QueryAnswer(gids, core, counts)
    if qpts.shape[1] != spts.shape[1]:
        raise ValueError(
            f"query points have {qpts.shape[1]} columns; the resident "
            f"skeleton carries {spts.shape[1]}"
        )
    slots = max(_PAD, int(config.env("DBSCAN_SERVE_QUERY_SLOTS")))
    drill = faults.site_active(site)
    for start in range(0, n, slots):
        stop = min(start + slots, n)
        q = stop - start
        qp_pad = _ratchet(floors, "serve_q", _ladder_width(q, _PAD))
        qp = np.zeros((qp_pad, qpts.shape[1]), np.float64)
        qp[:q] = qpts[start:stop]
        label = f"serve.query[{start}:{stop}]"

        def attempt(_budget, qp=qp, q=q, label=label):
            return _dispatch_one(
                qp, spts, sids, eps, min_points, metric, q, label,
                engine=engine,
            )

        if drill:
            fb = None
            if host_fallback:
                fb = lambda qp=qp, q=q: query_host(  # noqa: E731
                    qp[:q], spts, sids, eps, min_points, metric
                )
            g, c, cn = faults.supervised(
                site, attempt, fallback=fb, label=label
            )
        else:
            g, c, cn = attempt(None)
        gids[start:stop] = g
        core[start:stop] = c
        counts[start:stop] = cn
    return QueryAnswer(gids, core, counts)
