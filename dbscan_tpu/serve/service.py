"""Resident ClusterService: concurrent ingest + point->cluster queries.

The long-lived front of the streaming engine (ROADMAP item "a real
serving system"): ONE process holds the stream's device/jit state
resident across micro-batches, an ingest thread drives
``StreamingDBSCAN.update``, and concurrent reader threads answer
``query(points) -> (gid, core_flag)`` against the last PUBLISHED
snapshot of the resident grid — never a half-merged update.

Consistency: a seqlock-style epoch guards the published snapshot. The
ingest thread is the only writer; it bumps ``_seq`` to odd, swaps in
the new immutable :class:`Snapshot`, and bumps back to even — all
under the writer lock (one writer today, but the lock is what the
static race rules and the runtime sanitizer certify). Readers spin the
classic seqlock read (even seq, read, recheck) and therefore always
observe one complete epoch; the epoch number rides every answer so a
caller can correlate results with ingest progress.

Backpressure & health: ``submit`` blocks (or refuses, with
``block=False``) once ``DBSCAN_SERVE_QUEUE`` micro-batches are
pending — the ``serve.queue_depth`` gauge is the live signal — and
:meth:`health` reports queue depth, epoch/update counters, resident
skeleton size, HBM occupancy (obs/memory), the process fault counters,
and the pull-engine totals: everything a load balancer or autoscaler
polls.

Preemption: the service composes with the flight recorder's SIGTERM
path through :func:`obs.flight.on_sigterm` — on SIGTERM the recorder
dumps its postmortem ring FIRST, then this service's hook checkpoints
the last published snapshot (``checkpoint.save_serve``; quiet — the
signal path takes no telemetry locks), then the previous disposition
chains and the process dies. A restarted service restores the stream
state and resumes with BYTE-IDENTICAL labels for every later batch
(no relabeling drift; pinned by tests/test_serve.py).

Fault drills: ``DBSCAN_FAULT_SPEC`` clauses at the ``serve`` site
cover both legs — ingest steps and query dispatches each consume one
``serve`` ordinal when the site is named (opt-in, like ``pull``).
A retries-exhausted ingest fault marks the service degraded in
:meth:`health` but keeps the query side serving the last good epoch.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
from collections import deque
from typing import Callable, List, NamedTuple, Optional

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.config import DBSCANConfig, Engine, Precision
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import flight as obs_flight
from dbscan_tpu.obs import live as obs_live
from dbscan_tpu.obs import memory as obs_memory
from dbscan_tpu.obs import slo as slo_mod
from dbscan_tpu.parallel import checkpoint as ckpt_mod
from dbscan_tpu.parallel import pipeline as pipe_mod
from dbscan_tpu.serve import query as query_mod
from dbscan_tpu.streaming import StreamingDBSCAN, StreamUpdate

logger = logging.getLogger(__name__)


class Snapshot(NamedTuple):
    """One published query state: immutable by construction, so a
    reader that got a reference under an even seqlock value holds a
    complete epoch regardless of later publishes."""

    epoch: int
    n_updates: int
    spts: np.ndarray  # [Kp, D] ladder-padded skeleton core points
    sids: np.ndarray  # [Kp] int32 resolved stream ids (0 on padding)
    k: int  # valid skeleton rows
    state: Optional[dict]  # streaming.export_state() at this epoch
    update: Optional[StreamUpdate] = None  # the ingest step's labels


class QueryResult(NamedTuple):
    gids: np.ndarray  # [N] int64 resolved stream ids; 0 = noise
    core: np.ndarray  # [N] int8 would-be-core flag vs the skeleton
    counts: np.ndarray  # [N] int32 skeleton neighbors (self exclusive)
    epoch: int  # the snapshot epoch this answer is consistent with


def stream_fingerprint(cfg: DBSCANConfig, window: int) -> str:
    """Digest of the config fields that determine stream identity
    state — the gate :func:`checkpoint.load_serve` applies so a resumed
    server can never adopt another stream's ids."""
    h = hashlib.sha256()
    h.update(
        json.dumps(
            {
                "eps": cfg.eps,
                "min_points": cfg.min_points,
                "max_points_per_partition": cfg.max_points_per_partition,
                "metric": cfg.metric,
                "engine": cfg.engine.value,
                "precision": cfg.precision.value,
                "neighbor_backend": cfg.neighbor_backend,
                "window": int(window),
            },
            sort_keys=True,
        ).encode()
    )
    return h.hexdigest()


class ClusterService:
    """Long-lived concurrent ingest/query server over one stream.

    Lifecycle: construct (optionally restoring from ``checkpoint_dir``),
    :meth:`start`, then :meth:`submit` micro-batches from any thread
    while any number of threads call :meth:`query`; :meth:`stop` drains,
    checkpoints, and joins. Also usable as a context manager.
    """

    def __init__(
        self,
        eps: float,
        min_points: int,
        *,
        window: int = 3,
        metric: str = "euclidean",
        engine: Engine = Engine.ARCHERY,
        precision: Precision = Precision.F32,
        max_points_per_partition: int = 4096,
        config_obj: Optional[DBSCANConfig] = None,
        mesh=None,
        checkpoint_dir: Optional[str] = None,
        queue_depth: Optional[int] = None,
        snapshot_log: Optional[List[Snapshot]] = None,
        shard: Optional[int] = None,
        n_shards: int = 1,
        on_publish: Optional[Callable[[int, Snapshot], None]] = None,
        auto_restore: bool = True,
    ):
        """``shard``/``n_shards``: this service is one ingest shard of a
        :class:`~dbscan_tpu.serve.sharded.ShardedClusterService` — its
        fault-spec ordinals consume the ``serve@<shard>`` namespaced
        stream (shard 0 = the bare ``serve`` token, faults.shard_site)
        and its checkpoints carry the shard-suffixed layout. Unsharded
        (the default, shard None) behaves exactly as before.
        ``on_publish(shard, snap)`` is called after every seqlock
        publish — the sharded layer's consistent-cut assembly hook.
        ``auto_restore=False`` defers checkpoint adoption to the caller
        (the sharded layer restores all shards or none; see
        :meth:`adopt_state`)."""
        if config_obj is None:
            config_obj = DBSCANConfig(
                eps=eps,
                min_points=min_points,
                max_points_per_partition=max_points_per_partition,
                engine=engine,
                precision=precision,
                metric=metric,
                # the streaming front-end's steady-state contract:
                # ladder-pad the partition axis so micro-batches hit
                # the jit cache (streaming.py sets the same)
                static_partition_pad=True,
            )
        self._stream = StreamingDBSCAN(
            eps,
            min_points,
            max_points_per_partition,
            window=window,
            mesh=mesh,
            config=config_obj,
        )
        cfg = self._stream.config
        self._fingerprint = stream_fingerprint(cfg, self._stream.window)
        self._checkpoint_dir = checkpoint_dir
        self._shard = shard
        self._n_shards = max(1, int(n_shards))
        self._site = faults.shard_site(faults.SITE_SERVE, shard)
        self._on_publish = on_publish
        self._queue_max = max(
            1,
            int(
                queue_depth
                if queue_depth is not None
                else config.env("DBSCAN_SERVE_QUEUE")
            ),
        )
        self._floors = {}  # query-shape ratchet (ladder rungs recur)
        self._cv = _tsan.condition("serve.queue")
        self._queue: deque = deque()
        self._lock = _tsan.lock("serve.state")
        self._seq = 0  # seqlock: even = stable, odd = publish in flight
        self._snap = Snapshot(0, 0, np.zeros((0, 2)), np.zeros(0, np.int32), 0, None)
        self._snapshot_log = snapshot_log
        self._degraded_error: Optional[str] = None
        self._last_update_s = 0.0
        self._busy = False  # an update is being ingested right now
        self._fault_snap = faults.counters.snapshot()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._unhook = None
        self._t_started = time.perf_counter()
        # dedicated query-pull engine: the process-global engine
        # executes in strict submission order, so query pulls there
        # would queue behind the ingest train's chunk pulls — coupling
        # read latency to write batch size (query.py module docstring)
        self._pull = (
            pipe_mod.PullEngine(
                inflight=int(config.env("DBSCAN_PULL_INFLIGHT"))
            )
            if config.env("DBSCAN_PULL_PIPELINE")
            else None
        )
        if checkpoint_dir is not None and auto_restore:
            restored = ckpt_mod.load_serve(
                checkpoint_dir,
                self._fingerprint,
                shard=self._shard,
                n_shards=self._n_shards,
            )
            if restored is not None:
                self.adopt_state(restored)

    def adopt_state(self, restored: dict) -> None:
        """Adopt one loaded checkpoint state (checkpoint.load_serve)
        and publish it as the resume epoch — the restore tail of
        ``__init__``, split out so a sharded service can gate adoption
        on EVERY shard's checkpoint being present first (all-or-nothing;
        a partial restore would relabel across the shard boundary)."""
        self._stream.restore_state(restored)
        obs.count("serve.restores")
        self._publish(
            self._stream.export_state(),
            epoch=int(restored["scalars"].get("epoch", 0)),
        )

    # --- lifecycle ------------------------------------------------------

    def start(self) -> "ClusterService":
        obs.ensure_env()  # DBSCAN_TRACE + flight recorder/signal wiring
        if self._unhook is None:
            self._unhook = obs_flight.on_sigterm(self._sigterm_hook)
            if self._checkpoint_dir is not None and not (
                obs_flight.sigterm_armed()
            ):
                # the hook rides the flight recorder's SIGTERM handler;
                # with the recorder never enabled (DBSCAN_FLIGHTREC=0)
                # or start() off the main thread, that handler was
                # never installed and a preemption would kill the
                # process with NO checkpoint — say so now, not at the
                # first real SIGTERM
                logger.warning(
                    "serve: SIGTERM checkpoint hook is INERT — the "
                    "flight recorder's signal handler is not installed "
                    "(DBSCAN_FLIGHTREC=0, or the first enable ran off "
                    "the main thread). A preempted server will NOT "
                    "checkpoint; call checkpoint() explicitly or "
                    "enable the recorder."
                )
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._ingest_loop,
                name="dbscan-serve-ingest"
                + (f"-{self._shard}" if self._shard is not None else ""),
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self, checkpoint: bool = True, timeout: float = 60.0) -> None:
        """Drain-and-join: the ingest thread finishes queued batches,
        then exits; the final state is checkpointed (when a dir is
        configured) and the SIGTERM hook unregistered."""
        with self._cv:
            _tsan.access("serve.queue")
            self._stop_evt.set()
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        if self._unhook is not None:
            self._unhook()
            self._unhook = None
        if self._pull is not None:
            self._pull.close()
        if checkpoint:
            self.checkpoint()
        # the stream's per-update flushes predate the LAST publish (the
        # update's trace flush runs before the snapshot goes live): one
        # closing flush so the exported trace carries the final epoch
        obs.flush()

    def __enter__(self) -> "ClusterService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- ingest side ----------------------------------------------------

    def submit(
        self, batch: np.ndarray, *, block: bool = True, timeout=None
    ) -> bool:
        """Enqueue one micro-batch for the ingest thread. Returns False
        (and counts a refusal) when the queue is at its
        ``DBSCAN_SERVE_QUEUE`` bound and ``block`` is False or the wait
        timed out — the caller-visible backpressure signal."""
        b = np.asarray(batch, dtype=np.float64)
        if b.ndim != 2 or b.shape[1] < 2:
            raise ValueError(f"batch must be [B, >=2], got {b.shape}")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            _tsan.access("serve.queue")
            while len(self._queue) >= self._queue_max:
                if self._stop_evt.is_set():
                    raise RuntimeError("service is stopping")
                if not block:
                    obs.count("serve.ingest_rejects")
                    return False
                wait = (
                    None
                    if deadline is None
                    else max(0.0, deadline - time.monotonic())
                )
                if not self._cv.wait(wait if wait is not None else 1.0):
                    if deadline is not None:
                        obs.count("serve.ingest_rejects")
                        return False
            if self._stop_evt.is_set():
                raise RuntimeError("service is stopping")
            # the request context does not cross the queue on its own
            # (the ingest thread predates this request): capture the id
            # here, restore it around the ingest work
            self._queue.append((obs.current_request(), b))
            depth = len(self._queue)
            self._cv.notify_all()
        obs.gauge("serve.queue_depth", depth)
        return True

    def drain(self, timeout: float = 300.0) -> bool:
        """Block until every submitted batch has been ingested and
        published; True on success, False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            _tsan.access("serve.queue", write=False)
            while self._queue or self._busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.5))
        return True

    def _ingest_loop(self) -> None:
        while True:
            with self._cv:
                _tsan.access("serve.queue")
                while not self._queue and not self._stop_evt.is_set():
                    self._cv.wait(0.5)
                if not self._queue:
                    return  # stopping and drained
                rid, batch = self._queue.popleft()
                self._busy = True
                depth = len(self._queue)
                self._cv.notify_all()
            obs.gauge("serve.queue_depth", depth)
            try:
                with obs.request_scope(rid):
                    self._ingest_one(batch)
            except faults.FatalDeviceFault as e:
                # the query side keeps serving the last good epoch; the
                # health endpoint carries the degradation (the flight
                # recorder already dumped at the supervised raise site)
                with self._lock:
                    _tsan.access("serve.state")
                    self._degraded_error = str(e)
                obs.count("serve.degraded")
            finally:
                with self._cv:
                    _tsan.access("serve.queue")
                    self._busy = False
                    self._cv.notify_all()

    def _ingest_one(self, batch: np.ndarray) -> StreamUpdate:
        t0 = time.perf_counter()
        with obs.span(
            "serve.update",
            epoch=int(self._snap.epoch + 1),
            batch=int(len(batch)),
        ):
            if faults.site_active(self._site):
                # retry idempotence (fault-retry-unsafe): stream.update
                # mutates the stream (epoch counter, union-find, window)
                # BEFORE its device op can fault, so a bare retry would
                # double-apply the batch. Each attempt re-enters from
                # the pre-batch snapshot (the restore-prologue idiom the
                # effect model accepts), and the exhaustion path
                # restores it too, so the degraded service still serves
                # the last good epoch un-corrupted.
                state0 = self._stream.export_state()

                def _attempt(_b):
                    self._stream.restore_state(state0)
                    return self._stream.update(batch)

                try:
                    upd = faults.supervised(
                        self._site,
                        _attempt,
                        label=f"ingest epoch {self._snap.epoch + 1}",
                    )
                except faults.FatalDeviceFault:
                    self._stream.restore_state(state0)
                    raise
            else:
                upd = self._stream.update(batch)
            state = self._stream.export_state()
            self._publish(
                state, wall_s=time.perf_counter() - t0, update=upd
            )
        obs.count("serve.updates")
        obs.count("serve.ingest_points", int(len(batch)))
        obs_live.observe("serve.update_ms", (time.perf_counter() - t0) * 1e3)
        obs_live.bump("serve.updates")
        return upd

    def _publish(
        self,
        state: dict,
        epoch: Optional[int] = None,
        wall_s: float = 0.0,
        update: Optional[StreamUpdate] = None,
    ) -> None:
        """Build and publish one snapshot from an exported stream state
        (ingest thread, or __init__ on restore). The skeleton ids are
        re-resolved through the union-find so queries at this epoch see
        canonical ("elder wins") ids."""
        wpts = state["arrays"]["window_pts"]
        wids = self._stream.resolve(state["arrays"]["window_ids"])
        spts, sids, k = query_mod.pad_skeleton(wpts, wids, self._floors)
        snap = Snapshot(
            epoch=(self._snap.epoch + 1) if epoch is None else int(epoch),
            n_updates=int(state["scalars"]["n_updates"]),
            spts=spts,
            sids=sids,
            k=k,
            state=state,
            update=update,
        )
        with self._lock:
            _tsan.access("serve.state")
            self._seq += 1  # odd: publish in flight
            self._snap = snap
            self._last_update_s = wall_s
            self._seq += 1  # even: stable
            if self._snapshot_log is not None:
                self._snapshot_log.append(snap)
        obs.gauge("serve.epoch", snap.epoch)
        obs.gauge("serve.resident_points", snap.k)
        obs.event("serve.epoch_publish", epoch=snap.epoch, skeleton=snap.k)
        obs_live.bump("serve.epoch_publish")
        slo_mod.maybe_evaluate()
        if self._on_publish is not None:
            # AFTER the seqlock settles: the sharded layer folds this
            # shard's new epoch into the next published consistent cut
            self._on_publish(
                self._shard if self._shard is not None else 0, snap
            )

    # --- query side -------------------------------------------------------

    def _read_snapshot(self) -> Snapshot:
        """Seqlock read: retry while a publish is in flight. The
        snapshot itself is immutable, so an even-seq reference IS a
        consistent epoch. The spin is BOUNDED by
        ``DBSCAN_SERVE_READ_TIMEOUT_S``: a publish that never completes
        (wedged writer — the seq stays odd) starves every reader, and a
        reader that starves must say which writer wedged rather than
        burn a core forever."""
        deadline = None
        while True:
            s0 = self._seq
            if not (s0 & 1):
                snap = self._snap
                if self._seq == s0:
                    return snap
            if deadline is None:
                timeout = float(config.env("DBSCAN_SERVE_READ_TIMEOUT_S"))
                deadline = time.monotonic() + timeout
            elif time.monotonic() >= deadline:
                shard = self._shard if self._shard is not None else 0
                raise RuntimeError(
                    f"serve: seqlock read starved for {timeout:.3g}s — "
                    f"shard {shard}'s snapshot publish never completed "
                    "(wedged writer holds an odd epoch); raise "
                    "DBSCAN_SERVE_READ_TIMEOUT_S if the publish is "
                    "legitimately that slow"
                )
            time.sleep(0)  # yield to the publishing ingest thread

    def query(self, points: np.ndarray) -> QueryResult:
        """Answer ``point -> (gid, core_flag)`` for a batch, against
        the last published epoch. Safe from any number of threads,
        concurrent with ingest."""
        pts = np.asarray(points, dtype=np.float64)
        if pts.ndim != 2 or pts.shape[1] < 2:
            raise ValueError(f"query points must be [N, >=2], got {pts.shape}")
        snap = self._read_snapshot()
        cfg = self._stream.config
        ncols = 2 if cfg.metric == "euclidean" else pts.shape[1]
        qpts = pts[:, :ncols]
        t_q = time.perf_counter()
        with obs.span(
            "serve.query", epoch=int(snap.epoch), points=int(len(pts))
        ):
            if snap.k == 0:
                # empty skeleton: everything is noise (and core only in
                # the degenerate min_points <= 1 regime) — no dispatch
                ans = query_mod.QueryAnswer(
                    np.zeros(len(pts), np.int64),
                    np.full(
                        len(pts),
                        np.int8(1 if cfg.min_points <= 1 else 0),
                    ),
                    np.zeros(len(pts), np.int32),
                )
            else:
                ans = query_mod.batched_query(
                    qpts,
                    snap.spts,
                    snap.sids,
                    cfg.eps,
                    cfg.min_points,
                    cfg.metric,
                    floors=self._floors,
                    engine=self._pull,
                    site=self._site,
                )
        obs.count("serve.queries")
        obs.count("serve.query_points", int(len(pts)))
        obs_live.observe("serve.query_ms", (time.perf_counter() - t_q) * 1e3)
        obs_live.bump("serve.queries")
        return QueryResult(ans.gids, ans.core, ans.counts, snap.epoch)

    def resolve(self, ids: np.ndarray) -> np.ndarray:
        """Map previously-answered gids to their current canonical ids
        (merges only ever lower an id toward the elder)."""
        return self._stream.resolve(ids)

    def last_update(self) -> Optional[StreamUpdate]:
        """The most recent completed ingest step's stream-stable labels
        (None before the first epoch, or right after a restore — the
        checkpoint persists identity state, not the dead process's last
        batch labels)."""
        return self._read_snapshot().update

    # --- health / checkpoint ---------------------------------------------

    def health(self) -> dict:
        """The poll endpoint: backpressure, progress, residency, HBM,
        faults, pull-engine totals."""
        with self._cv:
            _tsan.access("serve.queue", write=False)
            depth = len(self._queue)
            busy = self._busy
        snap = self._read_snapshot()
        with self._lock:
            _tsan.access("serve.state", write=False)
            degraded = self._degraded_error
            last_update_s = self._last_update_s
        hbm = obs_memory.sample("serve.health")
        eng = self._pull if self._pull is not None else pipe_mod.get_engine()
        out = {
            "shard": self._shard,
            "epoch": snap.epoch,
            "n_updates": snap.n_updates,
            "queue_depth": depth,
            "queue_max": self._queue_max,
            "ingesting": busy,
            "backpressure": depth >= self._queue_max,
            "resident_points": snap.k,
            "last_update_s": round(last_update_s, 4),
            "uptime_s": round(time.perf_counter() - self._t_started, 3),
            "degraded": degraded,
            "faults": faults.counters.delta(self._fault_snap),
            "hbm_bytes_in_use": hbm,
            "pull": eng.totals() if eng is not None else None,
        }
        out.update(slo_mod.windowed_health())
        return out

    def checkpoint(self, quiet: bool = False) -> Optional[str]:
        """Persist the last published snapshot's stream state; returns
        the path (None without a checkpoint dir or before the first
        epoch). ``quiet`` skips telemetry — the SIGTERM hook sets it,
        because the interrupted frame may hold the obs locks."""
        if self._checkpoint_dir is None:
            return None
        snap = self._read_snapshot()
        if snap.state is None:
            return None
        path = ckpt_mod.save_serve(
            self._checkpoint_dir,
            self._fingerprint,
            snap.state["arrays"],
            {**snap.state["scalars"], "epoch": int(snap.epoch)},
            quiet=quiet,
            shard=self._shard,
            n_shards=self._n_shards,
        )
        if not quiet:
            obs.count("serve.checkpoints")
        return path

    def _sigterm_hook(self) -> None:
        """Runs on the flight recorder's SIGTERM path AFTER its dump:
        checkpoint the last published epoch, then let the recorder
        chain to the previous disposition. Quiet — a signal handler
        must not touch locks the interrupted frame may hold."""
        self.checkpoint(quiet=True)
