"""Replicated query reads: cut broadcast, failover routing, load shed.

The sharded service (serve/sharded.py) scales INGEST; this module
scales and hardens READS. A :class:`QueryRouter` subscribes to the
service's consistent-cut feed and maintains N query replicas — each a
device-resident copy of every shard's ladder-padded skeleton plus its
own dedicated PullEngine — then routes each query batch to one replica
by a deterministic content hash. Three robustness behaviors live here:

**Broadcast.** Every published cut transfers each shard's padded
skeleton to each live replica as one ``serve.broadcast`` family
dispatch (a jit identity-copy: the replica OWNS its skeleton, no
aliasing of the publisher's buffers). The arrays are already
ladder-padded at publish time, so after each replica warms its rungs
the broadcast compiles ZERO new kernels — a bounded, compile-stable
transfer, priced like everything else by a declared graftshape family
model (lint/shapes.py).

**Failover.** Each replica's dispatches run supervised at its own
``serve_replica@<r>`` fault site (faults.shard_site): TRANSIENT faults
heal in place (retry, replica keeps serving); a PERSISTENT fault
raises ``FatalDeviceFault``, the router EVICTS the replica (it leaves
the live set, its skeletons are dropped — the read mesh re-shards over
the survivors the way campaign.train_resharded shrinks the batch
ladder), and the in-flight query re-dispatches on the next live
replica AGAINST THE SAME PINNED CUT — the cut's host arrays are
immutable, so the answer the caller gets is the one its pinned epoch
vector promised, regardless of which replica died under it. With no
replica left the router degrades to the numpy union oracle
(:func:`~dbscan_tpu.serve.sharded.cut_query_host`). Net contract,
pinned by tests/test_serve_sharded.py: ZERO failed queries under any
schedule of replica kills.

**Load shed.** When the query p99 drifts past
``DBSCAN_SERVE_SHED_P99_MS`` (opt-in; 0 disables), the router sheds
the EXPENSIVE tail instead of queueing it: each candidate batch is
priced with the declared ``serve.query`` model (the admission
controller's forward-pricing discipline, serve/tenancy.py) and
admitted only if its price fits the headroom scaled down by
``bound / p99`` — the further p99 drifts, the cheaper a batch must be
to board. The p99 read is the LIVE sliding-window figure
(obs/live.py ``serve.query_ms``) whenever the live plane is on — a
shed decision sees the fleet's last window, not this router's
lifetime sample — and falls back to the in-router rolling deque with
``DBSCAN_OBS_LIVE=0``. Shed queries raise :class:`QueryShed` (an
admission refusal, not a failure), count ``serve.router.shed``, and
emit the declared ``serve.router.shed`` EVENT naming the SLO that
drove the refusal (query_p99);
``serve_shed_frac = shed / (shed + routed)`` is the bench/regression
surface (obs/bench_history.py, LOWER is better).

**Request tracing.** Every accepted query mints a request id at
ingress (``obs.mint_request_id``) and binds it for the whole routed
extent (``obs.request_scope``): the ``serve.route`` span, the
replica's per-shard ``serve.query`` dispatches, the PullEngine's
``pull.chunk`` spans, and any fault events the query touches all
carry the same ``rid`` — ``obs.analyze --requests`` reconstructs the
cross-shard critical path per request from a merged trace.
"""

from __future__ import annotations

import functools
import logging
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.obs import live as obs_live
from dbscan_tpu.obs import slo as slo_mod
from dbscan_tpu.parallel import pipeline as pipe_mod
from dbscan_tpu.serve import query as query_mod
from dbscan_tpu.serve.sharded import (
    Cut,
    ShardedClusterService,
    ShardedQueryResult,
    combine_answers,
    cut_query_host,
)

logger = logging.getLogger(__name__)

BROADCAST_FAMILY = "serve.broadcast"


class QueryShed(RuntimeError):
    """The router refused a query batch under shed pressure: the
    windowed (or fallback rolling) p99 is past the declared bound and
    this batch's priced cost does not fit the shrunk admission
    headroom. An ADMISSION refusal (retry later / smaller), not a
    failed query."""

    def __init__(self, price: int, allowed: int, p99: float, bound: float):
        super().__init__(
            f"serve.router: shed — query p99 {p99:.1f} ms is past the "
            f"{bound:.1f} ms bound and this batch prices at {price} B "
            f"vs the shrunk {allowed} B admission window"
        )
        self.price = int(price)
        self.allowed = int(allowed)
        self.p99 = float(p99)
        self.bound = float(bound)


@functools.lru_cache(maxsize=None)
def _broadcast_builder():
    """One compiled broadcast kernel (shared across replicas — the
    cpp jit cache keys executables per destination device): an
    identity-plus-zero copy so the replica owns fresh buffers rather
    than aliasing the publisher's donated ones."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(spts, sids):
        return spts + 0.0, sids + jnp.int32(0)

    return fn


class _Replica:
    """One query replica: a device pin, a dedicated PullEngine, the
    last broadcast cut and its device-resident skeletons, and its own
    ``serve_replica@<idx>`` fault-ordinal namespace. Mutable fields
    (``alive``/``cut``/``skel``) are guarded by the router lock."""

    def __init__(self, idx: int, device):
        self.idx = idx
        self.device = device
        self.site = faults.shard_site(faults.SITE_SERVE_REPLICA, idx)
        self.alive = True
        self.cut: Optional[Cut] = None
        #: shard -> (device spts, device gsids) for self.cut
        self.skel: Dict[int, Tuple] = {}
        # dedicated engine, same rationale as the service's (query.py):
        # replicas must not serialize behind each other's pulls
        self.pull = (
            pipe_mod.PullEngine(
                inflight=int(config.env("DBSCAN_PULL_INFLIGHT"))
            )
            if config.env("DBSCAN_PULL_PIPELINE")
            else None
        )
        self.floors: dict = {}  # per-replica [Q]-axis ladder ratchet


class QueryRouter:
    """Hash-routes query batches across N replicated readers of one
    :class:`ShardedClusterService`, with broadcast, failover, and
    priced load shedding (module docstring). Construct AFTER the
    service (the router subscribes to its cut feed and starts warm),
    close BEFORE discarding it. Usable as a context manager."""

    def __init__(
        self,
        service: ShardedClusterService,
        *,
        replicas: Optional[int] = None,
        devices: Optional[list] = None,
        p99_window: int = 256,
    ):
        n = int(
            replicas
            if replicas is not None
            else config.env("DBSCAN_SERVE_REPLICAS")
        )
        if n < 1:
            raise ValueError(f"replicas must be >= 1, got {n}")
        self._svc = service
        self._lock = _tsan.lock("serve.router")
        if devices is None:
            try:
                import jax

                devices = list(jax.devices())
            except Exception:  # pragma: no cover - jaxless host path
                devices = [None]
        self._replicas = [
            _Replica(i, devices[i % len(devices)]) for i in range(n)
        ]
        self._last_cut_id = 0
        self._lats = deque(maxlen=int(p99_window))
        self._routed = 0
        self._shed = 0
        self._closed = False
        self._headroom = int(config.env("DBSCAN_SERVE_HEADROOM_BYTES"))
        # the serving constructors are live-plane entry points: the
        # latch makes this a tuple compare after the first router
        obs_live.ensure_env()
        obs.gauge("serve.router.replicas_live", n)
        service.add_listener(self.publish_cut)

    # --- lifecycle ------------------------------------------------------

    def __enter__(self) -> "QueryRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Stop accepting broadcasts and join every replica's pull
        engine (evicted replicas' engines are joined here too — an
        evict must not block on a possibly-wedged worker)."""
        with self._lock:
            _tsan.access("serve.router")
            if self._closed:
                return
            self._closed = True
            replicas = list(self._replicas)
        for r in replicas:
            if r.pull is not None:
                r.pull.close()

    # --- broadcast side -------------------------------------------------

    def publish_cut(self, cut: Cut) -> None:
        """Transfer one published cut's shard skeletons to every live
        replica (the service's cut listener — runs on the publishing
        shard's ingest thread). Stale cut_ids are dropped: two shards
        racing their listeners can never regress a replica, because a
        later cut contains every earlier shard entry."""
        import jax

        with self._lock:
            _tsan.access("serve.router")
            if self._closed or cut.cut_id <= self._last_cut_id:
                return
            self._last_cut_id = cut.cut_id
            live = [r for r in self._replicas if r.alive]
        fn = _broadcast_builder()
        for r in live:
            skel: Dict[int, Tuple] = {}
            nbytes = 0
            for s, sc in enumerate(cut.shards):
                if sc.k == 0:
                    continue
                sp, si = sc.spts, sc.gsids
                if r.device is not None:
                    sp = jax.device_put(sp, r.device)
                    si = jax.device_put(si, r.device)
                skel[s] = obs_compile.tracked_call(
                    BROADCAST_FAMILY, fn, sp, si
                )
                nbytes += sc.spts.nbytes + sc.gsids.nbytes
            with self._lock:
                _tsan.access("serve.router")
                # a replica evicted (or a newer cut landed) while we
                # were transferring: drop, never regress
                if r.alive and (r.cut is None or cut.cut_id > r.cut.cut_id):
                    r.cut = cut
                    r.skel = skel
            obs.count("serve.broadcast.casts")
            obs.count("serve.broadcast.bytes", nbytes)

    # --- shed policy ----------------------------------------------------

    def _price(self, n_q: int, cut: Cut, d: int) -> int:
        """This batch's predicted dispatch bytes at its padded shapes:
        the declared ``serve.query`` model evaluated at (padded Q,
        summed padded K across non-empty shards, D) — the admission
        controller's arithmetic pointed at the read path."""
        from dbscan_tpu.lint.shapes import FAMILY_MODELS
        from dbscan_tpu.parallel.binning import _ladder_width

        qp = _ladder_width(max(n_q, 1), query_mod._PAD)
        kp = sum(len(sc.gsids) for sc in cut.shards if sc.k > 0)
        model = FAMILY_MODELS[query_mod.QUERY_FAMILY]
        binding = {"Q": int(qp), "K": int(max(kp, 1)), "D": int(d)}
        expr = model.input_expr() + model.overhead
        return int(expr.substitute(binding).evaluate(binding))

    def _rolling_p99(self) -> Optional[float]:
        with self._lock:
            _tsan.access("serve.router")
            lats = list(self._lats)
        if len(lats) < 8:  # not enough signal to declare drift
            return None
        return float(np.percentile(np.asarray(lats), 99))

    def _windowed_p99(self) -> Tuple[Optional[float], str]:
        """The p99 shed decisions read: the LIVE sliding-window figure
        when the live plane has data (source "window"), else this
        router's rolling sample (source "rolling" — the
        DBSCAN_OBS_LIVE=0 fallback)."""
        p99 = obs_live.quantile("serve.query_ms", 0.99)
        if p99 is not None:
            return p99, "window"
        return self._rolling_p99(), "rolling"

    def _shed_check(self, n_q: int, cut: Cut, d: int) -> None:
        bound = float(config.env("DBSCAN_SERVE_SHED_P99_MS"))
        if bound <= 0:
            return
        p99, source = self._windowed_p99()
        if p99 is None or p99 <= bound:
            return
        obs.gauge("serve.router.p99_ms", p99)
        if source == "window":
            obs.gauge("serve.windowed_p99_ms", p99)
        price = self._price(n_q, cut, d)
        allowed = int(self._headroom * (bound / p99))
        if price > allowed:
            with self._lock:
                _tsan.access("serve.router")
                self._shed += 1
            obs.count("serve.router.shed")
            obs_live.bump("serve.router.shed")
            # the refusal is attributable: the event NAMES the SLO
            # whose windowed burn drove it (the query-latency
            # objective), with the exact figures the decision read
            obs.event(
                "serve.router.shed",
                slo=slo_mod.QUERY_P99,
                p99_ms=round(p99, 3),
                bound_ms=bound,
                source=source,
                price=price,
                allowed=allowed,
            )
            slo_mod.maybe_evaluate()
            raise QueryShed(price, allowed, p99, bound)

    @property
    def shed_frac(self) -> float:
        """Shed fraction over this router's lifetime:
        ``shed / (shed + routed)`` (0.0 before any traffic)."""
        with self._lock:
            _tsan.access("serve.router")
            total = self._shed + self._routed
            return self._shed / total if total else 0.0

    # --- query side -----------------------------------------------------

    def _pick(self, key: int) -> Optional[_Replica]:
        with self._lock:
            _tsan.access("serve.router")
            live = [r for r in self._replicas if r.alive]
        if not live:
            return None
        return live[key % len(live)]

    def _evict(self, r: _Replica, err: BaseException) -> None:
        with self._lock:
            _tsan.access("serve.router")
            if not r.alive:
                return
            r.alive = False
            r.cut = None
            r.skel = {}
            live = sum(1 for x in self._replicas if x.alive)
        obs.count("serve.replica.evictions")
        obs.gauge("serve.router.replicas_live", live)
        obs.event(
            "serve.replica.evict",
            replica=r.idx,
            live=live,
            error=str(err)[:160],
        )
        logger.warning(
            "serve.router: replica %d evicted after a persistent fault "
            "(%s) — read mesh re-shards over %d survivor(s)",
            r.idx, err, live,
        )

    def _replica_query(
        self, r: _Replica, qpts: np.ndarray, cut: Cut
    ) -> query_mod.QueryAnswer:
        """One replica's answer at the PINNED cut: per-shard dispatches
        through the replica's own engine at its own fault site, folded
        by the union algebra. Uses the replica's device-resident
        skeletons only when its broadcast cut IS the pinned cut;
        otherwise (failover onto a replica mid-broadcast) the pinned
        cut's immutable host arrays ride the same ladder shapes."""
        cfg = self._svc.config
        answers = []
        for s, sc in enumerate(cut.shards):
            if sc.k == 0:
                continue
            dev = r.skel.get(s) if r.cut is cut else None
            sp, si = dev if dev is not None else (sc.spts, sc.gsids)
            answers.append(
                query_mod.batched_query(
                    qpts,
                    sp,
                    si,
                    cfg.eps,
                    cfg.min_points,
                    cfg.metric,
                    floors=r.floors,
                    engine=r.pull,
                    site=r.site,
                    host_fallback=False,
                )
            )
        return combine_answers(answers, len(qpts), cfg.min_points)

    def query(self, points: np.ndarray) -> ShardedQueryResult:
        """Route one query batch: pin a cut, hash to a live replica,
        answer there; on a persistent replica fault, evict and re-route
        the SAME pinned cut to the next live replica; with none left,
        answer from the numpy union oracle. Every accepted query gets
        an answer exact for its pinned epoch vector — the zero-failed-
        queries contract. Raises :class:`QueryShed` only as an
        admission refusal under p99 pressure."""
        pts = np.asarray(points, dtype=np.float64)
        if pts.ndim != 2 or pts.shape[1] < 2:
            raise ValueError(
                f"query points must be [N, >=2], got {pts.shape}"
            )
        cfg = self._svc.config
        ncols = 2 if cfg.metric == "euclidean" else pts.shape[1]
        qpts = np.ascontiguousarray(pts[:, :ncols])
        # deterministic content hash: the same batch always lands on
        # the same replica (for a fixed live set), so drills replay
        key = zlib.crc32(qpts.tobytes())
        pinned: Optional[Cut] = None
        t0 = time.perf_counter()
        # request ingress: mint the id here and bind it for the whole
        # routed extent — every span/event/fault this query touches
        # (route, per-shard dispatches, pull.chunk hops, failovers)
        # carries it into the exports and the flightrec ring
        rid = obs.mint_request_id()
        with obs.request_scope(rid), obs.span(
            "serve.route", points=int(len(pts))
        ):
            while True:
                r = self._pick(key)
                if r is None:
                    break  # no replica left: host oracle below
                if pinned is None:
                    pinned = r.cut if r.cut is not None else self._svc.cut()
                    self._shed_check(len(qpts), pinned, qpts.shape[1])
                try:
                    ans = self._replica_query(r, qpts, pinned)
                except faults.FatalDeviceFault as err:
                    self._evict(r, err)
                    obs.count("serve.router.failovers")
                    obs.event(
                        "serve.router.failover",
                        replica=r.idx,
                        cut=int(pinned.cut_id),
                    )
                    continue  # re-route the pinned cut to a survivor
                self._record(t0, replica=r.idx)
                return ShardedQueryResult(
                    ans.gids, ans.core, ans.counts, pinned.epochs
                )
            if pinned is None:
                pinned = self._svc.cut()
                self._shed_check(len(qpts), pinned, qpts.shape[1])
            ans = cut_query_host(
                qpts, pinned, cfg.eps, cfg.min_points, cfg.metric
            )
            obs.count("serve.router.host_fallbacks")
            self._record(t0, replica=-1)
            return ShardedQueryResult(
                ans.gids, ans.core, ans.counts, pinned.epochs
            )

    def _record(self, t0: float, replica: int) -> None:
        ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            _tsan.access("serve.router")
            self._lats.append(ms)
            self._routed += 1
        obs.count("serve.router.routed")
        # feed the live plane: the windowed histogram the NEXT shed
        # decision (and the SLO engine) reads, then a throttled SLO
        # evaluation pass — no dedicated thread anywhere
        obs_live.observe("serve.query_ms", ms)
        obs_live.bump("serve.router.routed")
        slo_mod.maybe_evaluate()

    def health(self) -> dict:
        with self._lock:
            _tsan.access("serve.router")
            live = [r.idx for r in self._replicas if r.alive]
            cut_ids = [
                (r.cut.cut_id if r.cut is not None else 0)
                for r in self._replicas
            ]
            shed, routed = self._shed, self._routed
        total = shed + routed
        out = {
            "replicas": len(self._replicas),
            "live": live,
            "replica_cut_ids": cut_ids,
            "routed": routed,
            "shed": shed,
            "shed_frac": shed / total if total else 0.0,
        }
        out.update(slo_mod.windowed_health())
        return out
