"""``python -m dbscan_tpu.serve`` — serve a synthetic stream.

The zero-to-serving demo AND the shape the bench harness measures:
start a :class:`ClusterService`, ingest drifting synthetic micro-
batches on the service's ingest thread, hammer it with concurrent
query batches from reader threads, print a health line per completed
update, then run a small multi-tenant :class:`JobBatcher` stream — and
finish with one JSON summary line (``serve_qps``, ``serve_p50_ms``,
``serve_p99_ms``, ``tenancy_jobs_s``), the same keys
``BENCH_SERVE_*.json`` captures carry.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Optional, Sequence

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.serve",
        description="Serve a synthetic stream: concurrent ingest + "
        "point->cluster queries, then a multi-tenant small-job batch.",
    )
    p.add_argument("--updates", type=int, default=6, help="ingest batches")
    p.add_argument(
        "--batch", type=int, default=2000, help="points per ingest batch"
    )
    p.add_argument("--eps", type=float, default=0.6)
    p.add_argument("--min-points", type=int, default=5)
    p.add_argument("--window", type=int, default=3)
    p.add_argument(
        "--max-points-per-partition", type=int, default=4096
    )
    p.add_argument(
        "--query-batch", type=int, default=256,
        help="points per query batch",
    )
    p.add_argument(
        "--readers", type=int, default=2,
        help="concurrent query reader threads",
    )
    p.add_argument(
        "--jobs", type=int, default=40,
        help="small tenant jobs for the JobBatcher leg (0 disables)",
    )
    p.add_argument(
        "--checkpoint-dir",
        help="serve state checkpoint dir (SIGTERM-safe resume)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--json", action="store_true",
        help="print ONLY the final JSON summary line",
    )
    p.add_argument(
        "--watch", action="store_true",
        help="after each health line, render the live-telemetry "
        "console frame (obs/live.py windows) for this process",
    )
    return p


def _synthetic_batches(rng, updates: int, batch: int):
    from dbscan_tpu.serve import synthetic

    centers = synthetic.blob_centers(side=4)
    for u in range(updates):
        yield synthetic.drifting_batch(rng, u, batch, centers)


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from dbscan_tpu.serve import ClusterService, JobBatcher

    rng = np.random.default_rng(args.seed)
    svc = ClusterService(
        args.eps,
        args.min_points,
        window=args.window,
        max_points_per_partition=args.max_points_per_partition,
        checkpoint_dir=args.checkpoint_dir,
    )
    lat_ms: list = []
    lat_lock = threading.Lock()
    stop = threading.Event()
    qpts = rng.uniform(0, 4 * 8.0, (args.query_batch, 2))

    def reader():
        while not stop.is_set():
            t0 = time.perf_counter()
            svc.query(qpts)
            dt = (time.perf_counter() - t0) * 1e3
            with lat_lock:
                lat_ms.append(dt)

    threads = [
        threading.Thread(target=reader, daemon=True)
        for _ in range(max(1, args.readers))
    ]
    t_start = time.perf_counter()
    with svc:
        for t in threads:
            t.start()
        last_epoch = 0
        for batch in _synthetic_batches(rng, args.updates, args.batch):
            svc.submit(batch)
            svc.drain()
            h = svc.health()
            last_epoch = h["epoch"]
            if not args.json:
                win = h.get("windowed") or {}
                wp99 = win.get("windowed_p99_ms")
                print(
                    f"epoch {h['epoch']}: queue={h['queue_depth']}/"
                    f"{h['queue_max']} resident={h['resident_points']} "
                    f"update={h['last_update_s']:.3f}s "
                    f"queries={len(lat_ms)}"
                    + (f" wp99={wp99:.1f}ms" if wp99 is not None else "")
                    + (f" expo={win['expo']}" if win.get("expo") else "")
                    + (" DEGRADED" if h["degraded"] else "")
                )
                if args.watch:
                    from dbscan_tpu.obs import live as obs_live

                    snap = obs_live.snapshot()
                    if snap is not None:
                        print(
                            obs_live.render_console(
                                obs_live.parse_expo(
                                    obs_live.render_expo(snap)
                                ),
                                "in-process",
                            )
                        )
        ingest_wall = time.perf_counter() - t_start
        stop.set()
        for t in threads:
            t.join(timeout=30)
        health = svc.health()

    with lat_lock:
        lats = np.asarray(lat_ms, np.float64)
    qps = len(lats) / ingest_wall if ingest_wall > 0 else 0.0

    tenancy_jobs_s = 0.0
    if args.jobs > 0:
        from dbscan_tpu.serve import synthetic

        batcher = JobBatcher()
        t0 = time.perf_counter()
        for j in range(args.jobs):
            batcher.submit(
                synthetic.tenant_job(rng), eps=0.5, min_points=4
            )
        done = batcher.flush()
        tenancy_wall = time.perf_counter() - t0
        tenancy_jobs_s = len(done) / tenancy_wall if tenancy_wall > 0 else 0.0

    from dbscan_tpu import obs

    obs.flush()  # land the tenancy-leg counters in any DBSCAN_TRACE file
    summary = {
        "metric": "serve",
        "serve_updates": int(args.updates),
        "serve_epoch": int(last_epoch),
        "serve_queries": int(len(lats)),
        "serve_qps": round(float(qps), 3),
        "serve_p50_ms": round(float(np.percentile(lats, 50)), 3)
        if len(lats)
        else None,
        "serve_p99_ms": round(float(np.percentile(lats, 99)), 3)
        if len(lats)
        else None,
        "serve_batch_period_s": round(ingest_wall / max(1, args.updates), 4),
        "serve_resident_points": int(health["resident_points"]),
        "tenancy_jobs_s": round(float(tenancy_jobs_s), 3),
        "degraded": health["degraded"],
    }
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
