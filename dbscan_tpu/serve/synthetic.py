"""Shared synthetic traffic generators for the serving demo and bench.

One definition of the drifting-blob ingest stream and the mixed tenant
job shape, consumed by both ``python -m dbscan_tpu.serve``
(serve/__main__.py) and the bench capture (``bench.py serve_row``) —
two independently-drifting copies of the harness data would let a fix
to one silently miss the other. The TIMING policy (warm-up rules,
reader gating) stays with each harness; only the data shapes live
here.
"""

from __future__ import annotations

import numpy as np


def blob_centers(side: int = 4, spacing: float = 8.0) -> np.ndarray:
    """A ``side x side`` grid of cluster centers."""
    return np.stack(
        np.meshgrid(np.arange(side) * spacing, np.arange(side) * spacing),
        axis=-1,
    ).reshape(-1, 2)


def drifting_batch(
    rng: np.random.Generator,
    u: int,
    batch: int,
    centers: np.ndarray,
    drift: float = 0.15,
    noise: float = 0.25,
) -> np.ndarray:
    """Micro-batch ``u`` of a drifting blob field: the same cluster
    grid plus a slow per-update drift, so stream identities persist
    across updates while the window skeleton keeps moving."""
    per = max(4, batch // len(centers))
    return (
        np.repeat(centers + drift * u, per, axis=0)
        + rng.normal(0, noise, (len(centers) * per, 2))
    )


def tenant_job(
    rng: np.random.Generator,
    lo: int = 40,
    hi: int = 260,
) -> np.ndarray:
    """One small tenant job: half a tight cluster, half uniform noise —
    the mixed density a per-user clustering request actually carries."""
    n = int(rng.integers(lo, hi))
    c = rng.uniform(0, 10, 2)
    return np.concatenate(
        [
            rng.normal(c, 0.2, (n // 2, 2)),
            rng.uniform(-20, 20, (n - n // 2, 2)),
        ]
    )
