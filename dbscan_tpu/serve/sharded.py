"""Sharded ClusterService: N resident ingest shards, one consistent cut.

The PR-13 service is one process on one device — a single point of
failure in the subsystem that faces the query load. This module is the
ingest-scale axis of the distributed serving design (ROADMAP item 2):
the resident streaming grid partitions over the mesh like the batch
engines, with one :class:`~dbscan_tpu.serve.service.ClusterService`
per partition (its own ingest thread, dedicated query-pull engine,
seqlock, fault-ordinal namespace, and shard-suffixed checkpoint), and
this layer owning two things the shards cannot own alone:

**Routing.** Micro-batches split by a deterministic spatial hash of
the ``8*eps`` grid cell (:func:`shard_of`) — the same grow-by-eps cell
geometry the batch partitioner bins by — so a point's shard is a pure
function of its coordinates and a resumed service routes every later
batch identically (byte-identical labels, the serving contract's one
hard rule). Per-shard stream ids are disjoint BY CONSTRUCTION:
:func:`namespace_sids` strides shard ``s``'s local id ``l`` to the
global ``(l - 1) * n_shards + s + 1``, so the cross-shard min-fold at
query time stays the stream's own "elder id wins" rule.

**The consistent cut.** Each shard publishes its own epoch under its
own seqlock; a reader must never mix shard 0's epoch 7 with shard 1's
half-published epoch 4. So the published unit here is an **epoch
vector**: after every shard publish, the completing shard folds its new
snapshot into a :class:`Cut` — the vector of every shard's CURRENT
snapshot — under a second, cut-level seqlock (classic odd/even
protocol, generalized to N writers by serializing publishers on the cut
lock). Readers pin one cut (:meth:`ShardedClusterService.cut`) and are
answered against exactly that vector: one completed update per shard,
never a blend of two cuts. The spin is bounded by
``DBSCAN_SERVE_READ_TIMEOUT_S`` and a starved reader names the shard
whose publish wedged.

Query semantics (the distributed serving contract, PARITY.md): the
sharded service's density skeleton is the UNION of the per-shard
skeletons at the pinned cut. Counts add across shards, the gid is the
min-fold of the per-shard gids (associative and partition-independent,
the same algebra the collective halo merge fixed-points over,
arXiv:1912.06255), and the core flag is recomputed from the summed
neighbor count. :func:`cut_query_host` is the numpy oracle for exactly
this contract — what the router degrades to with no replica left, and
what the drill tests pin device answers against.

Replicated reads ride on top: serve/router.py subscribes via
:meth:`add_listener`, broadcasts every published cut's ladder-padded
skeletons to its query replicas, and fails over between them.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, NamedTuple, Optional, Tuple

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.config import DBSCANConfig, Engine, Precision
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import live as obs_live
from dbscan_tpu.obs import slo as slo_mod
from dbscan_tpu.parallel import checkpoint as ckpt_mod
from dbscan_tpu.parallel import mesh as mesh_mod
from dbscan_tpu.serve import query as query_mod
from dbscan_tpu.serve.service import ClusterService, Snapshot

logger = logging.getLogger(__name__)

#: min-fold identity for the cross-shard gid combine (gids are int64
#: host-side; per-shard answers report 0 for "no adjacent skeleton")
_NO_GID = np.int64(np.iinfo(np.int64).max)


class ShardCut(NamedTuple):
    """One shard's contribution to a published cut: its epoch and the
    ladder-padded skeleton with GLOBALLY-namespaced ids — immutable, so
    a pinned cut stays answerable forever (failover re-runs against it
    on another replica without re-reading the shard)."""

    epoch: int
    spts: np.ndarray  # [Kp, D] ladder-padded skeleton core points
    gsids: np.ndarray  # [Kp] int32 shard-strided global ids (0 = pad)
    k: int  # valid skeleton rows
    snap: Optional[Snapshot]


class Cut(NamedTuple):
    """One published epoch VECTOR: every shard's current snapshot at
    one cut-seqlock publish. ``epochs`` rides every answer so a caller
    can correlate results with per-shard ingest progress."""

    cut_id: int
    epochs: Tuple[int, ...]
    shards: Tuple[ShardCut, ...]


class ShardedQueryResult(NamedTuple):
    gids: np.ndarray  # [N] int64 global stream ids; 0 = noise
    core: np.ndarray  # [N] int8 would-be-core flag vs the union skeleton
    counts: np.ndarray  # [N] int32 union-skeleton neighbors (self excl.)
    epochs: Tuple[int, ...]  # the pinned cut's per-shard epoch vector


def shard_of(points: np.ndarray, eps: float, n_shards: int) -> np.ndarray:
    """Deterministic spatial routing: hash of the ``8*eps`` grid cell
    of each point's first two (clustering) columns, mod the shard
    count. Cells are 8 eps wide so a cluster's points mostly land on
    one shard (locality), while the classic two-prime XOR hash spreads
    cells evenly. Pure function of coordinates — the property the
    byte-identical-resume contract needs."""
    cell = np.floor(
        np.asarray(points, np.float64)[:, :2] / (8.0 * float(eps))
    ).astype(np.int64)
    h = (cell[:, 0] * np.int64(73856093)) ^ (cell[:, 1] * np.int64(19349663))
    return ((h % n_shards) + n_shards) % n_shards


def namespace_sids(
    sids: np.ndarray, shard: int, n_shards: int
) -> np.ndarray:
    """Stride shard-local stream ids into the disjoint global id space:
    local ``l`` on shard ``s`` becomes ``(l - 1) * n_shards + s + 1``
    (injective across shards, monotone per shard — the cross-shard
    min-fold therefore still prefers elder local ids, tie-broken by
    shard index). 0 (padding/noise) maps to 0."""
    sids = np.asarray(sids)
    if sids.size:
        mx = int(sids.max())
        if mx > 0 and (mx - 1) * n_shards + shard + 1 >= np.iinfo(np.int32).max:
            raise ValueError(
                "shard-strided stream ids exceeded int32 range; the "
                "query kernel's device ids are i32"
            )
    out = np.where(
        sids > 0,
        (sids.astype(np.int64) - 1) * n_shards + shard + 1,
        0,
    )
    return out.astype(np.int32)


def combine_answers(
    answers: List[query_mod.QueryAnswer], n: int, min_points: int
) -> query_mod.QueryAnswer:
    """Fold per-shard answers into the union-skeleton answer: counts
    add, gid is the positive min across shards, and the core flag is
    recomputed from the SUMMED self-inclusive neighbor count (a point
    can be core against the union without being core against any one
    shard's skeleton)."""
    counts = np.zeros(n, np.int32)
    gids = np.full(n, _NO_GID)
    for a in answers:
        counts += a.counts
        gids = np.minimum(gids, np.where(a.gids > 0, a.gids, _NO_GID))
    gids = np.where(gids == _NO_GID, np.int64(0), gids)
    core = ((counts + 1) >= int(min_points)).astype(np.int8)
    return query_mod.QueryAnswer(gids, core, counts)


def cut_query_host(
    qpts: np.ndarray, cut: Cut, eps: float, min_points: int, metric: str
) -> query_mod.QueryAnswer:
    """The numpy oracle of the distributed serving contract: answer
    against the UNION of the pinned cut's shard skeletons — the router's
    no-replica-left degradation path, and the reference every device
    answer at this cut is pinned against."""
    answers = [
        query_mod.query_host(
            qpts, sc.spts, sc.gsids, eps, min_points, metric
        )
        for sc in cut.shards
        if sc.k > 0
    ]
    return combine_answers(answers, len(qpts), min_points)


def _shard_meshes(mesh, n_shards: int) -> List:
    """Partition one mesh's devices into contiguous per-shard slabs
    (mesh.parts_spec geometry, one sub-mesh per ingest shard) — shards
    must not share a mesh: each drives its own collective dispatches
    from its own ingest thread, and interleaved collectives on one
    device set would desync (streaming.py's single-writer rule, per
    shard). Fewer devices than shards leaves the tail shards meshless
    (single-device ingest)."""
    if mesh is None:
        return [None] * n_shards
    devs = list(np.asarray(mesh.devices).flat)
    slabs = np.array_split(np.arange(len(devs)), n_shards)
    out = []
    for slab in slabs:
        if len(slab) == 0:
            out.append(None)
        else:
            out.append(mesh_mod.make_mesh([devs[i] for i in slab]))
    return out


_EMPTY_SKEL = np.zeros((0, 2), np.float64)
_EMPTY_IDS = np.zeros(0, np.int32)


class ShardedClusterService:
    """N-shard resident serving front: concurrent per-shard ingest,
    epoch-vector consistent cuts, union-skeleton queries.

    Lifecycle mirrors :class:`ClusterService`: construct (optionally
    restoring per-shard checkpoints — all shards or none),
    :meth:`start`, :meth:`submit` micro-batches from any thread while
    readers call :meth:`query`; :meth:`stop` drains every shard,
    checkpoints each under its shard suffix, and joins. Usable as a
    context manager. ``cut_log`` (tests) records every published cut.
    """

    def __init__(
        self,
        eps: float,
        min_points: int,
        *,
        n_shards: int = 2,
        window: int = 3,
        metric: str = "euclidean",
        engine: Engine = Engine.ARCHERY,
        precision: Precision = Precision.F32,
        max_points_per_partition: int = 4096,
        config_obj: Optional[DBSCANConfig] = None,
        mesh=None,
        checkpoint_dir: Optional[str] = None,
        queue_depth: Optional[int] = None,
        cut_log: Optional[List[Cut]] = None,
    ):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.n_shards = int(n_shards)
        self._eps = float(eps)
        self._min_points = int(min_points)
        # cut seqlock state: N writer threads (one per shard) serialize
        # on the lock; readers spin the odd/even protocol unlocked
        self._cut_lock = _tsan.lock("serve.cut")
        self._cut_seq = 0
        self._publishing_shard: Optional[int] = None
        empty = tuple(
            ShardCut(0, _EMPTY_SKEL, _EMPTY_IDS, 0, None)
            for _ in range(self.n_shards)
        )
        self._cut = Cut(0, (0,) * self.n_shards, empty)
        self._cut_log = cut_log
        self._listeners: List[Callable[[Cut], None]] = []
        self._floors = {}  # [Q]-axis ladder ratchet for the read path
        meshes = _shard_meshes(mesh, self.n_shards)
        self._shards = [
            ClusterService(
                eps,
                min_points,
                window=window,
                metric=metric,
                engine=engine,
                precision=precision,
                max_points_per_partition=max_points_per_partition,
                config_obj=config_obj,
                mesh=meshes[s],
                checkpoint_dir=checkpoint_dir,
                queue_depth=queue_depth,
                shard=s,
                n_shards=self.n_shards,
                on_publish=self._on_shard_publish,
                auto_restore=False,
            )
            for s in range(self.n_shards)
        ]
        if checkpoint_dir is not None:
            self._restore(checkpoint_dir)

    def _restore(self, checkpoint_dir: str) -> None:
        """All-or-nothing per-shard restore: a cut with some shards
        resumed and others fresh would answer queries against a vector
        no service ever published — refuse-and-warn, start every shard
        fresh instead (the same contract load_serve applies to a
        shard-count mismatch)."""
        restored = [
            ckpt_mod.load_serve(
                checkpoint_dir,
                svc._fingerprint,
                shard=s,
                n_shards=self.n_shards,
            )
            for s, svc in enumerate(self._shards)
        ]
        have = sum(r is not None for r in restored)
        if have == 0:
            return
        if have < self.n_shards:
            logger.warning(
                "sharded serve checkpoint in %s is PARTIAL (%d of %d "
                "shard files restorable) — refusing the restore and "
                "starting every shard fresh; a half-restored cut would "
                "relabel across the shard boundary",
                checkpoint_dir, have, self.n_shards,
            )
            return
        for svc, r in zip(self._shards, restored):
            svc.adopt_state(r)

    # --- lifecycle ------------------------------------------------------

    @property
    def config(self) -> DBSCANConfig:
        return self._shards[0]._stream.config

    def start(self) -> "ShardedClusterService":
        for svc in self._shards:
            svc.start()
        return self

    def stop(self, checkpoint: bool = True, timeout: float = 60.0) -> None:
        for svc in self._shards:
            svc.stop(checkpoint=checkpoint, timeout=timeout)

    def __enter__(self) -> "ShardedClusterService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- ingest side ----------------------------------------------------

    def submit(
        self, batch: np.ndarray, *, block: bool = True, timeout=None
    ) -> bool:
        """Route one micro-batch across the shards (:func:`shard_of`)
        and enqueue each non-empty slice on its shard's ingest queue.
        False when ANY shard refused its slice (backpressure, same
        semantics as the unsharded service)."""
        b = np.asarray(batch, dtype=np.float64)
        if b.ndim != 2 or b.shape[1] < 2:
            raise ValueError(f"batch must be [B, >=2], got {b.shape}")
        if len(b) == 0:
            return True
        owner = shard_of(b, self._eps, self.n_shards)
        ok = True
        for s in range(self.n_shards):
            rows = b[owner == s]
            if len(rows) == 0:
                continue
            ok = (
                self._shards[s].submit(rows, block=block, timeout=timeout)
                and ok
            )
        return ok

    def replay(self, batches) -> int:
        """Resume helper: re-ingest the tail of a known batch sequence
        after a restore, giving each shard EXACTLY the slices its
        restored epoch says it has not ingested yet. Correct because
        routing is a pure function of coordinates: shard ``s``'s epoch
        counts the non-empty slices it completed, in sequence order, so
        replay walks the sequence, re-derives each batch's slices, and
        skips the first ``n_updates[s]`` non-empty ones. Returns the
        number of slices actually re-submitted."""
        done = [svc.health()["n_updates"] for svc in self._shards]
        seen = [0] * self.n_shards
        sent = 0
        for b in batches:
            b = np.asarray(b, dtype=np.float64)
            owner = shard_of(b, self._eps, self.n_shards)
            for s in range(self.n_shards):
                rows = b[owner == s]
                if len(rows) == 0:
                    continue
                seen[s] += 1
                if seen[s] > done[s]:
                    self._shards[s].submit(rows)
                    sent += 1
        return sent

    def drain(self, timeout: float = 300.0) -> bool:
        deadline = time.monotonic() + timeout
        for svc in self._shards:
            if not svc.drain(timeout=max(0.0, deadline - time.monotonic())):
                return False
        return True

    def _on_shard_publish(self, shard: int, snap: Snapshot) -> None:
        """Fold one shard's freshly-published snapshot into the next
        consistent cut (runs on that shard's ingest thread — the N
        writers of the cut seqlock, serialized by the cut lock)."""
        sc = ShardCut(
            epoch=snap.epoch,
            spts=snap.spts,
            gsids=namespace_sids(snap.sids, shard, self.n_shards),
            k=snap.k,
            snap=snap,
        )
        with self._cut_lock:
            _tsan.access("serve.cut")
            shards = list(self._cut.shards)
            shards[shard] = sc
            epochs = tuple(s.epoch for s in shards)
            new = Cut(self._cut.cut_id + 1, epochs, tuple(shards))
            self._publishing_shard = shard
            self._cut_seq += 1  # odd: cut publish in flight
            self._cut = new
            self._cut_seq += 1  # even: stable
            self._publishing_shard = None
            if self._cut_log is not None:
                self._cut_log.append(new)
            listeners = tuple(self._listeners)
        obs.gauge("serve.cut_id", new.cut_id)
        obs.event(
            "serve.cut_publish",
            shard=shard,
            cut=new.cut_id,
            epochs=list(epochs),
        )
        # broadcast OUTSIDE the seqlock (device transfers under it
        # would starve readers); listeners drop stale cut_ids, so two
        # shards racing here can never regress a replica's cut
        for fn in listeners:
            fn(new)

    # --- read side ------------------------------------------------------

    def cut(self) -> Cut:
        """Pin one published consistent cut (bounded seqlock read):
        every shard's epoch in the returned vector comes from the same
        publish — never a blend of two cuts."""
        deadline = None
        while True:
            s0 = self._cut_seq
            if not (s0 & 1):
                cut = self._cut
                if self._cut_seq == s0:
                    return cut
            if deadline is None:
                timeout = float(config.env("DBSCAN_SERVE_READ_TIMEOUT_S"))
                deadline = time.monotonic() + timeout
            elif time.monotonic() >= deadline:
                stale = self._publishing_shard
                raise RuntimeError(
                    f"serve: consistent-cut read starved for "
                    f"{timeout:.3g}s — shard "
                    f"{stale if stale is not None else '?'}'s cut "
                    "publish never completed (wedged writer holds an "
                    "odd cut epoch); raise DBSCAN_SERVE_READ_TIMEOUT_S "
                    "if the publish is legitimately that slow"
                )
            time.sleep(0)  # yield to the publishing shard thread

    def add_listener(self, fn: Callable[[Cut], None]) -> None:
        """Subscribe to cut publishes (the router's broadcast feed);
        the current cut is delivered immediately so a late subscriber
        starts warm."""
        with self._cut_lock:
            _tsan.access("serve.cut")
            self._listeners.append(fn)
            cut = self._cut
        if cut.cut_id:
            fn(cut)

    def query(self, points: np.ndarray) -> ShardedQueryResult:
        """Answer one batch against the union skeleton of a pinned
        consistent cut — the DIRECT read path (no router): one
        ``serve.query`` dispatch per non-empty shard, each through that
        shard's dedicated pull engine, folded by the cross-shard
        min/sum algebra."""
        pts = np.asarray(points, dtype=np.float64)
        if pts.ndim != 2 or pts.shape[1] < 2:
            raise ValueError(f"query points must be [N, >=2], got {pts.shape}")
        cut = self.cut()
        cfg = self.config
        ncols = 2 if cfg.metric == "euclidean" else pts.shape[1]
        qpts = pts[:, :ncols]
        t_q = time.perf_counter()
        with obs.span(
            "serve.query", cut=int(cut.cut_id), points=int(len(pts))
        ):
            answers = [
                query_mod.batched_query(
                    qpts,
                    sc.spts,
                    sc.gsids,
                    cfg.eps,
                    cfg.min_points,
                    cfg.metric,
                    floors=self._floors,
                    engine=self._shards[s]._pull,
                    site=self._shards[s]._site,
                )
                for s, sc in enumerate(cut.shards)
                if sc.k > 0
            ]
            ans = combine_answers(answers, len(pts), cfg.min_points)
        obs.count("serve.queries")
        obs.count("serve.query_points", int(len(pts)))
        obs_live.observe("serve.query_ms", (time.perf_counter() - t_q) * 1e3)
        obs_live.bump("serve.queries")
        return ShardedQueryResult(ans.gids, ans.core, ans.counts, cut.epochs)

    def resolve(self, ids: np.ndarray) -> np.ndarray:
        """Map previously-answered GLOBAL gids to their current
        canonical ids: un-stride to the owning shard's local id space,
        resolve through that shard's union-find, re-stride."""
        ids = np.asarray(ids, np.int64)
        out = ids.copy()
        pos = ids > 0
        owner = np.where(pos, (ids - 1) % self.n_shards, -1)
        for s in range(self.n_shards):
            mask = owner == s
            if not mask.any():
                continue
            local = (ids[mask] - 1) // self.n_shards + 1
            res = np.asarray(self._shards[s].resolve(local), np.int64)
            out[mask] = np.where(
                res > 0, (res - 1) * self.n_shards + s + 1, 0
            )
        return out

    # --- health / checkpoint --------------------------------------------

    def health(self) -> dict:
        """Fleet poll endpoint: the cut id + epoch vector, plus every
        shard's own health dict (queue depth, degradation, faults)."""
        cut = self.cut()
        shards = [svc.health() for svc in self._shards]
        out = {
            "n_shards": self.n_shards,
            "cut_id": cut.cut_id,
            "epochs": list(cut.epochs),
            "resident_points": int(sum(sc.k for sc in cut.shards)),
            "degraded": [
                s for s, h in enumerate(shards) if h["degraded"]
            ],
            "shards": shards,
        }
        out.update(slo_mod.windowed_health())
        return out

    def checkpoint(self, quiet: bool = False) -> List[Optional[str]]:
        """Persist every shard's last published snapshot under its
        shard-suffixed path; per-shard SIGTERM hooks do the same on the
        flight recorder's signal path (each shard registered its own
        hook at start())."""
        return [svc.checkpoint(quiet=quiet) for svc in self._shards]
