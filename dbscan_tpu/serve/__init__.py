"""dbscan_tpu.serve — the resident serving layer.

Two legs over the batch/streaming engines (ROADMAP: "a real serving
system"):

- **online** — :class:`ClusterService` (serve/service.py): a long-lived
  server whose ingest thread drives streaming micro-batch updates while
  concurrent readers answer ``query(points) -> (gid, core_flag)``
  against the last published snapshot epoch (serve/query.py), with
  backpressure/health from the obs counters and SIGTERM-safe
  checkpoint/restore through parallel/checkpoint.py;
- **batch tenancy** — :class:`JobBatcher` + :class:`AdmissionController`
  (serve/tenancy.py): thousands of small independent clustering jobs
  pad-and-stacked into single ``serve.jobs`` dispatches (zero
  recompiles across a mixed job stream), admission-priced against the
  graftshape HBM model before anything is dispatched;
- **distributed** — :class:`ShardedClusterService` (serve/sharded.py)
  partitions the resident ingest across N shards publishing epoch-VECTOR
  consistent cuts, and :class:`QueryRouter` (serve/router.py) replicates
  reads across N failover replicas with cut broadcast and priced load
  shedding — zero failed queries under any schedule of replica kills.

``python -m dbscan_tpu.serve`` serves a synthetic stream and prints
health/QPS (serve/__main__.py); ``cli.py --serve`` runs the same demo.
"""

from dbscan_tpu.serve.query import QueryAnswer, batched_query, query_host
from dbscan_tpu.serve.router import QueryRouter, QueryShed
from dbscan_tpu.serve.service import (
    ClusterService,
    QueryResult,
    Snapshot,
    stream_fingerprint,
)
from dbscan_tpu.serve.sharded import (
    Cut,
    ShardCut,
    ShardedClusterService,
    ShardedQueryResult,
    cut_query_host,
    shard_of,
)
from dbscan_tpu.serve.tenancy import (
    AdmissionController,
    AdmissionRejected,
    JobBatcher,
    JobResult,
)

__all__ = [
    "AdmissionController",
    "AdmissionRejected",
    "ClusterService",
    "Cut",
    "JobBatcher",
    "JobResult",
    "QueryAnswer",
    "QueryResult",
    "QueryRouter",
    "QueryShed",
    "ShardCut",
    "ShardedClusterService",
    "ShardedQueryResult",
    "Snapshot",
    "batched_query",
    "cut_query_host",
    "query_host",
    "shard_of",
    "stream_fingerprint",
]
