"""Loader + numpy-fallback wrappers for the native host kernels.

The driver's host phases (binning, packing, merge) are the pipeline
bottleneck on the 1-vCPU deployment host; ``native/hostops.cpp`` provides
fused single-pass C++ versions of the hottest primitives. This module
builds the shared library on first use with the system ``g++`` (cached
next to the source, keyed on mtime), binds it via ctypes, and exposes
numpy-identical wrappers that silently fall back to numpy when the
toolchain or library is unavailable (or when ``DBSCAN_TPU_NATIVE=0``).

No pybind11 in the image, hence ctypes over raw C ABI; every wrapper's
output is bit-identical to its numpy fallback (tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO, "native", "hostops.cpp")
_SO = os.path.join(_REPO, "native", "build", "hostops.so")

_lib: Optional[ctypes.CDLL] = None
_lib_failed = False
# The lazy load is reached from BOTH the main thread and the pull-engine
# worker (extract_prefix under _group_rows jobs): unguarded, two threads
# could race the build/dlopen and bind argtypes on a half-initialized
# handle. Double-checked: the fast path stays a plain read.
_load_lock = threading.Lock()

_I64P = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_I8P = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
_U8P = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_U32P = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
_U64P = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
_F32P = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_F64P = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    # compile to a per-pid temp then rename: os.replace is atomic, so a
    # concurrent importer can never dlopen a half-written library
    tmp = f"{_SO}.{os.getpid()}.tmp"
    # no -march=native: the kernels are memory-bound (nothing here
    # vectorizes past baseline), and a cached .so must not SIGILL when the
    # checkout moves to an older CPU (container images, shared volumes)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native hostops build failed (%s); using numpy", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (numpy fallbacks apply).
    Thread-safe: the main thread and the pull-engine worker both land
    here; the settled fast path is one unlocked read of the latch."""
    if _lib is not None or _lib_failed:
        return _lib
    with _load_lock:
        if _lib is not None or _lib_failed:
            return _lib
        return _load_locked()


def _load_locked() -> Optional[ctypes.CDLL]:
    """Build/load/bind under ``_load_lock`` (caller holds it)."""
    global _lib, _lib_failed
    from dbscan_tpu.config import env as _env

    if not _env("DBSCAN_TPU_NATIVE") or not os.path.exists(_SRC):
        _lib_failed = True
        return None
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(
        _SRC
    ):
        if not _build():
            _lib_failed = True
            return None
    try:
        L = ctypes.CDLL(_SO)
        L.radix_argsort_u32.argtypes = [_U32P, ctypes.c_int64, _I32P]
        L.radix_argsort_u64.argtypes = [_U64P, ctypes.c_int64, _I32P]
        L.group_by_u32.argtypes = [
            _U32P, ctypes.c_int64, _I32P, _I32P, _U32P, _I64P,
        ]
        L.group_by_u32.restype = ctypes.c_int64
        L.group_by_u64.argtypes = [
            _U64P, ctypes.c_int64, _I32P, _I32P, _U64P, _I64P,
        ]
        L.group_by_u64.restype = ctypes.c_int64
        L.prefix_maps.argtypes = [_I64P, ctypes.c_int64, _I32P, _I32P]
        L.repeat_i64.argtypes = [_I64P, _I64P, ctypes.c_int64, _I64P]
        L.extract_prefix_i64.argtypes = [
            _I64P, _I64P, ctypes.c_int64, ctypes.c_int64, _I64P,
        ]
        L.extract_prefix_i32.argtypes = [
            _I32P, _I64P, ctypes.c_int64, ctypes.c_int64, _I32P,
        ]
        L.extract_prefix_i8.argtypes = [
            _I8P, _I64P, ctypes.c_int64, ctypes.c_int64, _I8P,
        ]
        L.cell_keys.argtypes = [
            _F64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_double,
            _U64P, _I64P,
        ]
        L.cell_keys.restype = ctypes.c_int64
        L.classify_instances.argtypes = [
            _F64P, ctypes.c_int64, _I64P, _I64P, _I64P, _F64P, _F64P,
            _I64P, _I64P, ctypes.c_int64, _U8P, _U8P,
        ]
        L.fine_cells.argtypes = [
            _F64P, ctypes.c_int64, _I64P, _I64P, _F64P, ctypes.c_double,
            ctypes.c_int64, ctypes.c_uint8, _I64P, _I64P, _I64P, _I64P,
        ]
        _U16P = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
        pack_common = [
            _I64P, ctypes.c_int64, ctypes.c_int64, _I64P, _I64P, _I64P,
            _F64P, ctypes.c_int64, _I64P, _I64P, _I64P, _I32P, _I32P,
            _I32P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64,  # d_out payload columns
        ]

        def pack_outs(run_p):
            return [_U8P, _I64P, _I32P, run_p, run_p, _I32P, _I64P]

        L.pack_banded_group_f32.argtypes = (
            pack_common + [_F32P] + pack_outs(_I32P)
        )
        L.pack_banded_group_f64.argtypes = (
            pack_common + [_F64P] + pack_outs(_I32P)
        )
        L.pack_banded_group_f32_u16.argtypes = (
            pack_common + [_F32P] + pack_outs(_U16P)
        )
        L.pack_banded_group_f64_u16.argtypes = (
            pack_common + [_F64P] + pack_outs(_U16P)
        )
        L.cell_runs.argtypes = [
            _I64P, ctypes.c_int64, _U8P, _U8P, _I64P, _I64P, _I64P,
        ]
        L.cell_runs.restype = ctypes.c_int64
        L.halo_candidates.argtypes = [
            _I64P, _I64P, ctypes.c_int64, _I64P, _I32P, _F64P,
            ctypes.c_int64, _F64P, _I64P, _I64P,
        ]
        L.halo_candidates.restype = ctypes.c_int64
        L.build_inst_gid.argtypes = [
            _U8P, _I32P, _I64P, ctypes.c_int64, _I32P,
        ]
        L.scatter_sel.argtypes = [
            _I64P, _I64P, _I32P, _I8P, ctypes.c_int64, _I32P, _I8P, _U8P,
        ]
        L.uf_assign_gids.argtypes = [
            _I64P, _I64P, ctypes.c_int64, ctypes.c_int64, _I64P,
        ]
        L.uf_assign_gids.restype = ctypes.c_int64
        L.band_dedup.argtypes = [
            _I64P, ctypes.c_int64, _I64P, _I8P, _I64P, ctypes.c_int64,
            _I64P,
        ]
        L.band_dedup.restype = ctypes.c_int64
    except OSError as e:
        logger.warning("native hostops load failed (%s); using numpy", e)
        _lib_failed = True
        return None
    _lib = L
    return _lib


def argsort_ints(keys: np.ndarray) -> np.ndarray:
    """Stable argsort of a NONNEGATIVE integer array — drop-in for
    ``np.argsort(keys, kind="stable")`` at the driver's sort sites (all of
    which construct nonnegative packed keys by design). Returns int32
    indices (every caller's array length fits; half the sort traffic)."""
    keys = np.ascontiguousarray(keys)
    L = lib()
    if L is None or keys.size == 0 or keys.size >= 2**31:
        return np.argsort(keys, kind="stable")
    order = np.empty(keys.size, dtype=np.int32)
    if keys.dtype in (np.int32, np.uint32):
        L.radix_argsort_u32(keys.view(np.uint32), keys.size, order)
    elif keys.dtype in (np.int64, np.uint64):
        L.radix_argsort_u64(keys.view(np.uint64), keys.size, order)
    else:
        return np.argsort(keys, kind="stable")
    return order


def prefix_maps(counts: np.ndarray):
    """(rows, slots) int32 maps for the packers' prefix-slot layout, or
    None when the native library is unavailable."""
    L = lib()
    if L is None:
        return None
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    total = int(counts.sum())
    rows = np.empty(total, dtype=np.int32)
    slots = np.empty(total, dtype=np.int32)
    L.prefix_maps(counts, len(counts), rows, slots)
    return rows, slots


def repeat_i64(vals: np.ndarray, counts: np.ndarray):
    """np.repeat(vals, counts) for int64 vals, or None if unavailable."""
    L = lib()
    if L is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    out = np.empty(int(counts.sum()), dtype=np.int64)
    L.repeat_i64(vals, counts, len(counts), out)
    return out


def extract_prefix(src: np.ndarray, counts: np.ndarray):
    """Gather each row's valid prefix from a [P, B] buffer into one flat
    array (the packers' layout invariant), or None if unavailable."""
    L = lib()
    if L is None:
        return None
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    p, b = src.shape
    out = np.empty(int(counts.sum()), dtype=src.dtype)
    src = np.ascontiguousarray(src)
    if src.dtype == np.int64:
        L.extract_prefix_i64(src, counts, p, b, out)
    elif src.dtype == np.int32:
        L.extract_prefix_i32(src, counts, p, b, out)
    elif src.dtype in (np.int8, np.uint8, np.bool_):
        L.extract_prefix_i8(src.view(np.int8), counts, p, b, out.view(np.int8))
    else:
        return None
    return out


def cell_keys(pts: np.ndarray, cell_size: float):
    """Fused 2eps-grid snap + composite row-major key pass. Returns
    (key [N] uint64, mnx, mny, span_x, span_y) or None when unavailable
    or the span product would overflow the key space."""
    L = lib()
    if L is None:
        return None
    pts = np.ascontiguousarray(pts, dtype=np.float64)
    n = len(pts)
    key = np.empty(n, dtype=np.uint64)
    bounds = np.empty(4, dtype=np.int64)
    ok = L.cell_keys(pts, pts.shape[1], n, float(cell_size), key, bounds)
    if not ok:
        return None
    return key, int(bounds[0]), int(bounds[1]), int(bounds[2]), int(bounds[3])


def classify_instances(
    pts: np.ndarray,
    cells: np.ndarray,
    cell_inv: np.ndarray,
    rects_int: np.ndarray,
    inner: np.ndarray,
    main_r: np.ndarray,
    inst_part: np.ndarray,
    inst_ptidx: np.ndarray,
):
    """Fused native _classify_instances pass. Returns (band_any [N] bool,
    inst_inner [M] bool) or None when the native library is unavailable
    (caller runs the numpy formulation)."""
    L = lib()
    if L is None:
        return None
    pts = np.ascontiguousarray(pts, dtype=np.float64)
    m = len(inst_part)
    band_any = np.zeros(len(pts), dtype=np.uint8)
    inst_inner = np.zeros(m, dtype=np.uint8)
    L.classify_instances(
        pts, pts.shape[1],
        np.ascontiguousarray(cells, dtype=np.int64),
        np.ascontiguousarray(cell_inv, dtype=np.int64),
        np.ascontiguousarray(rects_int, dtype=np.int64),
        np.ascontiguousarray(inner, dtype=np.float64),
        np.ascontiguousarray(main_r, dtype=np.float64),
        np.ascontiguousarray(inst_part, dtype=np.int64),
        np.ascontiguousarray(inst_ptidx, dtype=np.int64),
        m, band_any, inst_inner,
    )
    return band_any.view(bool), inst_inner.view(bool)


def fine_cells(
    pts: np.ndarray,
    point_idx: np.ndarray,
    part_ids: np.ndarray,
    outer: np.ndarray,
    inv_cell: float,
    n_parts: int,
    is_f32: bool,
):
    """Fused fine-grid cell assignment (bucketize_banded's gather + cast +
    snap + reduceat-maxima block). Returns (cx [M], cy [M], cxmax [P],
    cymax [P]) int64 arrays, or None when the native library is
    unavailable."""
    L = lib()
    if L is None:
        return None
    pts = np.ascontiguousarray(pts, dtype=np.float64)
    m = len(point_idx)
    cx = np.empty(m, dtype=np.int64)
    cy = np.empty(m, dtype=np.int64)
    cxmax = np.zeros(n_parts, dtype=np.int64)
    cymax = np.zeros(n_parts, dtype=np.int64)
    L.fine_cells(
        pts, pts.shape[1],
        np.ascontiguousarray(point_idx, dtype=np.int64),
        np.ascontiguousarray(part_ids, dtype=np.int64),
        np.ascontiguousarray(outer, dtype=np.float64),
        float(inv_cell), m, 1 if is_f32 else 0, cx, cy, cxmax, cymax,
    )
    return cx, cy, cxmax, cymax


def pack_banded_group(
    sel_parts: np.ndarray,
    p_pad: int,
    part_start: np.ndarray,
    counts: np.ndarray,
    order: np.ndarray,
    pts: np.ndarray,
    point_idx: np.ndarray,
    cx_s: np.ndarray,
    cell_rank: np.ndarray,
    ustarts: np.ndarray,
    uspans: np.ndarray,
    sstart: np.ndarray,
    maxnb: int,
    tblock: int,
    b: int,
    dtype,
    run_dtype=np.int32,
    d_out: int = 2,
):
    """Fused banded group packing: one sequential native pass fills all
    eight group buffers (see native/hostops.cpp). ``run_dtype`` selects
    the run-table element type (uint16 when the slab bound fits — halves
    the largest device upload); ``d_out`` the payload column count (2 for
    planar coordinates, 3 for spherical-chord kernel coordinates).
    Returns (buf, mask, idx, fold, st, sp, cx, cgid) or None when the
    native library is unavailable."""
    L = lib()
    if L is None or dtype not in (np.float32, np.float64):
        return None
    if ustarts.shape[1] != 5 or uspans.shape[1] != 5:
        raise ValueError(
            "native packer is compiled for BANDED_ROWS == 5 window rows; "
            f"got run tables of width {ustarts.shape[1]}"
        )
    pts = np.ascontiguousarray(pts, dtype=np.float64)
    if pts.shape[1] < d_out:
        raise ValueError(f"payload wants {d_out} columns, pts has {pts.shape[1]}")
    buf = np.empty((p_pad, b, d_out), dtype=dtype)
    mask = np.empty((p_pad, b), dtype=np.uint8)
    idx = np.empty((p_pad, b), dtype=np.int64)
    fold = np.empty((p_pad, b), dtype=np.int32)
    st = np.empty((p_pad, b, 5), dtype=run_dtype)
    sp = np.empty((p_pad, b, 5), dtype=run_dtype)
    cxb = np.empty((p_pad, b), dtype=np.int32)
    cgid = np.empty((p_pad, b), dtype=np.int64)
    fn = {
        (np.float32, np.int32): L.pack_banded_group_f32,
        (np.float64, np.int32): L.pack_banded_group_f64,
        (np.float32, np.uint16): L.pack_banded_group_f32_u16,
        (np.float64, np.uint16): L.pack_banded_group_f64_u16,
    }[(np.dtype(dtype).type, np.dtype(run_dtype).type)]
    fn(
        np.ascontiguousarray(sel_parts, dtype=np.int64),
        len(sel_parts), p_pad,
        np.ascontiguousarray(part_start, dtype=np.int64),
        np.ascontiguousarray(counts, dtype=np.int64),
        np.ascontiguousarray(order, dtype=np.int64),
        pts, pts.shape[1],
        np.ascontiguousarray(point_idx, dtype=np.int64),
        np.ascontiguousarray(cx_s, dtype=np.int64),
        np.ascontiguousarray(cell_rank, dtype=np.int64),
        np.ascontiguousarray(ustarts, dtype=np.int32),
        np.ascontiguousarray(uspans, dtype=np.int32),
        np.ascontiguousarray(sstart, dtype=np.int32),
        maxnb, tblock, b, d_out,
        buf, mask, idx, fold, st, sp, cxb, cgid,
    )
    return buf, mask.view(bool), idx, fold, st, sp, cxb, cgid


def cell_runs(cg: np.ndarray):
    """Fused cell-run extraction over a flat cell-id array. Returns
    (segflags [m] bool, valid [m] bool, starts [U], ends [U], gids [U])
    or None when the native library is unavailable."""
    L = lib()
    if L is None:
        return None
    cg = np.ascontiguousarray(cg, dtype=np.int64)
    m = cg.size
    segflags = np.empty(m, dtype=np.uint8)
    valid = np.empty(m, dtype=np.uint8)
    st = np.empty(m, dtype=np.int64)
    en = np.empty(m, dtype=np.int64)
    gid = np.empty(m, dtype=np.int64)
    u = L.cell_runs(cg, m, segflags, valid, st, en, gid)
    # copies, not views: a view of the full m-sized scratch would keep
    # ~24 B per flat slot alive for the whole compact pass on the
    # memory-constrained host
    return (
        segflags.view(bool), valid.view(bool),
        st[:u].copy(), en[:u].copy(), gid[:u].copy(),
    )


def halo_candidates(
    ccell: np.ndarray,
    cpart: np.ndarray,
    cstart: np.ndarray,
    order_pts: np.ndarray,
    pts: np.ndarray,
    outer: np.ndarray,
    capacity: int,
):
    """Expand candidate (cell, partition) pairs to their contained points
    (grown-rect inclusive containment) in one pass. Returns (part [H],
    pt [H]) or None when the native library is unavailable."""
    L = lib()
    if L is None:
        return None
    pts = np.ascontiguousarray(pts, dtype=np.float64)
    out_part = np.empty(capacity, dtype=np.int64)
    out_pt = np.empty(capacity, dtype=np.int64)
    h = L.halo_candidates(
        np.ascontiguousarray(ccell, dtype=np.int64),
        np.ascontiguousarray(cpart, dtype=np.int64),
        len(ccell),
        np.ascontiguousarray(cstart, dtype=np.int64),
        np.ascontiguousarray(order_pts, dtype=np.int32),
        pts, pts.shape[1],
        np.ascontiguousarray(outer, dtype=np.float64),
        out_part, out_pt,
    )
    return out_part[:h], out_pt[:h]


def build_inst_gid(labeled: np.ndarray, urank: np.ndarray, gid_of_u: np.ndarray):
    """Per-instance global cluster id (0 at unlabeled rows) in one sweep,
    or None when the native library is unavailable."""
    L = lib()
    if L is None:
        return None
    m = labeled.size
    gid = np.empty(m, dtype=np.int32)
    L.build_inst_gid(
        np.ascontiguousarray(labeled, dtype=np.uint8),
        np.ascontiguousarray(urank, dtype=np.int32),
        np.ascontiguousarray(gid_of_u, dtype=np.int64),
        m, gid,
    )
    return gid


def scatter_sel(
    sel: np.ndarray,
    inst_ptidx: np.ndarray,
    inst_gid: np.ndarray,
    inst_flag: np.ndarray,
    res_cluster: np.ndarray,
    res_flag: np.ndarray,
    assigned: np.ndarray,
) -> bool:
    """Apply selected instances' (gid, flag) to the per-point outputs in
    one sweep. Returns False when the native library is unavailable."""
    L = lib()
    if L is None:
        return False
    L.scatter_sel(
        np.ascontiguousarray(sel, dtype=np.int64),
        np.ascontiguousarray(inst_ptidx, dtype=np.int64),
        np.ascontiguousarray(inst_gid, dtype=np.int32),
        np.ascontiguousarray(inst_flag, dtype=np.int8),
        len(sel), res_cluster, res_flag, assigned.view(np.uint8),
    )
    return True


def uf_assign_gids(edge_a: np.ndarray, edge_b: np.ndarray, n_nodes: int):
    """Union-find over rank-keyed cluster edges + dense 1-based global-id
    assignment in node-rank order (= the unique table's deterministic
    (part, loc) order). Returns (n_clusters, gid_of_u [K] int64) or None
    when the native library is unavailable or an endpoint is out of range
    (caller falls back to the Python union-find)."""
    L = lib()
    if L is None:
        return None
    gid = np.empty(n_nodes, dtype=np.int64)
    nc = L.uf_assign_gids(
        np.ascontiguousarray(edge_a, dtype=np.int64),
        np.ascontiguousarray(edge_b, dtype=np.int64),
        len(edge_a),
        n_nodes,
        gid,
    )
    if nc < 0:
        return None
    return int(nc), gid


def band_dedup(
    ci: np.ndarray,
    inst_ptidx: np.ndarray,
    inst_flag: np.ndarray,
    inst_part: np.ndarray,
    p_true: int,
):
    """Keep one candidate instance per point — best flag, then lowest
    partition (the finalize_merge band dedup) — in one fused pass.
    Returns the kept instance rows, or None when the native library is
    unavailable."""
    L = lib()
    if L is None:
        return None
    ci = np.ascontiguousarray(ci, dtype=np.int64)
    ck = np.empty(len(ci), dtype=np.int64)
    m = L.band_dedup(
        ci,
        len(ci),
        np.ascontiguousarray(inst_ptidx, dtype=np.int64),
        np.ascontiguousarray(inst_flag, dtype=np.int8),
        np.ascontiguousarray(inst_part, dtype=np.int64),
        p_true,
        ck,
    )
    return ck[:m]


def group_by_ints(keys: np.ndarray):
    """Fused group-by of nonnegative integer keys.

    Returns (uniq [U] ascending, inverse [N] dense rank per element,
    counts [U], order [N] stable sort order) — the native superset of
    ops/geometry.py::group_by_int_key (which discards ``order``). None if
    the native library is unavailable (caller falls back to numpy).
    """
    keys = np.ascontiguousarray(keys)
    L = lib()
    if L is None or keys.size >= 2**31:
        return None
    n = keys.size
    order = np.empty(n, dtype=np.int32)
    inverse = np.empty(n, dtype=np.int32)
    uniq = np.empty(n, dtype=keys.dtype)
    counts = np.empty(n, dtype=np.int64)
    if keys.dtype in (np.int32, np.uint32):
        u = L.group_by_u32(
            keys.view(np.uint32), n, order, inverse,
            uniq.view(np.uint32), counts,
        )
    elif keys.dtype in (np.int64, np.uint64):
        u = L.group_by_u64(
            keys.view(np.uint64), n, order, inverse,
            uniq.view(np.uint64), counts,
        )
    else:
        return None
    return uniq[:u], inverse, counts[:u], order
