"""Streaming micro-batch DBSCAN with persistent cluster identities.

The reference has no streaming mode; this implements BASELINE.json
configs[4] ("Spark Streaming micro-batch DBSCAN (incremental; reuse TPU
partition buffers)") on the batch pipeline:

Each ``update(batch)`` clusters the new batch TOGETHER with a sliding
window of recently-seen core points (the density skeleton of earlier
batches), then carries cluster identity forward: a fresh cluster that
contains a window core point inherits that point's stream id; clusters
bridging several old ids merge them (tracked in a union-find, so earlier
emitted labels stay resolvable via :meth:`resolve`); clusters touching no
window point get a new stream id.

Device-buffer reuse falls out of the batch pipeline's static bucketing
(parallel/binning.py): padded bucket shapes repeat across micro-batches of
similar size, so every update after the first hits the jit cache instead of
recompiling — the TPU analog of reusing executor-resident partition state.

Semantics notes (documented, inherent to windowed streaming):
- density is evaluated against the window skeleton, not all history: a
  point is core if its eps-neighborhood within (batch + window cores)
  reaches min_points. Only core points persist in the window — border and
  noise points of a batch do not densify later batches.
- a cluster split across batches keeps the elder id for both halves (ids
  never un-merge).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, NamedTuple, Optional, Tuple

import numpy as np

from dbscan_tpu.config import DBSCANConfig, Engine, Precision
from dbscan_tpu.ops.labels import CORE
from dbscan_tpu.parallel.driver import train_arrays


class _MinUnionFind:
    """Union-find over positive int stream ids where the component root is
    always the MINIMUM id — the "elder id wins" rule needs the canonical id
    to be deterministic, which weighted union does not guarantee. Tracks the
    live-root count incrementally so callers never scan all ids ever made."""

    def __init__(self):
        self._parent: dict = {}
        self.n_roots = 0

    def find(self, x: int) -> int:
        parent = self._parent
        if x not in parent:
            parent[x] = x
            self.n_roots += 1
            return x
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    def union(self, a: int, b: int) -> int:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return ra
        lo, hi = (ra, rb) if ra < rb else (rb, ra)
        self._parent[hi] = lo
        self.n_roots -= 1
        return lo


class StreamUpdate(NamedTuple):
    clusters: np.ndarray  # [B] stream-stable cluster ids; 0 = noise
    flags: np.ndarray  # [B] int8 Core/Border/Noise for the new batch
    n_stream_clusters: int  # distinct live stream ids so far
    stats: dict


class StreamingDBSCAN:
    """Micro-batch DBSCAN front-end over the distributed batch pipeline.

    window: number of past micro-batches whose core points stay in the
    density skeleton. mesh: optional device mesh, as in train().
    """

    def __init__(
        self,
        eps: float,
        min_points: int,
        max_points_per_partition: int = 250,
        *,
        window: int = 3,
        engine: Engine = Engine.ARCHERY,
        precision: Precision = Precision.F32,
        use_pallas: bool = False,
        mesh=None,
        config: Optional[DBSCANConfig] = None,
    ):
        self.config = config or DBSCANConfig(
            eps=eps,
            min_points=min_points,
            max_points_per_partition=max_points_per_partition,
            engine=engine,
            precision=precision,
            use_pallas=use_pallas,
        )
        self.config.validate()
        self.window = int(window)
        if self.window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
        self.mesh = mesh
        # (core points [K, 2], their stream ids [K]) per retained batch
        self._window: Deque[Tuple[np.ndarray, np.ndarray]] = deque(
            maxlen=self.window if self.window > 0 else None
        )
        self._uf = _MinUnionFind()
        self._next_id = 1
        self._n_updates = 0

    def _window_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        if not self._window:
            return np.empty((0, 2), np.float64), np.empty(0, np.int64)
        pts = np.concatenate([p for p, _ in self._window])
        ids = np.concatenate([i for _, i in self._window])
        return pts, ids

    def resolve(self, ids: np.ndarray) -> np.ndarray:
        """Map previously-emitted stream ids to their current canonical ids
        (after later batches merged clusters)."""
        ids = np.asarray(ids)
        out = ids.copy()
        for v in np.unique(ids):
            if v > 0:
                out[ids == v] = self._uf.find(int(v))
        return out

    def update(self, batch: np.ndarray) -> StreamUpdate:
        """Ingest one micro-batch; returns stream-stable labels for it."""
        batch = np.asarray(batch, dtype=np.float64)
        if batch.ndim != 2 or batch.shape[1] < 2:
            raise ValueError(f"batch must be [B, >=2], got {batch.shape}")
        self._n_updates += 1
        wpts, wids = self._window_arrays()
        combined = (
            np.concatenate([batch[:, :2], wpts]) if len(wpts) else batch[:, :2]
        )
        out = train_arrays(combined, self.config, mesh=self.mesh)

        b = len(batch)
        batch_cl = out.clusters[:b]
        batch_fl = out.flags[:b]
        win_cl = out.clusters[b:]

        # carry identity: batch-local cluster id -> stream id
        mapping: dict = {}
        # window points vote first (elder ids win: union-by-min)
        for local_id in np.unique(win_cl[win_cl > 0]):
            members = [int(s) for s in np.unique(wids[win_cl == local_id])]
            canon = self._uf.find(members[0])
            for s in members[1:]:
                canon = self._uf.union(canon, s)
            mapping[int(local_id)] = canon
        # re-canonicalize: a later cluster's union may have merged an id
        # assigned earlier in this same update
        mapping = {k: self._uf.find(v) for k, v in mapping.items()}
        for local_id in np.unique(batch_cl[batch_cl > 0]):
            if int(local_id) not in mapping:
                sid = self._next_id
                self._next_id += 1
                self._uf.find(sid)  # register
                mapping[int(local_id)] = sid

        stream_cl = np.zeros(b, dtype=np.int64)
        for local_id, sid in mapping.items():
            stream_cl[batch_cl == local_id] = sid

        # retain this batch's core points in the window skeleton
        core_mask = batch_fl == CORE
        self._window.append(
            (batch[core_mask][:, :2].copy(), stream_cl[core_mask].copy())
        )

        stats = dict(out.stats)
        stats.update(
            n_updates=self._n_updates,
            window_points=int(len(wpts)),
            batch_clusters=int(len(np.unique(batch_cl[batch_cl > 0]))),
        )
        return StreamUpdate(
            clusters=stream_cl,
            flags=batch_fl,
            n_stream_clusters=self._uf.n_roots,
            stats=stats,
        )
