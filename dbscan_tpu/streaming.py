"""Streaming micro-batch DBSCAN with persistent cluster identities.

The reference has no streaming mode; this implements BASELINE.json
configs[4] ("Spark Streaming micro-batch DBSCAN (incremental; reuse TPU
partition buffers)") on the batch pipeline:

Each ``update(batch)`` clusters the new batch TOGETHER with a sliding
window of recently-seen core points (the density skeleton of earlier
batches), then carries cluster identity forward: a fresh cluster that
contains a window core point inherits that point's stream id; clusters
bridging several old ids merge them (tracked in a union-find, so earlier
emitted labels stay resolvable via :meth:`resolve`); clusters touching no
window point get a new stream id.

Device-buffer reuse falls out of the batch pipeline's static bucketing
(parallel/binning.py): padded bucket shapes repeat across micro-batches of
similar size, so every update after the first hits the jit cache instead of
recompiling — the TPU analog of reusing executor-resident partition state.

Semantics notes (documented, inherent to windowed streaming):
- density is evaluated against the window skeleton, not all history: a
  point is core if its eps-neighborhood within (batch + window cores)
  reaches min_points. Only core points persist in the window — border and
  noise points of a batch do not densify later batches.
- a cluster split across batches keeps the elder id for both halves (ids
  never un-merge).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, NamedTuple, Optional, Tuple

import numpy as np

from dbscan_tpu import faults, obs
from dbscan_tpu.config import DBSCANConfig, Engine, Precision
from dbscan_tpu.ops.labels import CORE
from dbscan_tpu.parallel.driver import _cpu_fallback_allowed, train_arrays


class _MinUnionFind:
    """Union-find over positive int stream ids where the component root is
    always the MINIMUM id — the "elder id wins" rule needs the canonical id
    to be deterministic, which weighted union does not guarantee. Tracks the
    live-root count incrementally so callers never scan all ids ever made.

    Stream ids are allocated densely from 1 (:meth:`register_range`), so the
    parent table is a flat numpy array: scalar find/union serve the (few)
    identity-graph edges per update, while :meth:`find_many` resolves whole
    label arrays by vectorized pointer jumping — O(log chain depth) numpy
    rounds, no per-id Python loop."""

    def __init__(self):
        self._parent = np.arange(1, dtype=np.int64)  # slot 0 = noise, unused
        self.n_roots = 0

    def register_range(self, start: int, count: int) -> np.ndarray:
        """Register ids start..start+count-1 as fresh singleton roots;
        returns them."""
        end = start + count
        if end > len(self._parent):
            old = self._parent
            grown = np.arange(max(end, 2 * len(old)), dtype=np.int64)
            grown[: len(old)] = old
            self._parent = grown
        self.n_roots += count
        return np.arange(start, end, dtype=np.int64)

    def find(self, x: int) -> int:
        p = self._parent
        if x >= len(p):  # never registered: a self-root, not counted
            return x
        root = x
        while p[root] != root:
            root = p[root]
        while p[x] != root:
            p[x], x = root, p[x]
        return int(root)

    def find_many(self, ids: np.ndarray) -> np.ndarray:
        """Vectorized find over an id array (unregistered ids map to
        themselves); compresses the touched paths."""
        p = self._parent
        out = np.asarray(ids, dtype=np.int64).copy()
        inb = out < len(p)
        r = p[out[inb]]
        while True:
            nxt = p[r]
            if (nxt == r).all():
                break
            r = p[nxt]  # two jumps per numpy round
        p[out[inb]] = r  # path compression straight to the root
        out[inb] = r
        return out

    def union(self, a: int, b: int) -> int:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return ra
        lo, hi = (ra, rb) if ra < rb else (rb, ra)
        self._parent[hi] = lo
        self.n_roots -= 1
        return lo


class StreamUpdate(NamedTuple):
    clusters: np.ndarray  # [B] stream-stable cluster ids; 0 = noise
    flags: np.ndarray  # [B] int8 Core/Border/Noise for the new batch
    n_stream_clusters: int  # distinct live stream ids so far
    stats: dict


class StreamingDBSCAN:
    """Micro-batch DBSCAN front-end over the distributed batch pipeline.

    window: number of past micro-batches whose core points stay in the
    density skeleton. mesh: optional device mesh, as in train().
    """

    def __init__(
        self,
        eps: float,
        min_points: int,
        max_points_per_partition: int = 250,
        *,
        window: int = 3,
        engine: Engine = Engine.ARCHERY,
        precision: Precision = Precision.F32,
        use_pallas: bool = False,
        mesh=None,
        config: Optional[DBSCANConfig] = None,
    ):
        self.config = config or DBSCANConfig(
            eps=eps,
            min_points=min_points,
            max_points_per_partition=max_points_per_partition,
            engine=engine,
            precision=precision,
            use_pallas=use_pallas,
            # micro-batches must HIT the jit cache at steady state: ladder-
            # pad the per-group partition axis so data-dependent partition
            # counts stop minting fresh shapes every update
            static_partition_pad=True,
        )
        self.config.validate()
        if self.config.shape_floors is None:
            import dataclasses as _dc

            # the ratchet dict must be THE SAME object across updates —
            # it carries the monotone rung state that pins jit shapes
            self.config = _dc.replace(self.config, shape_floors={})
        self.window = int(window)
        if self.window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
        self.mesh = mesh
        # (core points [K, 2], their stream ids [K]) per retained batch
        self._window: Deque[Tuple[np.ndarray, np.ndarray]] = deque(
            maxlen=self.window if self.window > 0 else None
        )
        self._uf = _MinUnionFind()
        self._next_id = 1
        self._n_updates = 0
        self._ncols = None  # clustering columns, fixed by the first batch

    def _window_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        if not self._window:
            return (
                np.empty((0, self._ncols or 2), np.float64),
                np.empty(0, np.int64),
            )
        pts = np.concatenate([p for p, _ in self._window])
        ids = np.concatenate([i for _, i in self._window])
        return pts, ids

    def _cpu_update_fallback(self, combined: np.ndarray):
        """Degradation thunk for one micro-batch: the same batch
        pipeline pinned to the host jax CPU backend (labels identical —
        one algebra, another backend), so a persistently-faulting
        device costs latency, not the stream's cluster identities."""

        def run():
            import jax

            with jax.default_device(jax.devices("cpu")[0]):
                return train_arrays(combined, self.config, mesh=None)

        return run

    def export_state(self) -> dict:
        """Serialize everything future labels depend on — the window
        skeleton (per-batch core points + stream ids, in age order),
        the identity union-find, and the id/update counters — as flat
        arrays + scalars (``{"arrays": ..., "scalars": ...}``, the
        shape :func:`checkpoint.save_serve` persists).

        The contract (pinned by tests/test_serve.py): a stream restored
        from this state produces BYTE-IDENTICAL labels for every later
        batch to the uninterrupted stream — no relabeling drift. The
        export is a deep copy (the caller may hold it across later
        updates: the serving layer snapshots one per completed update),
        built on the updating thread, so it is torn-free by
        construction."""
        lens = np.array([len(p) for p, _ in self._window], np.int64)
        if len(self._window):
            wpts = np.concatenate([p for p, _ in self._window]).copy()
            wids = np.concatenate([i for _, i in self._window]).copy()
        else:
            wpts = np.empty((0, self._ncols or 2), np.float64)
            wids = np.empty(0, np.int64)
        return {
            "arrays": {
                "window_pts": wpts,
                "window_ids": wids,
                "window_lens": lens,
                "uf_parent": self._uf._parent.copy(),
            },
            "scalars": {
                "next_id": int(self._next_id),
                "n_updates": int(self._n_updates),
                "n_roots": int(self._uf.n_roots),
                "ncols": -1 if self._ncols is None else int(self._ncols),
                "window": int(self.window),
            },
        }

    def restore_state(self, state: dict) -> None:
        """Adopt an :meth:`export_state` snapshot: the next
        :meth:`update` continues the stream exactly where the exported
        one would have (same ids, same merges, same window expiry).
        The window length must match this instance's (the deque maxlen
        is construction state, not stream state)."""
        scalars = state["scalars"]
        if int(scalars["window"]) != self.window:
            raise ValueError(
                f"checkpoint was taken at window={scalars['window']}, "
                f"this stream has window={self.window}"
            )
        arrays = state["arrays"]
        self._window.clear()
        start = 0
        for ln in np.asarray(arrays["window_lens"], np.int64):
            ln = int(ln)
            self._window.append(
                (
                    np.asarray(arrays["window_pts"][start : start + ln]),
                    np.asarray(arrays["window_ids"][start : start + ln]),
                )
            )
            start += ln
        self._uf._parent = np.asarray(arrays["uf_parent"], np.int64).copy()
        self._uf.n_roots = int(scalars["n_roots"])
        self._next_id = int(scalars["next_id"])
        self._n_updates = int(scalars["n_updates"])
        ncols = int(scalars["ncols"])
        self._ncols = None if ncols < 0 else ncols

    def resolve(self, ids: np.ndarray) -> np.ndarray:
        """Map previously-emitted stream ids to their current canonical ids
        (after later batches merged clusters). Vectorized — safe to call on
        full label arrays of any size."""
        ids = np.asarray(ids)
        out = ids.copy()
        pos = ids > 0
        if pos.any():
            out[pos] = self._uf.find_many(ids[pos])
        return out

    def update(self, batch: np.ndarray) -> StreamUpdate:
        """Ingest one micro-batch; returns stream-stable labels for it."""
        batch = np.asarray(batch, dtype=np.float64)
        if batch.ndim != 2 or batch.shape[1] < 2:
            raise ValueError(f"batch must be [B, >=2], got {batch.shape}")
        # euclidean clusters on the first two columns only (reference
        # convention); other metrics (haversine lon/lat, cosine
        # embeddings) consume every column, so the window skeleton must
        # carry them all
        ncols = 2 if self.config.metric == "euclidean" else batch.shape[1]
        if self._ncols is None:
            self._ncols = ncols
        elif ncols != self._ncols:
            raise ValueError(
                f"batch has {ncols} clustering columns; this stream "
                f"started with {self._ncols}"
            )
        self._n_updates += 1
        wpts, wids = self._window_arrays()
        combined = (
            np.concatenate([batch[:, :ncols], wpts])
            if len(wpts)
            else batch[:, :ncols]
        )
        # Per-batch supervision (dbscan_tpu/faults.py): the inner
        # dispatches carry their own group-granular retry/degradation;
        # this outer wrapper covers faults that surface at pull/merge
        # time instead. train_arrays is a pure function of host state,
        # so a whole-batch retry is idempotent; the CPU degradation
        # re-runs the batch pinned to the host backend — stream
        # identities survive a dead device instead of dying with it.
        fault_snap = faults.counters.snapshot()
        # The per-batch pulls ride the process-global pull engine
        # (parallel/pipeline.py), whose worker persists across updates —
        # steady-state micro-batches pay no per-update thread spawn.
        # Snapshot/delta gives the WHOLE update's pull accounting,
        # including any batch-level supervised retry this wrapper takes
        # (mirrors the faults delta below).
        from dbscan_tpu.parallel import pipeline as pipe_mod

        pull_pipe = pipe_mod.get_engine()
        pull_snap = pull_pipe.totals() if pull_pipe is not None else None
        obs.ensure_env()
        with obs.span(
            "stream.update",
            update=int(self._n_updates),
            batch=int(len(batch)),
            window_points=int(len(wpts)),
        ):
            out = faults.supervised(
                faults.SITE_STREAM,
                lambda _b: train_arrays(
                    combined, self.config, mesh=self.mesh
                ),
                policy=faults.RetryPolicy.from_config(self.config),
                # same gate as the driver's per-group degradation: in a
                # multi-process job one host re-running the batch on CPU
                # while the others issue mesh collectives would desync
                # the collective sequence — forced off there
                fallback=(
                    self._cpu_update_fallback(combined)
                    if _cpu_fallback_allowed(self.config)
                    else None
                ),
                label=f"update {self._n_updates}",
            )

        b = len(batch)
        batch_cl = out.clusters[:b]
        batch_fl = out.flags[:b]
        win_cl = out.clusters[b:]

        # carry identity: batch-local cluster id -> stream id, all in
        # unique-cluster space (no per-id boolean masking over the batch:
        # that was O(clusters * points), quadratic for dense streams)
        b_pos = batch_cl > 0
        uniq_b = np.unique(batch_cl[b_pos]).astype(np.int64)  # sorted
        sid_of = np.zeros(len(uniq_b), dtype=np.int64)  # 0 = not yet mapped

        # window points vote first (elder ids win: union-by-min): group the
        # (local cluster, window stream id) pairs by one packed-key unique —
        # the union loop below runs over identity-graph EDGES (distinct
        # pairs), not window points
        w_pos = win_cl > 0
        wl = win_cl[w_pos].astype(np.int64)
        ws = wids[w_pos].astype(np.int64)
        if wl.size:
            base = np.int64(self._next_id)  # every stream id < _next_id
            uk = np.unique(wl * base + ws)
            ul, us = np.divmod(uk, base)
            starts = np.flatnonzero(np.r_[True, ul[1:] != ul[:-1]])
            ends = np.r_[starts[1:], len(ul)]
            # target slot in uniq_b per voted cluster (a window-only cluster
            # with no batch member still gets its ids unioned)
            tgt = np.searchsorted(uniq_b, ul[starts])
            tgt_c = np.minimum(tgt, max(0, len(uniq_b) - 1))
            in_batch = (
                uniq_b[tgt_c] == ul[starts] if uniq_b.size
                else np.zeros(len(starts), dtype=bool)
            )
            for i in range(len(starts)):
                a, e = starts[i], ends[i]
                canon = self._uf.find(int(us[a]))
                for s in us[a + 1 : e]:
                    canon = self._uf.union(canon, int(s))
                if in_batch[i]:
                    sid_of[tgt_c[i]] = canon
            # re-canonicalize: a later cluster's union may have merged an id
            # assigned earlier in this same update
            got = sid_of > 0
            if got.any():
                sid_of[got] = self._uf.find_many(sid_of[got])
        # clusters touching no window point get fresh sequential ids
        fresh = sid_of == 0
        n_new = int(fresh.sum())
        if n_new:
            sid_of[fresh] = self._uf.register_range(self._next_id, n_new)
            self._next_id += n_new

        stream_cl = np.zeros(b, dtype=np.int64)
        if uniq_b.size:
            stream_cl[b_pos] = sid_of[
                np.searchsorted(uniq_b, batch_cl[b_pos])
            ]

        # retain this batch's core points in the window skeleton
        core_mask = batch_fl == CORE
        self._window.append(
            (batch[core_mask][:, :ncols].copy(), stream_cl[core_mask].copy())
        )

        stats = dict(out.stats)
        stats.update(
            n_updates=self._n_updates,
            window_points=int(len(wpts)),
            batch_clusters=len(uniq_b),
            # whole-update fault delta: the inner train_arrays delta
            # misses batch-level retries/degradations this wrapper took
            faults=faults.counters.delta(fault_snap),
        )
        if pull_pipe is not None:
            stats["pull"] = pipe_mod.delta_totals(
                pull_snap, pull_pipe.totals()
            )
        # the inner train_arrays flushed BEFORE this update's outer span
        # closed; re-flush so the trace file always contains the last
        # complete stream.update span
        obs.flush()
        return StreamUpdate(
            clusters=stream_cl,
            flags=batch_fl,
            n_stream_clusters=self._uf.n_roots,
            stats=stats,
        )
