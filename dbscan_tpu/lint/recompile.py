"""recompile rules: patterns that mint fresh jit signatures (the storm
``obs/compile.py`` can only observe after the fact).

- ``jit-in-loop``: ``jax.jit(...)`` invoked lexically inside a
  ``for``/``while`` body — every iteration builds a fresh wrapper with
  an empty trace cache. Hoist the jit (module level or an lru_cache
  builder keyed on the static config, the driver's idiom).
- ``jit-scalar-arg``: a call to a KNOWN jitted callable passing a
  Python scalar or tuple literal positionally while the jit declared
  no static_argnums/static_argnames — tuples fail at trace, scalars
  retrace per dtype and silently defeat weak-type reuse when mixed.
- ``dtype-drift``: float64 dtype literals in kernel code (``ops/`` and
  ``parallel/spill_device.py``): ``jnp.float64`` references, string
  ``"float64"`` dtypes flowing into ``jnp.*``/``astype`` calls. The
  kernels are f32/bf16 by design (config.Precision); a float64 constant
  either upcasts a kernel (2x HBM, MXU off the fast path) or retraces
  against the f32 signature. Host-side ``np.*`` float64 (grid
  coordinates, merge precision) is exempt.
"""

from __future__ import annotations

import ast
import os
from typing import List

from dbscan_tpu.lint.core import Finding, Package
from dbscan_tpu.lint.callgraph import _is_jax_jit

_SCALARS = (int, float, bool, str)


def _kernel_file(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    return "ops" in parts or os.path.basename(path) == "spill_device.py"


def _check_jit_in_loop(mod, findings: List[Finding]) -> None:
    class LoopVisitor(ast.NodeVisitor):
        def __init__(self):
            self.loop_depth = 0

        def _loop(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = _loop
        visit_While = _loop
        visit_AsyncFor = _loop

        def visit_Call(self, node: ast.Call):
            if self.loop_depth > 0 and _is_jax_jit(node.func):
                findings.append(
                    Finding(
                        "jit-in-loop",
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        "jax.jit called inside a loop body builds a fresh "
                        "wrapper (empty trace cache) every iteration; "
                        "hoist it to module level or an lru_cache builder",
                    )
                )
            self.generic_visit(node)

    LoopVisitor().visit(mod.tree)


def _is_scalar_or_tuple_literal(arg: ast.AST) -> bool:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, _SCALARS):
        return True
    if isinstance(arg, ast.Tuple):
        return True
    if isinstance(arg, ast.UnaryOp) and isinstance(
        arg.operand, ast.Constant
    ):
        return True
    return False


def _check_scalar_args(pkg: Package, findings: List[Finding]) -> None:
    cg = pkg.callgraph
    for mod in cg.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            has_statics = None
            f = node.func
            if isinstance(f, ast.Name):
                key = (mod.path, f.id)
                if key in cg.jitted_names:
                    has_statics = cg.jitted_names[key]
                else:
                    tgt = mod.from_names.get(f.id)
                    if tgt is not None:
                        m2 = cg.by_modname.get(tgt[0])
                        info = (
                            m2.functions.get(tgt[1]) if m2 is not None else None
                        )
                        if info is not None and info.is_jit_root:
                            has_statics = info.jit_has_statics
            if has_statics is not False:
                continue  # unknown callee, or statics declared
            for i, arg in enumerate(node.args):
                if _is_scalar_or_tuple_literal(arg):
                    findings.append(
                        Finding(
                            "jit-scalar-arg",
                            mod.path,
                            arg.lineno,
                            arg.col_offset,
                            f"positional arg {i} is a Python "
                            "scalar/tuple literal passed to a jitted "
                            "function with no static_argnums/"
                            "static_argnames — tuples fail at trace, "
                            "scalars defeat signature reuse; declare it "
                            "static or pass an array",
                        )
                    )


def _check_dtype_drift(mod, findings: List[Finding]) -> None:
    if not _kernel_file(mod.path):
        return

    def flag(node, what):
        findings.append(
            Finding(
                "dtype-drift",
                mod.path,
                node.lineno,
                node.col_offset,
                f"{what} in kernel code: the device kernels are f32/bf16 "
                "(config.Precision); a float64 constant upcasts or "
                "retraces the kernel — use the configured dtype",
            )
        )

    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "float64"
            and isinstance(node.value, ast.Name)
            and node.value.id in ("jnp",)
        ):
            flag(node, "jnp.float64")
        elif isinstance(node, ast.Call):
            f = node.func
            is_jnp_call = (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "jnp"
            )
            is_astype = isinstance(f, ast.Attribute) and f.attr == "astype"
            if not (is_jnp_call or is_astype):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Constant) and arg.value == "float64":
                    flag(arg, '"float64" dtype literal')
                elif (
                    isinstance(arg, ast.Attribute)
                    and arg.attr == "float64"
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id in ("np", "numpy", "jnp")
                ):
                    flag(arg, f"{arg.value.id}.float64 dtype")


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    cg = pkg.callgraph
    for mod in cg.modules.values():
        _check_jit_in_loop(mod, findings)
        _check_dtype_drift(mod, findings)
    _check_scalar_args(pkg, findings)
    return findings
