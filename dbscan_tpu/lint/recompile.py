"""recompile rules: patterns that mint fresh jit signatures (the storm
``obs/compile.py`` can only observe after the fact).

- ``jit-in-loop``: ``jax.jit(...)`` invoked lexically inside a
  ``for``/``while`` body — every iteration builds a fresh wrapper with
  an empty trace cache. Hoist the jit (module level or an lru_cache
  builder keyed on the static config, the driver's idiom).
- ``jit-scalar-arg``: a call to a KNOWN jitted callable passing a
  Python scalar or tuple literal positionally while the jit declared
  no static_argnums/static_argnames — tuples fail at trace, scalars
  retrace per dtype and silently defeat weak-type reuse when mixed.

The old literal-only ``dtype-drift`` rule lived here until graftshape:
``lint/shapes.py``'s flow-based ``dtype-flow-drift`` supersedes it
(``lint.ALIASES`` keeps the old id working in globs/baselines/
suppressions). :func:`_kernel_file` stays here as the shared
definition of "kernel code" both families scope to.
"""

from __future__ import annotations

import ast
import os
from typing import List

from dbscan_tpu.lint.core import Finding, Package
from dbscan_tpu.lint.callgraph import _is_jax_jit

_SCALARS = (int, float, bool, str)


def _kernel_file(path: str) -> bool:
    # embed/ holds device kernels too (lsh/neighbors jit builders), so
    # the kernel-only rules (dtype-flow-drift et al.) cover it like ops/
    parts = os.path.normpath(path).split(os.sep)
    return (
        "ops" in parts
        or "embed" in parts
        or os.path.basename(path) == "spill_device.py"
    )


def _check_jit_in_loop(mod, findings: List[Finding]) -> None:
    class LoopVisitor(ast.NodeVisitor):
        def __init__(self):
            self.loop_depth = 0

        def _loop(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = _loop
        visit_While = _loop
        visit_AsyncFor = _loop

        def visit_Call(self, node: ast.Call):
            if self.loop_depth > 0 and _is_jax_jit(node.func):
                findings.append(
                    Finding(
                        "jit-in-loop",
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        "jax.jit called inside a loop body builds a fresh "
                        "wrapper (empty trace cache) every iteration; "
                        "hoist it to module level or an lru_cache builder",
                    )
                )
            self.generic_visit(node)

    LoopVisitor().visit(mod.tree)


def _is_scalar_or_tuple_literal(arg: ast.AST) -> bool:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, _SCALARS):
        return True
    if isinstance(arg, ast.Tuple):
        return True
    if isinstance(arg, ast.UnaryOp) and isinstance(
        arg.operand, ast.Constant
    ):
        return True
    return False


def _check_scalar_args(pkg: Package, findings: List[Finding]) -> None:
    cg = pkg.callgraph
    for mod in cg.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            has_statics = None
            f = node.func
            if isinstance(f, ast.Name):
                key = (mod.path, f.id)
                if key in cg.jitted_names:
                    has_statics = cg.jitted_names[key]
                else:
                    tgt = mod.from_names.get(f.id)
                    if tgt is not None:
                        m2 = cg.by_modname.get(tgt[0])
                        info = (
                            m2.functions.get(tgt[1]) if m2 is not None else None
                        )
                        if info is not None and info.is_jit_root:
                            has_statics = info.jit_has_statics
            if has_statics is not False:
                continue  # unknown callee, or statics declared
            for i, arg in enumerate(node.args):
                if _is_scalar_or_tuple_literal(arg):
                    findings.append(
                        Finding(
                            "jit-scalar-arg",
                            mod.path,
                            arg.lineno,
                            arg.col_offset,
                            f"positional arg {i} is a Python "
                            "scalar/tuple literal passed to a jitted "
                            "function with no static_argnums/"
                            "static_argnames — tuples fail at trace, "
                            "scalars defeat signature reuse; declare it "
                            "static or pass an array",
                        )
                    )


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    cg = pkg.callgraph
    for mod in cg.modules.values():
        _check_jit_in_loop(mod, findings)
    _check_scalar_args(pkg, findings)
    return findings
