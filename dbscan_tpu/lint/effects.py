"""graftfault effect model: caller-visible-effect abstract interpreter.

The fault plane's retry contract (dbscan_tpu/faults.py) is behavioral:
the callable handed to ``supervised(site, fn)`` must be IDEMPOTENT —
re-running it from the top after a partial execution must land the same
final state, because a transient fault retries it and a budget halving
re-enters it. PR 5 established the discipline by hand for
``driver._pull_record`` ("the record is NOT mutated until every pull
succeeded"); this module makes it checkable for every site.

Per function we compute an ordered list of EVENTS over the body's own
frame (nested defs/lambdas are separate frames, joined at their call
sites):

- **mutations** of some root expression, flavored:
  ``store`` (plain ``x.a = v`` / ``x[k] = v`` — idempotent when the
  value does not read the mutated root), ``augment`` (``+=`` or a
  store whose RHS reads the root — NOT idempotent), ``mutator``
  (``.append``/``.pop``/... — NOT idempotent), ``del``, ``file-write``
  (``open(p, "w")``) and ``file-append`` (mode ``"a"``).
- **fallible** operations — the ops a device fault can surface from:
  jax-module calls (``jnp.*``/``jax.*``/``lax.*`` through the import
  maps), ``tracked_call`` dispatches, jitted-name calls, device syncs
  (``block_until_ready``/``device_get``/``device_put``/``.item``/
  ``copy_to_host_async``), nested ``faults.supervised``, and any
  resolved callee that transitively contains one.
- **tsan sites** — ``tsan.access("<site>")`` literals, the observable
  mutation vocabulary the runtime half (lint/faultcheck.py) fingerprints
  supervised execution against.

The **success point** of a frame is its last fallible event: mutations
strictly after it (and not sharing a loop with a fallible event) are
post-success and retry-safe; everything else is pre-success.

Roots classify as in the race rules (lint/races.py):

- ``local`` — created in the frame: ownership, exempt;
- ``param`` — ownership transfer (objects handed TO the callable are
  the caller's gift — ``_pull_record(rec)``'s record), exempt at the
  top frame but tracked for interprocedural mapping;
- ``self`` / ``global`` / ``closure`` — caller-visible.

Documented exemptions (the PARITY.md "Fault surface contract"):

- **telemetry**: calls into ``dbscan_tpu.obs.*``, ``lint/tsan.py``,
  ``lint/faultcheck.py``, ``logging``, and ``dbscan_tpu.faults``'s own
  accounting (FaultCounters / registry bookkeeping) carry no modeled
  effects — counters are monotone diagnostics, not results;
- **wall-clock accounting**: an augment whose RHS reads
  ``perf_counter``/``monotonic``/``time.time`` is timing telemetry;
- **failure paths**: effects inside ``except`` handlers run only after
  the attempt already failed — they are the abort protocol;
- **locks / thread-locals**: acquiring ``self._mu`` or writing a
  ``threading.local()`` attr is not a caller-visible result;
- **``__init__``**: the object under construction is not yet shared;
- **memoization caches**: module-global registries following the
  ``*_CACHE`` naming convention (driver's resident cache) — retries
  re-land the same keyed entries;
- **append-mode files**: ledgers/logs by the atomic-write contract;
  their readers reconcile duplicate rows (bench history, progress);
- **convergent guards**: a mutation under an ``if`` whose test reads
  the mutated state (``if _engine is None: _engine = ...``) — re-entry
  re-evaluates the guard and skips the already-applied arm (the
  singleton-lifecycle idiom; self-rooted effects demand the guard read
  the same attribute);
- **restore-prologue**: a callable whose FIRST statement calls
  ``<root>.restore_state(...)`` is re-entrant by construction for
  mutations of ``<root>`` — each attempt re-enters from the snapshot
  (the serve ingest idempotence fix rides this idiom).

Interprocedural composition: a resolved call imports the callee's
summary at the call position. Callee self-mutations map through the
receiver expression's root in the caller (``trial.update()`` on a local
is ownership; ``self._stream.update()`` through a closure-captured
``self`` is caller-visible); callee param-mutations map through the
argument expressions the same way. Callee mutations that were
PRE-success in the callee's own frame stay pre-success at any call site
reached by a retry (the callee's own fallible op can fault after them);
post-success callee mutations inherit the call site's position.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dbscan_tpu.lint import callgraph as cg_mod
from dbscan_tpu.lint.callgraph import (
    CallGraph,
    FuncInfo,
    callable_argument,
    local_types,
    resolve_callable,
    terminal_name,
)

# mutator method names (the races.py set): receiver mutated in place
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "clear", "remove", "discard", "setdefault", "appendleft", "popleft",
    "extendleft",
}

# device-sync / host-pull attribute calls: a real device fault surfaces
# here even when the dispatch itself was async
_DEVICE_SYNC_ATTRS = {
    "block_until_ready", "device_get", "device_put", "item",
    "copy_to_host_async", "pull_to_host",
}

# telemetry-plane modules: calls into them carry no modeled effects
_TELEMETRY_MODULES = (
    "dbscan_tpu.obs",
    "dbscan_tpu.lint.tsan",
    "dbscan_tpu.lint.faultcheck",
    "dbscan_tpu.faults",
    "logging",
)

# unresolved receiver aliases treated as telemetry (the instrumented
# modules import them under these names)
_TELEMETRY_ALIASES = {
    "obs", "obs_live", "obs_memory", "obs_compile", "obs_flight",
    "_obs_live", "_obs_memory", "_obs_flight", "logger", "logging",
    "tsan", "_tsan", "faults", "counters",
}

_TIME_FNS = {"perf_counter", "monotonic", "time", "process_time"}


def _root_name(expr: ast.AST) -> Optional[str]:
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _attr_chain(expr: ast.AST) -> str:
    """Dotted/bracketed rendering of a mutation target for messages."""
    parts: List[str] = []
    while True:
        if isinstance(expr, ast.Attribute):
            parts.append("." + expr.attr)
            expr = expr.value
        elif isinstance(expr, ast.Subscript):
            parts.append("[...]")
            expr = expr.value
        elif isinstance(expr, ast.Name):
            parts.append(expr.id)
            break
        else:
            parts.append("<expr>")
            break
    return "".join(reversed(parts))


class Effect:
    """One caller-visible mutation candidate inside a frame."""

    __slots__ = (
        "flavor", "root_kind", "root", "target", "line", "pos",
        "loops", "pre", "via", "guarded",
    )

    def __init__(self, flavor, root_kind, root, target, line, pos, loops):
        self.flavor = flavor  # store|augment|mutator|del|file-write|file-append
        self.root_kind = root_kind  # local|param|self|global|closure
        self.root = root  # root simple name ("self", "counters", ...)
        self.target = target  # rendered chain for the finding message
        self.line = line
        self.pos = pos  # walk-order position in the frame
        self.loops = loops  # frozenset of enclosing loop ids
        self.pre = False  # before the frame's success point?
        self.via = ""  # callee qualname when imported from a summary
        self.guarded = False  # under a convergent check-then-act guard?

    def idempotent(self) -> bool:
        return self.flavor in ("store", "file-write")


class FrameModel:
    """One function frame's ordered events + interprocedural summary."""

    __slots__ = (
        "info", "effects", "fallible", "tsan_sites", "self_pre",
        "self_post", "global_pre", "global_post", "param_pre",
        "param_post", "is_fallible", "file_writes",
    )

    def __init__(self, info: FuncInfo):
        self.info = info
        self.effects: List[Effect] = []  # every recorded mutation
        self.fallible: List[Tuple[int, frozenset]] = []  # (pos, loops)
        self.tsan_sites: Set[str] = set()
        self.is_fallible = False
        # summary: non-idempotent mutation descriptors by root class,
        # split at the frame's own success point
        self.self_pre: List[Effect] = []
        self.self_post: List[Effect] = []
        self.global_pre: List[Effect] = []
        self.global_post: List[Effect] = []
        self.param_pre: List[Effect] = []
        self.param_post: List[Effect] = []
        self.file_writes: List[Effect] = []


def _frame_locals(node: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(locally bound names, explicitly nonlocal/global names) for one
    frame — scope-bounded, nested defs excluded."""
    binds: Set[str] = set()
    outer: Set[str] = set()
    args = getattr(node, "args", None)
    if args is not None:
        for a in (
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            binds.add(a.arg)
    for n in cg_mod.walk_scope(node):
        if isinstance(n, (ast.Global, ast.Nonlocal)):
            outer.update(n.names)
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            binds.add(n.id)
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            for al in n.names:
                binds.add((al.asname or al.name).split(".")[0])
        elif isinstance(n, ast.ExceptHandler) and n.name:
            binds.add(n.name)
    return binds - outer, outer


def _param_names(node: ast.AST) -> Set[str]:
    args = getattr(node, "args", None)
    if args is None:
        return set()
    out = set()
    for a in (
        list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ):
        out.add(a.arg)
    return out


class EffectModel:
    """Memoized per-function frame models over one callgraph."""

    def __init__(self, cg: CallGraph):
        self.cg = cg
        self._frames: Dict[int, FrameModel] = {}
        self._in_progress: Set[int] = set()

    # --- classification helpers ---------------------------------------

    def _root_kind(
        self, info: FuncInfo, name: str, binds: Set[str]
    ) -> str:
        if name == "self" and info.owner_class is not None:
            return "self"
        if name in _param_names(info.node):
            return "param"
        if name in binds:
            return "local"
        # free variable: module-global if the module binds it, else a
        # closure capture from an enclosing frame — both caller-visible
        if name in info.module.module_globals:
            return "global"
        if name in info.module.functions or name in info.module.classes:
            return "local"  # rebinding a function name is not state
        return "closure"

    def _is_jax_alias(self, info: FuncInfo, name: str) -> bool:
        mod = info.module
        target = mod.import_alias.get(name)
        if target is None and name in mod.from_names:
            target = mod.from_names[name][0]
        return bool(target) and (
            target == "jax" or target.startswith("jax.")
        )

    def _telemetry_callee(self, callee: Optional[FuncInfo]) -> bool:
        if callee is None:
            return False
        modname = callee.module.modname
        return any(
            modname == t or modname.startswith(t + ".")
            for t in _TELEMETRY_MODULES
        )

    def _telemetry_call(self, info: FuncInfo, call: ast.Call) -> bool:
        f = call.func
        root = _root_name(f) if isinstance(f, ast.Attribute) else None
        if root is not None and root in _TELEMETRY_ALIASES:
            return True
        if isinstance(f, ast.Name) and f.id in ("note_degrade",):
            return True
        # self._counters.add(...) style: terminal telemetry verbs on a
        # chain ending in a counters-ish attr stay un-modeled
        if isinstance(f, ast.Attribute) and isinstance(
            f.value, ast.Attribute
        ):
            if f.value.attr in ("counters", "metrics", "_metrics"):
                return True
        return False

    def _fallible_call(
        self, info: FuncInfo, call: ast.Call, types
    ) -> bool:
        f = call.func
        tname = terminal_name(f)
        if tname in ("tracked_call", "supervised"):
            return True
        if tname in _DEVICE_SYNC_ATTRS:
            return True
        if isinstance(f, ast.Attribute):
            root = _root_name(f)
            if root is not None and self._is_jax_alias(info, root):
                return True
        if isinstance(f, ast.Name):
            if self._is_jax_alias(info, f.id):
                return True
            key = (info.path, f.id)
            if key in self.cg.jitted_names:
                return True
        return False

    # --- the per-frame walk -------------------------------------------

    def frame(self, info: FuncInfo) -> FrameModel:
        key = id(info.node)
        got = self._frames.get(key)
        if got is not None:
            return got
        if key in self._in_progress:
            return FrameModel(info)  # cycle: optimistic empty summary
        self._in_progress.add(key)
        try:
            fm = self._build(info)
            self._frames[key] = fm
            return fm
        finally:
            self._in_progress.discard(key)

    def _restore_roots(self, info: FuncInfo) -> Set[str]:
        """Roots covered by a restore-prologue: the frame's first
        statement is ``<root chain>.restore_state(...)`` (or
        ``restore``) — each attempt re-enters from the snapshot, so
        mutations of that root are re-entrant by construction."""
        body = getattr(info.node, "body", None)
        if not isinstance(body, list) or not body:
            return set()
        first = body[0]
        if not (
            isinstance(first, ast.Expr)
            and isinstance(first.value, ast.Call)
        ):
            return set()
        f = first.value.func
        if isinstance(f, ast.Attribute) and f.attr in (
            "restore_state", "restore"
        ):
            root = _root_name(f)
            if root is not None:
                return {root}
        return set()

    def _build(self, info: FuncInfo) -> FrameModel:
        fm = FrameModel(info)
        node = info.node
        binds, _outer = _frame_locals(node)
        types = local_types(self.cg, info)
        restore_roots = self._restore_roots(info)
        is_init = getattr(node, "name", "") == "__init__"
        tls = (
            info.owner_class.tls_attrs if info.owner_class else set()
        )
        pos = 0
        loop_stack: List[int] = []
        if_stack: List[ast.AST] = []
        except_depth = 0

        def guard_matches(root: str, target: str) -> bool:
            """Is some enclosing ``if`` test reading the mutated state?
            Check-then-act on the same root converges under re-entry
            (``if _engine is None: _engine = ...`` — the retry
            re-evaluates the guard and skips the already-applied arm).
            Self-rooted effects demand the test read the same first
            attribute, or ``if self:`` would exempt every method."""
            first_attr = None
            if target.startswith(root + "."):
                rest = target[len(root) + 1:]
                first_attr = rest.split(".", 1)[0].split("[", 1)[0]
            for test in if_stack:
                for sub in ast.walk(test):
                    if root == "self" or first_attr is not None:
                        if (
                            isinstance(sub, ast.Attribute)
                            and sub.attr == first_attr
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id == root
                        ):
                            return True
                        if root != "self" and isinstance(
                            sub, ast.Name
                        ) and sub.id == root:
                            return True
                    elif isinstance(sub, ast.Name) and sub.id == root:
                        return True
            return False

        def classify_target(tgt: ast.AST, flavor: str, line: int):
            root = _root_name(tgt)
            if root is None:
                return
            if root in info.module.tls_globals:
                return  # threading.local(): per-thread scratch
            kind = self._root_kind(info, root, binds)
            if kind == "self":
                if is_init:
                    return
                # self.<tls_attr> is per-thread scratch
                t = tgt
                while isinstance(t, ast.Subscript):
                    t = t.value
                if isinstance(t, ast.Attribute) and t.attr in tls:
                    return
            if root in restore_roots or (
                kind == "self"
                and "self" in restore_roots
            ):
                return
            eff = Effect(
                flavor, kind, root, _attr_chain(tgt), line, pos,
                frozenset(loop_stack),
            )
            eff.guarded = guard_matches(root, eff.target)
            fm.effects.append(eff)

        def add_fallible():
            fm.fallible.append((pos, frozenset(loop_stack)))
            fm.is_fallible = True

        def import_summary(
            callee_fm: FrameModel, call: ast.Call, self_recv="func"
        ):
            """Map a resolved callee's summary into this frame at the
            call position. ``self_recv`` is where the callee's
            self-mutations land: the call func's receiver (default), an
            explicit expression (callable arguments land through the
            ARGUMENT's receiver — ``Thread(target=self._worker)``
            mutates ``self``, not ``Thread``), or ``"drop"`` when no
            receiver is resolvable (conservative)."""
            if callee_fm.is_fallible:
                add_fallible()
            f = call.func
            if self_recv == "func":
                recv = f.value if isinstance(f, ast.Attribute) else None
            elif self_recv == "drop":
                recv = "drop"
            else:
                recv = self_recv

            def land(eff: Effect, tgt_expr, callee_pre: bool):
                if tgt_expr == "drop":
                    return
                if tgt_expr is None:
                    # global/closure roots keep their name, but the
                    # KIND reclassifies in this frame: a callee-closure
                    # root bound HERE is this frame's own local
                    root, target = eff.root, eff.target
                    if root in info.module.tls_globals:
                        return
                    kind = self._root_kind(info, root, binds)
                    if eff.root_kind == "global" and kind == "closure":
                        kind = "global"  # defined in the callee's module
                else:
                    root = _root_name(tgt_expr)
                    if root is None:
                        return
                    kind = self._root_kind(info, root, binds)
                    target = (
                        _attr_chain(tgt_expr)
                        + "." + eff.target.split(".", 1)[-1]
                        if "." in eff.target
                        else _attr_chain(tgt_expr)
                    )
                if kind in ("local",):
                    return  # ownership: the caller made this object
                if root in restore_roots:
                    return
                e2 = Effect(
                    eff.flavor, kind, root, target,
                    call.lineno, pos, frozenset(loop_stack),
                )
                e2.via = callee_fm.info.qualname
                # convergent either in the callee's own frame or by a
                # check-then-act guard around this call site
                e2.guarded = eff.guarded or guard_matches(root, target)
                if callee_pre:
                    e2.pre = True  # sticky: pre in the callee's frame
                fm.effects.append(e2)

            # callee self-mutations attach to the receiver expression
            for eff in callee_fm.self_pre:
                land(eff, recv, True)
            for eff in callee_fm.self_post:
                land(eff, recv, False)
            # callee global/closure mutations are caller-visible as-is
            for eff in callee_fm.global_pre:
                land(eff, None, True)
            for eff in callee_fm.global_post:
                land(eff, None, False)
            # callee param-mutations map through the argument exprs
            callee_params = sorted(_param_names(callee_fm.info.node))
            pmap = {}
            args_list = getattr(callee_fm.info.node, "args", None)
            ordered = (
                [a.arg for a in args_list.posonlyargs + args_list.args]
                if args_list is not None
                else callee_params
            )
            skip_self = bool(
                callee_fm.info.owner_class is not None
                and ordered
                and ordered[0] == "self"
            )
            if skip_self:
                ordered = ordered[1:]
            for i, a in enumerate(call.args):
                if i < len(ordered):
                    pmap[ordered[i]] = a
            for kw in call.keywords:
                if kw.arg:
                    pmap[kw.arg] = kw.value
            for eff, callee_pre in [
                (e, True) for e in callee_fm.param_pre
            ] + [(e, False) for e in callee_fm.param_post]:
                tgt = pmap.get(eff.root)
                if tgt is not None:
                    land(eff, tgt, callee_pre)
            fm.tsan_sites.update(callee_fm.tsan_sites)
            for eff in callee_fm.file_writes:
                e2 = Effect(
                    eff.flavor, "global", eff.root, eff.target,
                    call.lineno, pos, frozenset(loop_stack),
                )
                e2.via = callee_fm.info.qualname
                e2.pre = eff.pre
                fm.effects.append(e2)

        def visit(n: ast.AST):
            nonlocal pos, except_depth
            pos += 1
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not node:
                return  # separate frame
            if isinstance(n, ast.ExceptHandler):
                # failure-path effects are the abort protocol: the
                # attempt already failed, the retry has not re-run yet
                except_depth += 1
                for c in ast.iter_child_nodes(n):
                    visit(c)
                except_depth -= 1
                return
            if isinstance(n, (ast.For, ast.AsyncFor, ast.While)):
                loop_stack.append(id(n))
                for c in ast.iter_child_nodes(n):
                    visit(c)
                loop_stack.pop()
                return
            if isinstance(n, ast.If):
                if_stack.append(n.test)
                for c in ast.iter_child_nodes(n):
                    visit(c)
                if_stack.pop()
                return
            if except_depth == 0:
                self._visit_effect(
                    n, info, fm, binds, types, classify_target,
                    add_fallible, import_summary,
                )
            for c in ast.iter_child_nodes(n):
                visit(c)

        body = getattr(node, "body", None)
        stmts = body if isinstance(body, list) else [node.body]
        for stmt in stmts:
            visit(stmt)
        # direct tsan-access literals, UNCONDITIONALLY (failure-path
        # handlers still execute inside a supervised window, so their
        # writes belong in the runtime containment model)
        for n in cg_mod.walk_scope(node):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "access"
                and isinstance(n.func.value, ast.Name)
                and "tsan" in n.func.value.id
                and n.args
                and isinstance(n.args[0], ast.Constant)
                and isinstance(n.args[0].value, str)
            ):
                fm.tsan_sites.add(n.args[0].value)
        self._summarize(fm)
        return fm

    def _visit_effect(
        self, n, info, fm, binds, types, classify_target,
        add_fallible, import_summary,
    ):
        if isinstance(n, ast.Assign):
            for tgt in n.targets:
                self._classify_store(tgt, n.value, classify_target, n)
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            self._classify_store(n.target, n.value, classify_target, n)
        elif isinstance(n, ast.AugAssign):
            if not self._timing_rhs(n.value):
                classify_target(n.target, "augment", n.lineno)
        elif isinstance(n, ast.Delete):
            for tgt in n.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    classify_target(tgt, "del", n.lineno)
        elif isinstance(n, ast.Call):
            self._classify_call(
                n, info, fm, types, classify_target, add_fallible,
                import_summary,
            )

    def _classify_store(self, tgt, value, classify_target, stmt):
        if isinstance(tgt, ast.Name):
            return  # local (re)bind — scope bookkeeping, not an effect
        if isinstance(tgt, ast.Tuple):
            for el in tgt.elts:
                self._classify_store(el, value, classify_target, stmt)
            return
        if not isinstance(tgt, (ast.Attribute, ast.Subscript)):
            return
        root = _root_name(tgt)
        flavor = "store"
        if root is not None:
            for sub in ast.walk(value):
                if isinstance(sub, ast.Name) and sub.id == root:
                    flavor = "augment"  # x.a = f(x.a): reads the root
                    break
        classify_target(tgt, flavor, stmt.lineno)

    def _timing_rhs(self, value: ast.AST) -> bool:
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                t = terminal_name(sub.func)
                if t in _TIME_FNS:
                    return True
        return False

    def _classify_call(
        self, call, info, fm, types, classify_target, add_fallible,
        import_summary,
    ):
        f = call.func
        tname = terminal_name(f)
        # tsan site literals: the observable mutation vocabulary
        if (
            tname == "access"
            and isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and "tsan" in f.value.id
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
        ):
            fm.tsan_sites.add(call.args[0].value)
            return
        if self._telemetry_call(info, call):
            return
        if tname in ("acquire", "release", "wait", "notify",
                     "notify_all", "set", "is_set"):
            return  # lock/event protocol, not a result
        # file writes: open(path, "w"/"a")
        if isinstance(f, ast.Name) and f.id == "open":
            mode = None
            if len(call.args) >= 2 and isinstance(
                call.args[1], ast.Constant
            ):
                mode = call.args[1].value
            for kw in call.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if isinstance(mode, str) and (
                "w" in mode or "a" in mode or "x" in mode or "+" in mode
            ):
                flavor = "file-append" if "a" in mode else "file-write"
                eff = Effect(
                    flavor, "global", "open",
                    ast.unparse(call.args[0]) if call.args else "<path>",
                    call.lineno, 0, frozenset(),
                )
                fm.effects.append(eff)
                fm.file_writes.append(eff)
            return
        if self._fallible_call(info, call, types):
            add_fallible()
            # a nested supervised's attempt callable is that frame's
            # own contract; don't double-import it here
            if tname == "supervised":
                return
        # mutator method on a receiver chain
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _MUTATORS
            and isinstance(f.value, (ast.Name, ast.Attribute,
                                     ast.Subscript))
        ):
            callee = resolve_callable(self.cg, info, f, types)
            if callee is None:
                classify_target(f.value, "mutator", call.lineno)
                return
        # resolved repo callee: import its summary
        callee = resolve_callable(self.cg, info, f, types)
        if callee is not None and not self._telemetry_callee(callee):
            import_summary(self.frame(callee), call)
        # callable arguments (thunks handed onward) run here too: their
        # self-effects land through the ARGUMENT's receiver (a bound
        # method mutates its own object, not the accepting callee)
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            fi = callable_argument(self.cg, info, arg, types)
            if fi is not None and fi.node is not info.node:
                if not self._telemetry_callee(fi):
                    recv = (
                        arg.value
                        if isinstance(arg, ast.Attribute)
                        else "drop"
                    )
                    import_summary(self.frame(fi), call, self_recv=recv)

    # --- success-point split ------------------------------------------

    def _summarize(self, fm: FrameModel) -> None:
        fallible = fm.fallible
        for eff in fm.effects:
            if not eff.pre:
                eff.pre = any(
                    fpos > eff.pos or (floops & eff.loops)
                    for fpos, floops in fallible
                )
            bucket = {
                "self": (fm.self_pre, fm.self_post),
                "param": (fm.param_pre, fm.param_post),
                "global": (fm.global_pre, fm.global_post),
                "closure": (fm.global_pre, fm.global_post),
            }.get(eff.root_kind)
            if bucket is None:
                continue
            if eff.idempotent() and not eff.pre:
                continue  # post-success stores never matter upstream
            (bucket[0] if eff.pre else bucket[1]).append(eff)


def unsafe_mutations(model: EffectModel, info: FuncInfo) -> List[Effect]:
    """The fault-retry-unsafe verdict for one supervised callable:
    caller-visible, non-idempotent (or callee-pre-success) mutations
    before the frame's success point."""
    fm = model.frame(info)
    out = []
    for eff in fm.effects:
        if eff.root_kind in ("local", "param"):
            continue  # ownership / ownership transfer
        if not eff.pre:
            continue
        if eff.idempotent():
            # a pre-success keyed/plain store re-runs to the same value
            # on retry (the repo's determinism bar: attempts are
            # reproducible), and a whole-file rewrite re-lands the same
            # content — direct or via a callee
            continue
        if eff.flavor == "file-append":
            # append-mode artifacts are ledgers/logs by the atomic-write
            # contract; their readers reconcile duplicates (bench
            # history, progress ledger)
            continue
        if eff.root.endswith("_CACHE"):
            # memoization registries (the *_CACHE module-global naming
            # convention, e.g. driver._RESIDENT_CACHE): re-populating a
            # keyed cache on retry lands the same entries
            continue
        if eff.guarded:
            # check-then-act convergence: an enclosing `if` reads the
            # mutated state, so re-entry re-evaluates the guard and the
            # already-applied arm is skipped (the get_engine singleton
            # lifecycle idiom)
            continue
        out.append(eff)
    return out


def callable_tsan_sites(model: EffectModel, info: FuncInfo) -> Set[str]:
    """Transitive tsan-access literals reachable from one callable —
    the static half of the faultcheck containment test."""
    roots = [info]
    closure = cg_mod.reach_closure(model.cg, roots)
    sites: Set[str] = set()
    for fi in closure.values():
        sites.update(model.frame(fi).tsan_sites)
    return sites
