"""graftshape rules: symbolic shape/dtype/HBM checks over the jit call
graph, plus the per-dispatch-family footprint models the runtime
cross-check (``lint/shapecheck.py``) asserts against live runs.

Four rule families on top of :mod:`lint.absint`:

- ``shape-mismatch``: provable broadcast / concatenate / reshape / dot
  incompatibilities under symbolic dims. The interpreter is
  conservative by construction — a dim it cannot prove concrete
  unifies with anything — so every finding is an arithmetic
  impossibility, not a heuristic.
- ``shape-unratcheted-dim``: a data-dependent leading dim (``len(x)``,
  ``np.flatnonzero`` counts, ``.sum()`` values) entering a KNOWN jit
  boundary without passing through one of the repo's sanctioned
  padding functions (``_ratchet`` / ``_ladder_width`` / ``_pad_parts``
  / ``_pad_idx`` / ``_ladder8``). This is the static twin of the
  ``compiles.ratchet_raises`` counter: the dim that mints a fresh jit
  signature per batch, caught before it ships.
- ``dtype-flow-drift``: explicit float64 (np.float64 constructions,
  ``dtype="float64"``, ``astype(float64)``) reaching device code in
  kernel files (``ops/``, ``parallel/spill_device.py``) via VALUE FLOW
  — supersedes the literal-only ``dtype-drift`` rule (kept as an
  alias, see ``lint.ALIASES``): the old rule saw ``jnp.sum(x,
  dtype=jnp.float64)``; this one also sees ``w = np.float64(h);
  jnp.sum(x * w)``. numpy's silent float64 DEFAULTS (host geometry
  math) are deliberately exempt — only explicit f64 is drift.
- ``hbm-over-budget`` / ``shard-indivisible``: the memory-envelope and
  mesh-divisibility gates. ``hbm-over-budget`` fires (a) on any array
  CONSTRUCTED inside jit-reachable code whose concrete byte count
  alone exceeds the device budget, and (b) on any ``tracked_call``
  dispatch family whose knob-bounded worst case
  (:data:`FAMILY_MODELS`, evaluated against the live
  ``config.ENV_VARS`` values) exceeds it — so raising
  ``DBSCAN_GROUP_SLOTS`` past what HBM can hold fails lint before it
  OOMs a chip. ``shard-indivisible`` checks concrete arg dims against
  statically-visible ``shard_map`` mesh axis sizes at jit call sites —
  the gate ROADMAP item 1 (multi-chip scale-out) needs.

:data:`FAMILY_MODELS` is the single declared symbolic model of every
dispatch family's argument shapes, dtype classes, constraints, and
footprint algebra; ``python -m dbscan_tpu.lint --shape-table`` renders
it for PARITY.md and ``lint/shapecheck.py`` unifies observed shapes
against it at runtime (``DBSCAN_SHAPECHECK=1``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from dbscan_tpu.lint import absint
from dbscan_tpu.lint.absint import (
    Arr,
    DTYPE_BYTES,
    E,
    FLOATS,
    INTS,
    IntVal,
    Interp,
    Lit,
    Sym,
    Tup,
    UNKNOWN,
    fresh,
    unify_dim,
)
from dbscan_tpu.lint.core import Finding, Package
from dbscan_tpu.lint.recompile import _kernel_file

#: static HBM budget for the envelope checks: one v5e chip's HBM. The
#: runtime cross-check uses the live ``device.memory_stats()``
#: ``bytes_limit`` instead; this constant only gates the lint-time
#: worst case (tests may monkeypatch it to exercise the rule).
DEFAULT_HBM_BYTES = 16 * 2**30

#: the sanctioned padding functions — a dim produced by one of these
#: carries the "ratchet" provenance the unratcheted-dim rule accepts
#: (the repo's idioms: binning._ratchet/_ladder_width/_pad_parts,
#: driver._pad_idx, spill_device._ladder8)
RATCHET_FNS = ("_ratchet", "_ladder_width", "_pad_parts", "_ladder8")
RATCHET_ARRAY_FNS = ("_pad_idx",)

#: shape-transparent mesh helpers (return their array argument's shape)
_TRANSPARENT_LAST_ARG = ("shard_host_array",)
_TRANSPARENT_FIRST_ARG = ("replicate_host_array", "device_put")

# --- dispatch-family models --------------------------------------------

#: dtype classes for model args
FLOAT = FLOATS
INT = INTS
BOOL = ("bool",)
ANY = FLOATS + INTS + BOOL

#: block size the banded packer pads bucket widths to — mirrors
#: ``parallel.binning.BANDED_BLOCK`` (pinned equal by
#: tests/test_shapecheck.py; lint stays stdlib-only, so no import)
BANDED_BLOCK = 512
#: window rows per point — mirrors ``parallel.binning.BANDED_ROWS``
BANDED_ROWS = 5


class ArgModel:
    """Symbolic model of one dispatch argument: ``dims`` are symbol
    names (shared across the call's args), ints, or :class:`E`
    expressions; ``dtypes`` the allowed canonical dtype class."""

    def __init__(self, name: str, dims: Tuple, dtypes: Tuple,
                 tuple_of: bool = False):
        self.name = name
        self.dims = dims
        self.dtypes = dtypes
        #: a tuple/list of arrays, each matching ``dims`` with FRESH
        #: per-element symbols (the postpass chunk-group idiom)
        self.tuple_of = tuple_of

    def render(self) -> str:
        dims = ",".join(
            d if isinstance(d, str)
            else (d.render() if isinstance(d, E) else str(d))
            for d in self.dims
        )
        cls = (
            "float" if self.dtypes == FLOAT
            else "int" if self.dtypes == INT
            else "bool" if self.dtypes == BOOL
            else "any"
        )
        body = f"[{dims}] {cls}"
        return f"{self.name}: ({body}, ...)" if self.tuple_of else (
            f"{self.name}: {body}"
        )


class FamilyModel:
    """One dispatch family's declared contract.

    ``args``: positional :class:`ArgModel`s (extra observed scalar args
    are permitted — static-argnum specialization bakes scalars into the
    builder, but a few families pass them through).
    ``constraints``: ``(lhs, rhs)`` E-expression pairs over the model
    symbols that must agree once bound (shard-block division:
    ``B == BANDED_BLOCK * NB``).
    ``overhead``: symbolic temp+output bytes ON TOP of the exact input
    bytes (which the checker computes from the observed arrays).
    ``static_slots``: symbol -> env-var name binding the worst case for
    the lint-time gate, or None when the family scales with data the
    knobs do not bound (resident payload rows) — listed in the table,
    gated only at runtime.
    """

    def __init__(
        self,
        family: str,
        args: List[ArgModel],
        overhead: E,
        constraints: List[Tuple[E, E]] = (),
        static_slots: Optional[Dict[str, str]] = None,
        note: str = "",
    ):
        self.family = family
        self.args = args
        self.overhead = overhead
        self.constraints = list(constraints)
        self.static_slots = static_slots
        self.note = note

    # symbolic exact-input bytes, for the table and the static bound
    def input_expr(self) -> Optional[E]:
        total = E(0)
        for a in self.args:
            if a.tuple_of:
                # tuple args: per-element dims are fresh, so their
                # TOTAL rides the family's slot-bound overhead term
                # instead (one element's worst case is meaningless)
                continue
            size = max(DTYPE_BYTES[d] for d in a.dtypes)
            prod = E(size)
            for d in a.dims:
                if isinstance(d, str):
                    prod = prod * E.of(Sym(d))
                else:
                    prod = prod * E.of(d)
            total = total + prod
        return total

    def static_worst(self, env_fn) -> Optional[int]:
        """Worst-case total bytes under the live budget knobs, or None
        when some symbol has no knob bound (runtime-only family)."""
        if self.static_slots is None:
            return None
        binding: Dict[str, int] = {}
        for sym, env_name in self.static_slots.items():
            if isinstance(env_name, int):
                binding[sym] = env_name
            else:
                binding[sym] = int(env_fn(env_name))
        expr = self.input_expr() + self.overhead
        return expr.substitute(binding).evaluate(binding)

    def overhead_bytes(self, subst: Dict[str, int]) -> Optional[int]:
        return self.overhead.evaluate(subst)


def _sy(name: str) -> E:
    return E.of(Sym(name))


def _models() -> Dict[str, FamilyModel]:
    P, B, D, NB, N, M, K, G, C, V = (
        _sy(n) for n in ("P", "B", "D", "NB", "N", "M", "K", "G", "C", "V")
    )
    R = BANDED_ROWS
    slots = P * B  # one group's padded slot count
    # the driver's dense vmap temp cap: batch <= 1.2e9 elements of
    # [B, B] f32 adjacency in flight (driver._dispatch_partitions)
    dense_temp = E(int(1.2e9) * 4)
    return {
        m.family: m
        for m in (
            FamilyModel(
                "dispatch.dense",
                [
                    ArgModel("points", ("P", "B", "D"), FLOAT),
                    ArgModel("mask", ("P", "B"), BOOL),
                ],
                # temp: capped [batch, B, B] adjacencies; out: labels +
                # core per slot (i32 + bool + i32 seeds)
                overhead=dense_temp + slots * 9,
                static_slots={
                    # one width-class group's P*B is bounded by the
                    # dispatch-group slot budget; D <= 4 by the payload
                    # contract (binning's difference-form limit)
                    "P": "DBSCAN_GROUP_SLOTS", "B": 1, "D": 4,
                },
                note="temp = capped [batch,B,B] f32 adjacency "
                "(1.2e9 elements, driver._dispatch_partitions)",
            ),
            FamilyModel(
                "dispatch.resident",
                [
                    ArgModel("x", ("N", "D"), FLOAT),
                    ArgModel("idx", ("P", "B"), INT),
                    ArgModel("mask", ("P", "B"), BOOL),
                ],
                overhead=dense_temp + slots * 9 + slots * D * 4,
                static_slots=None,
                note="unbounded statically: scales with resident "
                "payload rows N (gated at runtime)",
            ),
            FamilyModel(
                "dispatch.banded_p1",
                [
                    ArgModel("points", ("P", "B", "D"), FLOAT),
                    ArgModel("mask", ("P", "B"), BOOL),
                    ArgModel("rel_starts", ("P", "B", R), INT),
                    ArgModel("spans", ("P", "B", R), INT),
                    ArgModel("slab_starts", ("P", "NB", R), INT),
                    ArgModel("cx", ("P", "B"), INT),
                ],
                # out: core bool + bits i32 per slot (+ per-slot counts
                # consumed on device); temp: per-batch slab gathers,
                # dwarfed by the run tables — covered by 2x slot bytes
                overhead=slots * (1 + 4 + 4) + slots * 8,
                constraints=[(B, E(BANDED_BLOCK) * NB)],
                static_slots={
                    "P": "DBSCAN_GROUP_SLOTS", "B": 1, "D": 4,
                    "NB": 1,
                },
                note=f"B = {BANDED_BLOCK}*NB (BANDED_BLOCK slabs); "
                "run tables ship u16 when slabs fit",
            ),
            FamilyModel(
                "cellcc.postpass",
                [
                    ArgModel("cores", ("Pi", "Bi"), BOOL, tuple_of=True),
                    ArgModel("bitses", ("Pi", "Bi"), INT, tuple_of=True),
                    ArgModel("segflags", ("Si",), BOOL, tuple_of=True),
                    ArgModel("or_idx", ("G",), INT),
                ],
                # the device-resident tuple inputs (core bool + bits
                # i32 + segflag bool per slot) plus flat concats, scan
                # buffers, and the packed output over the chunk's M
                # slots, plus the gathered scan bytes
                overhead=M * (1 + 4 + 1) + M * (1 + 4 + 1 + 8) + G * 8,
                constraints=[],
                static_slots={
                    "M": "DBSCAN_COMPACT_CHUNK_SLOTS",
                    "G": "DBSCAN_COMPACT_CHUNK_SLOTS",
                },
                note="M = sum of the chunk's P*B slots, bounded by "
                "the compact-chunk budget; inputs are already "
                "device-resident",
            ),
            FamilyModel(
                "cellcc.gather",
                [
                    ArgModel("src", ("M",), INT),
                    ArgModel("idx", ("K",), INT),
                ],
                overhead=K * 4,
                static_slots={
                    "M": "DBSCAN_COMPACT_CHUNK_SLOTS",
                    "K": "DBSCAN_COMPACT_CHUNK_SLOTS",
                },
                note="border-candidate gather from the resident "
                "bits_flat; K is ladder-padded (driver._pad_idx)",
            ),
            FamilyModel(
                "cellcc.unpack",
                [
                    ArgModel("combo", ("CB",), INT),
                    ArgModel("cell_flat", ("M",), INT),
                    ArgModel("fold_flat", ("M",), INT),
                    ArgModel("or_gid", ("K",), INT),
                ],
                # outputs: core bool [M] + the per-cell partials
                # ([C, 25] bool + [C] i32, C = padded cell count — not
                # an arg dim, so the HBM half gates at runtime only);
                # temps: the [K, 25] unpacked scan values
                overhead=M + K * (4 + BANDED_ROWS * BANDED_ROWS * 4)
                + C * (BANDED_ROWS * BANDED_ROWS + 4),
                static_slots=None,
                note="per-chunk device fold of the packed postpass "
                "slabs into per-cell partials (CB = M/8 + 4*K combo "
                "bytes); C scales with occupied cells — data-scaled, "
                "runtime-gated",
            ),
            FamilyModel(
                "cellcc.fused",
                [
                    ArgModel("combo", ("CB",), INT),
                    ArgModel("cell_flat", ("M",), INT),
                    ArgModel("fold_flat", ("M",), INT),
                    ArgModel("or_gid", ("K",), INT),
                    ArgModel(
                        "wintab", ("C", BANDED_ROWS * BANDED_ROWS), INT
                    ),
                ],
                # compiled_cellcc_unpack's envelope plus the folded
                # first-sweep partial: core [M] + the [K, 25] Pallas
                # bit expansions + per-cell partials and the [C, 25]
                # window gather behind lab0
                overhead=M * 5
                + K * (4 + 2 * BANDED_ROWS * BANDED_ROWS * 4)
                + C * (2 * BANDED_ROWS * BANDED_ROWS * 4 + 12),
                static_slots=None,
                note="fused Pallas unpack+fold+propagate per chunk "
                "(ops/pallas_banded.py compiled_cellcc_fused): the "
                "cellcc.unpack scatter-fold plus the first window_cc "
                "sweep in ONE dispatch riding the packing window; C "
                "scales with occupied cells — data-scaled, "
                "runtime-gated",
            ),
            FamilyModel(
                "cellcc.cc",
                [
                    ArgModel(
                        "wintab", ("C", BANDED_ROWS * BANDED_ROWS), INT
                    ),
                    ArgModel(
                        "cellors",
                        ("Ci", BANDED_ROWS * BANDED_ROWS),
                        BOOL,
                        tuple_of=True,
                    ),
                    ArgModel("cellfolds", ("Ci",), INT, tuple_of=True),
                    ArgModel("cores", ("Mi",), BOOL, tuple_of=True),
                    ArgModel("bitses", ("Mi",), INT, tuple_of=True),
                    ArgModel("cells", ("Mi",), INT, tuple_of=True),
                    ArgModel("folds", ("Mi",), INT, tuple_of=True),
                    # the fused path's per-chunk first-sweep label
                    # partials (EMPTY tuple on the split unpack path —
                    # tuple args validate elementwise, so empty is
                    # exactly "no warm start")
                    ArgModel("labs", ("Ci",), INT, tuple_of=True),
                ],
                # temps: labels/comp/seed tables + the [C, 25] seed_win
                # + bounded lax.map label-pass tiles; outputs: the
                # compacted [V] i32 seeds + i8 flags (V = ladder-padded
                # valid count — not an arg dim, runtime-gated)
                overhead=C * (BANDED_ROWS * BANDED_ROWS * 4 + 16) + V * 5,
                static_slots=None,
                note="one fused dispatch: cell CC (min-label "
                "propagation + pointer jump) + border algebra + "
                "valid-prefix compaction across every chunk; V scales "
                "with instances — data-scaled, runtime-gated",
            ),
            FamilyModel(
                "spill.gather",
                [
                    ArgModel("x", ("N", "D"), FLOAT),
                    ArgModel("idx", ("K",), INT),
                ],
                overhead=K * D * 2,
                static_slots=None,
                note="unbounded statically: scales with resident "
                "payload rows N (gated at runtime)",
            ),
            FamilyModel(
                "halo.merge",
                [
                    ArgModel("ua", ("EH",), INT),
                    ArgModel("ub", ("EH",), INT),
                ],
                # temps: the replicated [NH] int32 label vector and its
                # per-round scatter/ring/jump copies (~4 live at once)
                # per shard; NH (padded node count) is not an arg dim —
                # data-scaled with the per-partition cluster count,
                # runtime-gated like the other data-scaled families
                overhead=_sy("NH") * 4 * 4,
                static_slots=None,
                note="collective halo-merge fixed point "
                "(parallel/halo.py): border-union edges shard over "
                "every mesh axis, the label vector replicates; EH is "
                "the ladder-padded edge count",
            ),
            FamilyModel(
                "serve.query",
                [
                    ArgModel("qpts", ("Q", "D"), FLOAT),
                    ArgModel("spts", ("K", "D"), FLOAT),
                    ArgModel("sids", ("K",), INT),
                ],
                # temps: the [Q, K] measure (f64 on the x64 serving
                # path) + adjacency + a couple of where/min copies;
                # outputs: gid i32 + core i8 + counts i32 per query
                # slot. Trailing eps rides as a plain Python scalar.
                # K is the published skeleton — data-scaled with the
                # stream's window density, runtime-gated.
                overhead=_sy("Q") * _sy("K") * 24 + _sy("Q") * 16,
                static_slots=None,
                note="resident-grid point->cluster query "
                "(dbscan_tpu/serve/query.py): Q is the ladder-padded "
                "query batch (split past DBSCAN_SERVE_QUERY_SLOTS), K "
                "the ladder-padded skeleton — data-scaled, "
                "runtime-gated",
            ),
            FamilyModel(
                "serve.broadcast",
                [
                    ArgModel("spts", ("K", "D"), FLOAT),
                    ArgModel("sids", ("K",), INT),
                ],
                # temps/outs: one owned copy of each input on the
                # replica's device (the identity-plus-zero transfer —
                # the replica must not alias the publisher's buffers).
                # K is the ladder-padded skeleton — data-scaled,
                # runtime-gated like serve.query.
                overhead=_sy("K") * _sy("D") * 8 + _sy("K") * 4,
                static_slots=None,
                note="per-replica consistent-cut skeleton broadcast "
                "(dbscan_tpu/serve/router.py): one dispatch per "
                "non-empty shard per live replica per published cut; "
                "padded at publish time, so steady-state broadcasts "
                "compile ZERO new kernels",
            ),
            FamilyModel(
                "serve.jobs",
                [
                    ArgModel("pts", ("J", "S", "D"), FLOAT),
                    ArgModel("mask", ("J", "S"), BOOL),
                    ArgModel("eps", ("J",), FLOAT),
                    ArgModel("min_points", ("J",), INT),
                ],
                # temps per job: the [S, S] measure (f64) + adjacency
                # + core-CC label passes; outputs: seeds i32 + flags i8
                # per slot. This is ALSO the admission controller's
                # pricing expression (serve/tenancy.py prices candidate
                # batches with exactly this model before dispatch).
                overhead=_sy("J") * _sy("S") * _sy("S") * 24
                + _sy("J") * _sy("S") * 16,
                static_slots={
                    "J": "DBSCAN_SERVE_BATCH_JOBS",
                    "S": "DBSCAN_SERVE_JOB_SLOTS",
                    "D": 4,
                },
                note="pad-and-stack multi-tenant small-job dispatch "
                "(dbscan_tpu/serve/tenancy.py): J jobs of S padded "
                "point slots, per-job eps/min_points traced — the "
                "admission headroom gate prices THIS envelope",
            ),
            FamilyModel(
                "embed.hash",
                [
                    ArgModel("x", ("N", "D"), FLOAT),
                    ArgModel("planes", ("TH", "D"), FLOAT),
                ],
                # temps/outs: the [N, T*H] projection matrix + packed
                # per-table codes + the primary table's projections —
                # bounded by 3x the projection bytes
                overhead=_sy("N") * _sy("TH") * 12,
                static_slots=None,
                note="SRP hash of the embed payload (dbscan_tpu/embed/"
                "lsh.py): one [N, D] x [D, T*H] matmul; N/D are "
                "ladder-padded — data-scaled, runtime-gated",
            ),
            FamilyModel(
                "embed.neighbors",
                [
                    ArgModel("x", ("B", "D"), FLOAT),
                    ArgModel("mask", ("B",), BOOL),
                    ArgModel("ids", ("B",), INT),
                ],
                # temps: one [128, B] similarity slab (+ adjacency/key
                # copies) per lax.map step; outs: the [B, W] neighbor
                # table + seed/flag/count vectors. W (the neighbor-slot
                # rung) is not an arg dim — data-scaled like cellcc's
                # C/V, runtime-gated; trailing eps/eff_min/keep/seed
                # ride as plain Python scalars.
                overhead=E(128) * _sy("B") * 16
                + _sy("B") * (_sy("W") * 8 + 16),
                static_slots=None,
                note="blocked cosine neighbor kernel per embed bucket "
                "(dbscan_tpu/embed/neighbors.py): B is the ladder-"
                "padded bucket width (<= DENSE_MAX_BUCKET via the "
                "dense-width guard), W the ratcheted neighbor-slot "
                "rung — data-scaled, runtime-gated",
            ),
            FamilyModel(
                "embed.quantize",
                [
                    ArgModel("x", ("N", "D"), FLOAT),
                ],
                # temps/outs: the [N, M] chord matrix (f32 on device)
                # + the [M, D] pivot matrix, masses, and the fp/Lloyd
                # working copies. M (the post-ladder IVF cell count) is
                # not an arg dim — data-scaled like embed.neighbors' W,
                # runtime-gated; the fp seed rides as a plain Python
                # scalar.
                overhead=_sy("N") * _sy("M") * 8
                + _sy("M") * (_sy("D") * 8 + 8),
                static_slots=None,
                note="IVF coarse quantizer for the embed engine "
                "(dbscan_tpu/embed/quantize.py): the spill tree's "
                "fp+Lloyd kernel over the padded payload plus the "
                "[N, M] chord matrix against M post-ladder cells — "
                "data-scaled, runtime-gated",
            ),
            FamilyModel(
                "density.core",
                [
                    ArgModel("x", ("N", "D"), FLOAT),
                    ArgModel("mask", ("N",), BOOL),
                    ArgModel("start", (), INT),
                ],
                # temps: one [C, N] f32 distance slab + the top_k
                # working copy per chunk; outs: the [C] chunk vector.
                # C (the DBSCAN_DENSITY_CHUNK packing-window rung,
                # clamped to N) is not an arg dim — data-scaled like
                # embed's W, runtime-gated; the chunk start rides as a
                # TRACED 0-d int32 so every chunk shares one kernel.
                overhead=_sy("C") * _sy("N") * 12 + _sy("C") * 4,
                static_slots=None,
                note="chunked k-th-neighbor core distances "
                "(dbscan_tpu/density/core.py): N is the ladder-padded "
                "payload, one dispatch per DBSCAN_DENSITY_CHUNK rows",
            ),
            FamilyModel(
                "density.boruvka",
                [
                    ArgModel("x", ("N", "D"), FLOAT),
                    ArgModel("mask", ("N",), BOOL),
                    ArgModel("core", ("N",), FLOAT),
                    ArgModel("comp", ("N",), INT),
                ],
                # temps: one [128, N] mutual-reachability slab per
                # lax.map step + the per-point candidate vectors and
                # the scatter-min stages (a handful of [N] arrays);
                # outs: comp' + the selected-edge vectors
                overhead=E(128) * _sy("N") * 16 + _sy("N") * 64,
                static_slots=None,
                note="one Borůvka MST round over mutual-reachability "
                "edges (dbscan_tpu/density/boruvka.py): scatter-min "
                "cheapest-edge selection + union-find contraction; "
                "data-scaled, runtime-gated",
            ),
            FamilyModel(
                "density.condense",
                [
                    ArgModel("eu", ("EP",), INT),
                    ArgModel("ev", ("EP",), INT),
                    ArgModel("ew", ("EP",), FLOAT),
                    ArgModel("valid", ("EP",), BOOL),
                ],
                # temps: the three lexsort key vectors + the perm;
                # outs: five sorted vectors + a scalar — all [EP]
                overhead=_sy("EP") * 64,
                static_slots=None,
                note="MST edge sort under the total order + lambda "
                "prefix (dbscan_tpu/density/condense.py): EP is the "
                "128-step padded edge ladder; data-scaled, "
                "runtime-gated",
            ),
            _level_model(),
            _level_final_model(),
        )
    }


#: pivot-slot ceiling of the level build (mirrors spill._MAX_PIVOTS via
#: spill_device._ladder8's cap; pinned equal by tests/test_spill_tree.py
#: — lint stays stdlib-only, so no import)
LEVEL_PIVOT_CAP = 192


def _level_model() -> "FamilyModel":
    """The level-synchronous spill-tree step (``spill.level``): compact
    the previous level's membership bits into the new slot-contiguous
    layout, then batched pivot selection + membership over the open
    prefix. Trailing scalars (instance totals, halo, slack) ride as
    plain Python numbers. Data-scaled (resident rows N, per-level
    instance capacity M) — runtime-gated like dispatch.resident."""
    N, D, MP, MB, MQ, SP, SP1, T, MS, S, S1 = (
        _sy(n)
        for n in ("N", "D", "MP", "MB", "MQ", "SP", "SP1", "T", "MS",
                  "S", "S1")
    )
    mcap = E(LEVEL_PIVOT_CAP)
    return FamilyModel(
        "spill.level",
        [
            ArgModel("x", ("N", "D"), FLOAT),
            ArgModel("idx_p", ("MP",), INT),
            ArgModel("home_p", ("MP",), BOOL),
            ArgModel("assign_p", ("MP",), INT),
            ArgModel("member_p", ("MP", "MB"), INT),
            ArgModel("base_p", ("SP1",), INT),
            ArgModel("dest", ("SP", "MQ"), INT),
            ArgModel("carry", ("SP",), BOOL),
            ArgModel("out_base", ("T",), INT),
            ArgModel("sel_pos", ("MS",), INT),
            ArgModel("seed_pos", ("S",), INT),
            ArgModel("m_req", ("S",), INT),
            ArgModel("base", ("S1",), INT),
        ],
        # sampled selection rows + the gathered f32 rows and membership
        # working set of the NEW layout (its capacity is duplication-
        # bounded by ~2.4x the previous level's, folded into the MP
        # factors; pivot slots capped at LEVEL_PIVOT_CAP) + the
        # compaction cumsum over the previous layout + per-node pivot
        # tables — deliberately generous upper bounds
        overhead=(
            MS * D * 8
            + MP * D * 16
            + MP * mcap * 32
            + MP * MQ * 16
            + S * mcap * D * 8
            + S * mcap * mcap * 8
        ),
        constraints=[(SP1, SP + 1), (S1, S + 1), (MQ, E(8) * MB)],
        static_slots=None,
        note="one fused dispatch per tree level (compact + build); "
        "unbounded statically: scales with the level's instance "
        "count M (gated at runtime; m slots bounded by "
        "DBSCAN_SPILL_LEVEL_SLOTS)",
    )


def _level_final_model() -> "FamilyModel":
    """The closing compact-only dispatch (``spill.level_final``): the
    last level's children are all leaves/fallbacks, so only the layout
    scatter runs."""
    MP, MB, MQ, SP, SP1, T = (
        _sy(n) for n in ("MP", "MB", "MQ", "SP", "SP1", "T")
    )
    return FamilyModel(
        "spill.level_final",
        [
            ArgModel("idx_p", ("MP",), INT),
            ArgModel("home_p", ("MP",), BOOL),
            ArgModel("assign_p", ("MP",), INT),
            ArgModel("member_p", ("MP", "MB"), INT),
            ArgModel("base_p", ("SP1",), INT),
            ArgModel("dest", ("SP", "MQ"), INT),
            ArgModel("carry", ("SP",), BOOL),
            ArgModel("out_base", ("T",), INT),
        ],
        # the unpacked membership + cumsum over the previous layout plus
        # the (ladder-padded, duplication-bounded) output buffers
        overhead=MP * MQ * 16 + MP * 32,
        constraints=[(SP1, SP + 1), (MQ, E(8) * MB)],
        static_slots=None,
        note="closing compact of the level build; data-scaled, "
        "runtime-gated",
    )


FAMILY_MODELS: Dict[str, FamilyModel] = _models()

# tuple args are validated elementwise with per-element fresh symbols;
# these cross-arg couplings say WHICH tuple args must agree per element
TUPLE_COUPLED = {
    # cores[i].shape == bitses[i].shape; segflags[i] = prod(cores[i])
    "cellcc.postpass": (("cores", "bitses"),),
    # the per-chunk flat arrays all share one slot count per element
    "cellcc.cc": (
        ("cores", "bitses"),
        ("cores", "cells"),
        ("cores", "folds"),
    ),
}


def shape_table(env_fn=None, budget: Optional[int] = None) -> str:
    """The PARITY.md per-dispatch-family predicted-footprint table
    (``python -m dbscan_tpu.lint --shape-table`` prints it)."""
    from dbscan_tpu import config

    env_fn = env_fn or config.env
    budget = budget if budget is not None else DEFAULT_HBM_BYTES
    lines = [
        "| Family | Symbolic args | Overhead (temp+out bytes) | "
        "Knob-bounded worst case | Verdict |",
        "|---|---|---|---|---|",
    ]
    for family in sorted(FAMILY_MODELS):
        m = FAMILY_MODELS[family]
        worst = m.static_worst(env_fn)
        if worst is None:
            wtxt, verdict = "unbounded (data-scaled)", "runtime-gated"
        else:
            wtxt = f"{worst / 2**30:.2f} GiB"
            verdict = (
                "fits" if worst <= budget
                else f"OVER {budget / 2**30:.0f} GiB budget"
            )
        args = "<br>".join(a.render() for a in m.args)
        lines.append(
            f"| `{family}` | {args} | `{m.overhead.render()}` | "
            f"{wtxt} | {verdict} |"
        )
    return "\n".join(lines)


# --- runtime-shared validation ----------------------------------------


def validate_args(family: str, observed: List) -> Tuple[
    Dict[str, int], List[str]
]:
    """Unify observed ``(shape, dtype)`` specs (see
    ``shapecheck.spec_of``) against the family model. Returns
    ``(subst, violations)``; an unknown family is itself a violation.
    TRAILING observed scalars (static-argnum passthrough) are
    tolerated, but an undeclared extra ARRAY argument is a violation —
    a kernel signature growing a buffer the model does not know about
    must fail the cross-check (updating FAMILY_MODELS is the
    registration step)."""
    model = FAMILY_MODELS.get(family)
    if model is None:
        return {}, [f"undeclared dispatch family {family!r}"]
    subst: Dict[str, int] = {}
    problems: List[str] = []
    arrays = list(observed)
    if len(arrays) < len(model.args):
        problems.append(
            f"{family}: {len(arrays)} args observed, model declares "
            f"{len(model.args)}"
        )
        return subst, problems
    for i, extra in enumerate(
        arrays[len(model.args):], start=len(model.args)
    ):
        is_arrayish = isinstance(extra, list) or (
            isinstance(extra, tuple)
            and len(extra) == 2
            and isinstance(extra[0], tuple)
        )
        if is_arrayish:
            problems.append(
                f"{family}: undeclared extra array argument at "
                f"position {i} ({extra!r}) — the model declares "
                f"{len(model.args)} args; register the new buffer in "
                "lint/shapes.py FAMILY_MODELS"
            )
    for spec, obs in zip(model.args, arrays):
        if spec.tuple_of:
            if not isinstance(obs, (list, tuple)):
                problems.append(
                    f"{family}.{spec.name}: expected a tuple of arrays, "
                    f"got {obs!r}"
                )
                continue
            for i, el in enumerate(obs):
                _match_one(
                    family, f"{spec.name}[{i}]", spec, el, {}, problems
                )
            continue
        _match_one(family, spec.name, spec, obs, subst, problems)
    # per-element couplings across tuple args (postpass: cores[i] and
    # bitses[i] share a shape; segflags[i] has prod(cores[i]) slots)
    for pair in TUPLE_COUPLED.get(family, ()):
        tuples = {}
        for spec, obs in zip(model.args, arrays):
            if spec.name in pair and isinstance(obs, (list, tuple)):
                tuples[spec.name] = obs
        if len(tuples) == len(pair):
            a, b = (tuples[n] for n in pair)
            if len(a) != len(b):
                problems.append(
                    f"{family}: {pair[0]} has {len(a)} elements, "
                    f"{pair[1]} has {len(b)}"
                )
            else:
                for i, (ea, eb) in enumerate(zip(a, b)):
                    sa = ea[0] if isinstance(ea, tuple) else None
                    sb = eb[0] if isinstance(eb, tuple) else None
                    if sa is not None and sb is not None and sa != sb:
                        problems.append(
                            f"{family}: {pair[0]}[{i}] shape {sa} != "
                            f"{pair[1]}[{i}] shape {sb}"
                        )
    for lhs, rhs in model.constraints:
        lv = lhs.evaluate(subst)
        rv = rhs.evaluate(subst)
        if lv is not None and rv is not None and lv != rv:
            problems.append(
                f"{family}: constraint {lhs.render()} == {rhs.render()} "
                f"violated ({lv} != {rv}) under {subst}"
            )
    return subst, problems


def _match_one(family, label, spec: ArgModel, obs, subst, problems):
    if not (isinstance(obs, tuple) and len(obs) == 2):
        # non-array observed (None, scalar): scalars are permitted
        # passthroughs only for 0-d model slots — report otherwise
        problems.append(f"{family}.{label}: expected an array, got {obs!r}")
        return
    shape, dtype = obs
    if len(shape) != len(spec.dims):
        problems.append(
            f"{family}.{label}: rank {len(shape)} observed, model "
            f"declares [{','.join(map(str, spec.dims))}]"
        )
        return
    for i, (md, od) in enumerate(zip(spec.dims, shape)):
        model_dim = E.of(Sym(md)) if isinstance(md, str) else E.of(md)
        if not unify_dim(model_dim, int(od), subst):
            problems.append(
                f"{family}.{label}: dim {i} = {od} does not instantiate "
                f"model dim "
                f"{md if isinstance(md, str) else model_dim.render()} "
                f"under {subst}"
            )
            return
    if dtype is not None and dtype not in spec.dtypes:
        problems.append(
            f"{family}.{label}: dtype {dtype} outside the declared "
            f"class {spec.dtypes}"
        )


# --- static rule driver ------------------------------------------------


class _MeshVal:
    """Abstract mesh: axis name -> size (None when not literal)."""

    def __init__(self, axes: Dict[str, Optional[int]]):
        self.axes = axes


class _SpecVal:
    """Abstract PartitionSpec: per-dim axis name (or None)."""

    def __init__(self, entries):
        self.entries = entries


class _JitFn:
    """A name bound to ``jax.jit(shard_map(block, mesh=..,
    in_specs=..))`` inside one scope: calling it checks concrete arg
    dims for divisibility by the partitioning mesh axes."""

    def __init__(self, rules: "_Rules", mesh: Optional[_MeshVal],
                 in_specs: List):
        self.rules = rules
        self.mesh = mesh
        self.in_specs = in_specs

    def absint_call(self, interp, node, args, kwargs):
        if self.mesh is None:
            return UNKNOWN
        for arg, spec in zip(args, self.in_specs):
            if not (isinstance(arg, Arr) and arg.shape is not None):
                continue
            if not isinstance(spec, _SpecVal):
                continue
            for i, axis in enumerate(spec.entries):
                if axis is None or i >= len(arg.shape):
                    continue
                size = self.mesh.axes.get(axis)
                dim = arg.shape[i].const()
                if size and dim is not None and dim % size != 0:
                    self.rules.add(
                        "shard-indivisible",
                        node,
                        f"dim {i} = {dim} of a shard_map input is not "
                        f"divisible by mesh axis {axis!r} (size "
                        f"{size}): the block would see ragged shards — "
                        "pad the dim to a mesh multiple "
                        "(binning._pad_parts) before dispatch",
                    )
        return UNKNOWN


class _Rules:
    """Per-module rule context: wires the interpreter hooks to
    findings."""

    def __init__(self, pkg: Package, mod, findings: List[Finding],
                 budget: int):
        self.pkg = pkg
        self.mod = mod
        self.findings = findings
        self.budget = budget
        self.jitted_local: set = set()
        cg = pkg.callgraph
        if cg is not None:
            for (path, name), _stat in cg.jitted_names.items():
                if path == mod.path:
                    self.jitted_local.add(name)
            # from-imported jit roots callable by bare name
            for name, (src, orig) in mod.from_names.items():
                m2 = cg.by_modname.get(src)
                info = m2.functions.get(orig) if m2 is not None else None
                if info is not None and info.is_jit_root:
                    self.jitted_local.add(name)

    def add(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(
                rule,
                self.mod.path,
                getattr(node, "lineno", 1),
                getattr(node, "col_offset", 0),
                msg,
            )
        )

    # --- interpreter hooks ---------------------------------------------

    def intrinsics(self) -> Dict:
        out = {}
        for name in RATCHET_FNS:
            out[name] = self._ratchet_scalar
        for name in RATCHET_ARRAY_FNS:
            out[name] = self._ratchet_array
        for name in _TRANSPARENT_LAST_ARG:
            out[name] = self._passthrough_last
        for name in _TRANSPARENT_FIRST_ARG:
            out[name] = self._passthrough_first
        out["jit"] = self._jit
        out["Mesh"] = self._mesh
        out["make_mesh"] = self._make_mesh
        out["P"] = self._pspec
        out["PartitionSpec"] = self._pspec
        return out

    @staticmethod
    def _ratchet_scalar(interp, node, args, kwargs):
        return IntVal(E.of(fresh("pad", "ratchet")))

    @staticmethod
    def _ratchet_array(interp, node, args, kwargs):
        return Arr((E.of(fresh("pad", "ratchet")),), "i32")

    @staticmethod
    def _passthrough_last(interp, node, args, kwargs):
        return args[-1] if args else UNKNOWN

    @staticmethod
    def _passthrough_first(interp, node, args, kwargs):
        return args[0] if args else UNKNOWN

    @staticmethod
    def _mesh(interp, node, args, kwargs):
        # Mesh(devices, ("x", "y")): sizes are runtime (device count)
        axes: Dict[str, Optional[int]] = {}
        names = args[1] if len(args) > 1 else kwargs.get("axis_names")
        if isinstance(names, Tup):
            for it in names.items:
                if isinstance(it, Lit) and isinstance(it.v, str):
                    axes[it.v] = None
        return _MeshVal(axes)

    @staticmethod
    def _make_mesh(interp, node, args, kwargs):
        # jax.make_mesh((4, 2), ("x", "y")): literal sizes resolve
        axes: Dict[str, Optional[int]] = {}
        shape = args[0] if args else kwargs.get("axis_shapes")
        names = args[1] if len(args) > 1 else kwargs.get("axis_names")
        if isinstance(shape, Tup) and isinstance(names, Tup):
            for sv, nv in zip(shape.items, names.items):
                if isinstance(nv, Lit) and isinstance(nv.v, str):
                    size = (
                        sv.e.const() if isinstance(sv, IntVal) else None
                    )
                    axes[nv.v] = size
        return _MeshVal(axes)

    @staticmethod
    def _pspec(interp, node, args, kwargs):
        entries = []
        for a in args:
            if isinstance(a, Lit) and isinstance(a.v, str):
                entries.append(a.v)
            elif isinstance(a, Lit) and a.v is None:
                entries.append(None)
            else:
                entries.append(None)
        return _SpecVal(entries)

    def _jit(self, interp, node, args, kwargs):
        """``jax.jit(shard_map(block, mesh=.., in_specs=..))``: return
        a _JitFn so calls through the bound name get the divisibility
        check. Plain jits return UNKNOWN (callable opaque)."""
        if not node.args:
            return UNKNOWN
        target = node.args[0]
        if not isinstance(target, ast.Call):
            return UNKNOWN
        tname = target.func.attr if isinstance(
            target.func, ast.Attribute
        ) else (target.func.id if isinstance(target.func, ast.Name) else "")
        if tname != "shard_map":
            return UNKNOWN
        mesh_v = None
        in_specs: List = []
        for kw in target.keywords:
            if kw.arg == "mesh":
                v = interp.expr(kw.value)
                if isinstance(v, _MeshVal):
                    mesh_v = v
            elif kw.arg == "in_specs":
                v = interp.expr(kw.value)
                if isinstance(v, Tup):
                    in_specs = v.items
                elif isinstance(v, _SpecVal):
                    in_specs = [v]
        return _JitFn(self, mesh_v, in_specs)

    def on_call(self, interp, node, name, args, kwargs):
        # (1) data-dependent leading dims entering a KNOWN jit boundary
        jit_args: Optional[List] = None
        if name in self.jitted_local:
            jit_args = args
        elif name in ("tracked_call",) and len(args) >= 2:
            jit_args = args[2:]
        if jit_args:
            for a in jit_args:
                if not (isinstance(a, Arr) and a.shape):
                    continue
                lead = a.shape[0]
                if Interp._prov(lead) == "data":
                    self.add(
                        "shape-unratcheted-dim",
                        node,
                        "data-dependent leading dim "
                        f"[{lead.render()}] enters a jit boundary "
                        "without a shape ratchet: every distinct value "
                        "mints a fresh jit signature (the compile-storm "
                        "mechanism) — pad it through binning._ratchet /"
                        " _ladder_width / _pad_idx first",
                    )
                    break
        # (2) constructed-array HBM check inside jit-reachable code
        if self._in_jit_scope and name in absint._CREATION:
            shape = interp._shape_from(args[0]) if args else None
            if shape is not None:
                dt, _exp = interp._dtype_from(
                    kwargs.get("dtype", UNKNOWN)
                )
                if dt is None:
                    for a in args[1:]:
                        dt, _exp = interp._dtype_from(a)
                        if dt is not None:
                            break
                total = absint.nbytes(shape, dt or "f32")
                c = total.const() if total is not None else None
                if c is not None and c > self.budget:
                    self.add(
                        "hbm-over-budget",
                        node,
                        f"array of {c / 2**30:.1f} GiB constructed in "
                        "jit-reachable code exceeds the "
                        f"{self.budget / 2**30:.0f} GiB device budget "
                        "— tile it (lax.map batching, the driver's "
                        "mem_cap idiom) or lower the slot knobs",
                    )
    _in_jit_scope = False


def _literal_jnp_f64(mod, findings: List[Finding]) -> None:
    """Parity with the superseded literal rule: a bare ``jnp.float64``
    reference in kernel code is drift even before it flows anywhere."""
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "float64"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jnp"
        ):
            findings.append(
                Finding(
                    "dtype-flow-drift",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    "jnp.float64 in kernel code: the device kernels "
                    "are f32/bf16 (config.Precision) — use the "
                    "configured dtype",
                )
            )


def _static_family_budget(pkg: Package, findings: List[Finding],
                          budget: int) -> None:
    """The knob-bound worst-case gate: every ``tracked_call`` family
    literal in the linted set whose :data:`FAMILY_MODELS` envelope,
    evaluated against the LIVE ``config.ENV_VARS`` values, exceeds the
    device budget."""
    from dbscan_tpu import config
    from dbscan_tpu.lint.callgraph import terminal_name

    for mod in pkg.callgraph.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) not in (
                "tracked_call", "note_compile"
            ):
                continue
            if not (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            fam = node.args[0].value
            model = FAMILY_MODELS.get(fam)
            if model is None:
                continue  # schema-family rule owns unknown literals
            worst = model.static_worst(config.env)
            if worst is not None and worst > budget:
                findings.append(
                    Finding(
                        "hbm-over-budget",
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        f"dispatch family {fam!r} worst-case footprint "
                        f"{worst / 2**30:.1f} GiB exceeds the "
                        f"{budget / 2**30:.0f} GiB device budget under "
                        "the current budget knobs ("
                        + ", ".join(
                            sorted(
                                v
                                for v in model.static_slots.values()
                                if isinstance(v, str)
                            )
                        )
                        + ") — lower them or split the dispatch",
                    )
                )


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    cg = pkg.callgraph
    if cg is None:
        return findings
    budget = DEFAULT_HBM_BYTES
    for mod in cg.modules.values():
        kernel = _kernel_file(mod.path)
        rules = _Rules(pkg, mod, findings, budget)
        if kernel:
            _literal_jnp_f64(mod, findings)

        def emit(rule, node, msg, _rules=rules):
            _rules.add(rule, node, msg)

        def run_one(fn_node, in_jit: bool, _rules=rules, _mod=mod,
                    _kernel=kernel, _emit=emit):
            interp = Interp(
                _emit,
                module_aliases=_mod.import_alias,
                intrinsics=_rules.intrinsics(),
                kernel=_kernel,
                on_call=_rules.on_call,
            )
            _rules._in_jit_scope = in_jit
            params: Dict[str, object] = {}
            args = getattr(fn_node, "args", None)
            info = cg.func_for(fn_node)
            statics = info.static_params if info is not None else set()
            if args is not None:
                for a in list(args.args) + list(args.kwonlyargs):
                    if a.arg in statics:
                        # static-argnum specialization: the param is a
                        # compile-time int the shapes may use as a dim
                        params[a.arg] = IntVal(E.of(fresh(a.arg)))
                    else:
                        params[a.arg] = Arr(None, None, device=in_jit)
            try:
                interp.run(fn_node, params)
            except Exception:
                if absint.STRICT:
                    raise
                # a modeling gap must never break lint: skip the fn

        seen = set()
        for info in mod.all_functions:
            node = info.node
            if id(node) in seen or not hasattr(node, "body"):
                continue
            seen.add(id(node))
            run_one(node, cg.in_reachable(node))
        # module-level statements (kernel constants, builder wiring)
        class _ModFn:
            body = mod.tree.body
        run_one(_ModFn, False)
    _static_family_budget(pkg, findings, budget)
    return findings
