"""graftfault runtime cross-check: validate the static effect model
against a real supervised run.

The static rules (``lint/faultsurface.py`` over ``lint/effects.py``)
reason about what a ``faults.supervised(site, fn)`` callable may mutate;
this module watches the same contract AT RUNTIME so the two check each
other: when ``DBSCAN_FAULTCHECK=1`` (or a test calls :func:`enable`),
every supervised window records the shared-state WRITE accesses the
tsan site hooks observe on the executing thread and asserts

- **mutation containment**: the per-site observed mutation set must be
  a subset of the static effect model's reachable tsan sites for that
  site's supervised callables (plus :data:`FAULTS_BASELINE` — the
  registry/counter state the supervision machinery itself touches when
  windows nest). An observed write the model cannot explain is a
  violation: either the callable grew an effect the analyzer missed
  (fix the model — that IS the registration step) or a retry-safety
  bug shipped;
- **retry idempotence** (test-driven): on injected-transient drills the
  suite compares :func:`fingerprint` of a faulted run against the
  no-fault run's — equal mutation SETS mean the retry re-applied only
  what the clean path applies (tests/test_faultcheck.py).

Attribution is per-thread: a window records the accesses made by the
thread executing the attempt (and any telemetry those calls make on
that thread). Nested windows each record — an outer site's model
reaches the inner callable transitively, so containment composes.

Overhead contract (same discipline as tsan/shapecheck): the DISABLED
path is one module-global truthiness check per supervised attempt and
per tsan write access; enabling costs a thread-local set-add per write
plus a lock merge per window. The static model is parsed lazily at the
first report/assert, never on the hot path.

Reports: :func:`report` (dict), :func:`assert_clean` (raises on any
containment violation), and — under ``DBSCAN_FAULTCHECK_REPORT=path``
— an atexit JSON dump, which is how the tier-1 rerun of the fault +
pipeline suites asserts an empty violation report from outside the
process. :func:`emit_telemetry` publishes the declared ``faultcheck.*``
counters/events when obs is enabled.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Dict, List, Optional, Set, Tuple

from dbscan_tpu import config

_rt: Optional["FaultcheckRuntime"] = None

#: tsan sites the supervision machinery itself touches inside a window
#: (nested supervised calls tick the registry and counters): always
#: allowed, never evidence of a callable-side effect.
FAULTS_BASELINE = frozenset(
    {"faults.registry", "faults.registry_state", "faults.counters"}
)

# thread-local stack of open supervised windows (each frame collects
# the write accesses observed while it is open)
_tls = threading.local()

#: site -> frozenset of statically-reachable tsan sites, or None when
#: the site has no statically-resolvable supervised callable (e.g. the
#: router's replica site, whose callable arrives as an argument).
#: Computed lazily from the installed package; process-cached.
_static_cache: Optional[Dict[str, Optional[frozenset]]] = None


def _base_site(site: str) -> str:
    """Strip the ``@shard`` suffix so fingerprints aggregate per base
    site (faults.shard_site composes ``base@N``)."""
    return site.split("@", 1)[0]


class FaultcheckRuntime:
    """Process-global cross-check state (see module docstring)."""

    def __init__(self):
        # a raw lock on purpose (like tsan's _mu): the runtime is
        # itself diagnostic machinery, invisible to the sanitizer
        self._mu = threading.Lock()
        self.checks = 0
        self.violations: List[dict] = []
        self.sites: Dict[str, dict] = {}  # base site -> record
        # telemetry watermark: emit_telemetry publishes deltas
        self._emitted = {"checks": 0, "violations": 0}

    def settle_window(self, site: str, observed: Set[str]) -> None:
        """Merge one closed window's observations into the per-site
        fingerprint (containment is judged lazily at report time, so
        the window close never pays the static-model parse)."""
        base = _base_site(site)
        with self._mu:
            self.checks += 1
            rec = self.sites.setdefault(
                base, {"calls": 0, "mutations": set()}
            )
            rec["calls"] += 1
            rec["mutations"] |= observed

    def snapshot(self) -> dict:
        """Report with containment judged against the static model.
        The model parse happens OUTSIDE the lock (it loads and walks
        the package source)."""
        model = static_model()
        with self._mu:
            sites = {}
            for base, rec in sorted(self.sites.items()):
                allowed = model.get(base)
                observed = rec["mutations"]
                extra = (
                    sorted(observed - allowed - FAULTS_BASELINE)
                    if allowed is not None
                    else []
                )
                sites[base] = {
                    "calls": rec["calls"],
                    "mutations": sorted(observed),
                    "modeled": allowed is not None,
                    "extra": extra,
                }
                if extra:
                    key = (base, tuple(extra))
                    if key not in self._flagged():
                        self.violations.append(
                            {
                                "kind": "mutation-containment",
                                "site": base,
                                "extra": extra,
                                "detail": (
                                    f"supervised site '{base}' mutated "
                                    f"{extra} at runtime; the static "
                                    "effect model allows only "
                                    f"{sorted(allowed)}"
                                ),
                            }
                        )
            return {
                "enabled": True,
                "checks": self.checks,
                "sites": sites,
                "violations": list(self.violations),
            }

    def _flagged(self) -> Set[Tuple[str, tuple]]:
        """Dedup key set for already-recorded containment violations
        (snapshot is re-entrant: report -> emit -> atexit dump)."""
        return {
            (v["site"], tuple(v["extra"]))
            for v in self.violations
            if v.get("kind") == "mutation-containment"
        }


def _empty_report() -> dict:
    return {"enabled": False, "checks": 0, "sites": {}, "violations": []}


# --- supervised-window hooks (called from faults.supervised) -----------


def begin(site: str) -> None:
    """Open a window on the calling thread. faults.supervised guards
    this behind the one ``_rt is not None`` truthiness check."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append((site, set()))


def end(site: str) -> None:
    """Close the innermost window and merge its observations (called
    from a finally, so fault paths settle too)."""
    rt = _rt
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    opened, observed = stack.pop()
    if rt is not None:
        rt.settle_window(opened, observed)


def note_access(site_name: str) -> None:
    """Record one shared-state WRITE into every open window on this
    thread (tsan.access forwards writes here; nested windows each see
    the mutation so outer fingerprints stay complete)."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    for _, observed in stack:
        observed.add(site_name)


# --- static model ------------------------------------------------------


def _compute_static_model() -> Dict[str, Optional[frozenset]]:
    """site -> allowed tsan sites, from the installed package source:
    resolve every ``supervised(site, fn)`` call's callable and union
    the effect model's reachable tsan sites over its call closure.
    Declared sites whose callable is not statically resolvable map to
    None (containment is skipped — the static rules already require a
    drill for every consumed site, so the gap is visible there)."""
    import dbscan_tpu
    from dbscan_tpu import faults
    from dbscan_tpu.lint import callgraph, effects, faultsurface
    from dbscan_tpu.lint.core import load_package

    pkg = load_package(
        [os.path.dirname(os.path.abspath(dbscan_tpu.__file__))]
    )
    pkg.callgraph = cg = callgraph.build(pkg)
    model = effects.EffectModel(cg)
    allowed: Dict[str, Optional[frozenset]] = {
        site: None for site in faults.SITES
    }
    for sc in faultsurface.site_consumptions(pkg):
        if (
            sc.site is None
            or sc.kind != "supervised"
            or len(sc.call.args) < 2
            or sc.info is None
        ):
            continue
        types = callgraph.local_types(cg, sc.info)
        fn = callgraph.callable_argument(
            cg, sc.info, sc.call.args[1], types
        )
        if fn is None:
            continue
        reach = effects.callable_tsan_sites(model, fn)
        base = _base_site(sc.site)
        prev = allowed.get(base)
        allowed[base] = frozenset(reach) | (prev or frozenset())
    return allowed


def static_model() -> Dict[str, Optional[frozenset]]:
    """The cached site -> allowed-mutations model (parsed once per
    process, on the first report/assert — never on the hot path)."""
    global _static_cache
    if _static_cache is None:
        _static_cache = _compute_static_model()
    return _static_cache


# --- public API --------------------------------------------------------


def runtime() -> Optional[FaultcheckRuntime]:
    """The live runtime, or None when disabled — the ONE check
    faults.supervised and tsan.access pay on the disabled path."""
    return _rt


def enabled() -> bool:
    return _rt is not None


def enable() -> FaultcheckRuntime:
    """Turn the cross-check on (idempotent); returns the runtime."""
    global _rt
    if _rt is None:
        _rt = FaultcheckRuntime()
    return _rt


def disable() -> None:
    global _rt
    _rt = None


def reset() -> None:
    """Fresh runtime if enabled (drop recorded state, keep recording)."""
    global _rt
    if _rt is not None:
        _rt = FaultcheckRuntime()


def fingerprint(site: str) -> Tuple[str, ...]:
    """The sorted observed-mutation set for one base site — the value
    the retry-idempotence drills compare between a faulted and a
    no-fault run. Empty when disabled or the site never ran."""
    rt = _rt
    if rt is None:
        return ()
    with rt._mu:
        rec = rt.sites.get(_base_site(site))
        return tuple(sorted(rec["mutations"])) if rec else ()


def report() -> dict:
    """The current cross-check report (a disabled checker reports
    ``enabled: False`` with empty tables)."""
    rt = _rt
    if rt is None:
        return _empty_report()
    return rt.snapshot()


def assert_clean() -> None:
    """Raise AssertionError when the run recorded any containment
    violation (the test-suite gate)."""
    rep = report()
    if rep["violations"]:
        raise AssertionError(
            f"faultcheck found {len(rep['violations'])} violation(s): "
            + json.dumps(rep["violations"], indent=2, default=str)
        )


def emit_telemetry() -> None:
    """Publish the declared ``faultcheck.*`` counters and any pending
    violation events (no-op unless both the checker and obs are
    enabled). Emits DELTAS since the last call, so periodic publication
    never double-counts."""
    rt = _rt
    if rt is None:
        return
    from dbscan_tpu import obs

    if not obs.active():
        return
    rep = rt.snapshot()  # judges containment against the static model
    with rt._mu:
        checks, nviol = rt.checks, len(rt.violations)
        done = dict(rt._emitted)
        rt._emitted = {"checks": checks, "violations": nviol}
        fresh = rt.violations[done["violations"]:nviol]
    obs.count("faultcheck.checks", checks - done["checks"])
    obs.count("faultcheck.violations", nviol - done["violations"])
    for v in fresh:
        obs.event(
            "faultcheck.violation",
            site=v.get("site", ""),
            detail=v.get("detail", ""),
        )
    del rep


def write_report(path: str) -> str:
    """Write the JSON report atomically; returns the path. Publishes
    pending ``faultcheck.*`` telemetry deltas first (the one product
    call site — the ``DBSCAN_FAULTCHECK_REPORT`` atexit hook)."""
    emit_telemetry()
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=1, sort_keys=True, default=str)
    os.replace(tmp, path)
    return path


def _env_init() -> None:
    """Activate from the environment at import: ``DBSCAN_FAULTCHECK=1``
    turns recording on; ``DBSCAN_FAULTCHECK_REPORT=path`` additionally
    dumps the JSON report at process exit (how the tier-1 subprocess
    rerun of the fault/pipeline suites is asserted clean from
    outside)."""
    if config.env("DBSCAN_FAULTCHECK"):
        enable()
        path = config.env("DBSCAN_FAULTCHECK_REPORT")
        if path:
            atexit.register(write_report, path)


_env_init()
