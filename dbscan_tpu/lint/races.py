"""graftcheck race rules: shared-state discipline on the PullEngine
worker slice, plus whole-repo lock hygiene.

PR 5 made the codebase genuinely concurrent: the pull-pipeline worker
(``parallel/pipeline.py``) runs pulls, host finalize, and
fault-supervised retries off the main thread against process-global
state (``faults.counters``, the fault registry, the obs registries).
These rules machine-check the discipline that code relies on:

- ``race-unlocked-shared`` — a WRITE to module-global state (or to
  ``self`` attributes of a lock-owning class) from a function reachable
  from a PullEngine worker callable (``callgraph.walk_worker``), not
  lexically inside a ``with <lock>`` block and not thread-local.
  Scope notes (the rule's designed false-positive boundary, pinned by
  the fixture tests): writes through parameters/locals are exempt —
  objects handed TO the worker (PullJob records, chunk record dicts)
  are ownership-transferred, ordered by the job's completion event,
  and the runtime sanitizer (``lint/tsan.py``) is the layer that
  watches those; ``__init__`` bodies are exempt (object not yet
  shared); attributes reached through a ``threading.local()`` attr are
  exempt; a function whose name ends in ``_locked`` asserts "caller
  holds the lock" (the repo's existing convention —
  ``PullEngine._start_ready_locked``, ``Tracer._trim_locked``) and its
  body is treated as locked — an assertion the runtime sanitizer
  checks for real, since the lockset it records at the shared access
  is empty if a caller ever breaks the convention.
- ``race-lock-order`` — a cycle in the whole-repo lock-acquisition-
  order graph. Lock identities are RESOLVED (module-global lock
  constructions and ``self.<attr> = threading.Lock()/tsan.lock(...)``
  class attrs); edges come from lexically nested ``with`` blocks AND
  from calls, inside a ``with L:`` body, to functions whose transitive
  acquisition set is known (so ``with A: helper()`` where helper takes
  B still yields A->B). A ``with L:`` body re-acquiring non-reentrant
  L is reported under the same rule (self-deadlock).
- ``race-sync-under-lock`` — a blocking device sync
  (``jax.block_until_ready`` / ``device_get`` / ``pull_to_host`` /
  ``.item()``) lexically inside a ``with <lock>`` body, anywhere in the
  repo: a multi-second device wait while holding a lock the pull worker
  or a telemetry hook needs is a stall (or deadlock) amplifier.

"Provably under a lock" accepts: a with-item that resolves to a known
lock (see ``callgraph._lock_ctor``) or whose terminal name looks like
one (``*lock``/``*cv``/``*cond``/``*mutex``) — name-based items guard
protection checks but are excluded from the ORDER graph, which only
trusts resolved identities.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from dbscan_tpu.lint.core import Finding, Package

_LOCK_NAME_RE = re.compile(r"(^|_)(lock|locks|lk|cv|cond|condition|mutex)$")

#: method names that mutate their receiver in place
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "clear", "remove", "discard", "setdefault", "appendleft", "popleft",
    "extendleft",
}

#: blocking device syncs (race-sync-under-lock)
_SYNC_ATTRS = {"block_until_ready", "device_get", "pull_to_host", "item"}


def _root_name(expr: ast.AST) -> Optional[str]:
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _attr_chain(expr: ast.AST) -> List[str]:
    """Attribute names along the access path (outermost last)."""
    out: List[str] = []
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        if isinstance(expr, ast.Attribute):
            out.append(expr.attr)
        expr = expr.value
    out.reverse()
    return out


def _lock_identity(cg, info, expr) -> Optional[Tuple[str, bool]]:
    """Resolved lock identity (id, reentrant) for a with-item/lock
    expression, or None. Identities: ``<modname>.<global>`` for module
    locks, ``<ClassQual>.<attr>`` for instance locks."""
    mod = info.module
    if isinstance(expr, ast.Name):
        if expr.id in mod.lock_globals:
            return (f"{mod.modname}.{expr.id}", mod.lock_globals[expr.id])
        tgt = mod.from_names.get(expr.id)
        if tgt is not None:
            m2 = cg.by_modname.get(tgt[0])
            if m2 is not None and tgt[1] in m2.lock_globals:
                return (
                    f"{m2.modname}.{tgt[1]}",
                    m2.lock_globals[tgt[1]],
                )
        return None
    if isinstance(expr, ast.Attribute):
        from dbscan_tpu.lint import callgraph as cg_mod

        bt = cg_mod.expr_type(cg, info, expr.value)
        if bt is not None and expr.attr in bt.lock_attrs:
            return (
                f"{bt.qualname}.{expr.attr}",
                expr.attr in bt.rlock_attrs,
            )
        # module-alias global lock: pipe_mod._engine_lock
        if isinstance(expr.value, ast.Name):
            modname = mod.import_alias.get(expr.value.id)
            if modname is None and expr.value.id in mod.from_names:
                src, orig = mod.from_names[expr.value.id]
                modname = f"{src}.{orig}"
            if modname is not None:
                m2 = cg.by_modname.get(modname)
                if m2 is not None and expr.attr in m2.lock_globals:
                    return (
                        f"{m2.modname}.{expr.attr}",
                        m2.lock_globals[expr.attr],
                    )
    return None


def _lockish(cg, info, expr) -> bool:
    """Does this with-item look like a lock at all (resolved identity
    OR a lock-looking terminal name)? Used for protection checks."""
    if _lock_identity(cg, info, expr) is not None:
        return True
    name = None
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    return name is not None and _LOCK_NAME_RE.search(name) is not None


# --- race-unlocked-shared ---------------------------------------------


class _SharedWriteScanner(ast.NodeVisitor):
    def __init__(self, cg, info, findings: List[Finding]):
        self.cg = cg
        self.info = info
        self.mod = info.module
        self.findings = findings
        # the `_locked` suffix is the repo's caller-holds-the-lock
        # convention; the runtime sanitizer validates it (empty lockset
        # at the access = a caller broke it)
        self.lock_depth = 1 if info.name.endswith("_locked") else 0
        node = info.node
        # names bound locally (params + local assignments): writes
        # through them are ownership-transfer, not shared-state, and a
        # local that shadows a module global is local
        self.local_binds: Set[str] = set()
        args = getattr(node, "args", None)
        if args is not None:
            for a in (
                list(args.args)
                + list(args.kwonlyargs)
                + list(args.posonlyargs)
            ):
                self.local_binds.add(a.arg)
            if args.vararg:
                self.local_binds.add(args.vararg.arg)
            if args.kwarg:
                self.local_binds.add(args.kwarg.arg)
        self.global_decls: Set[str] = set()
        # scope-bounded: a nested def's locals/`global` declarations
        # must not shadow-exempt (or spuriously globalize) the
        # enclosing function's writes
        from dbscan_tpu.lint.callgraph import walk_scope

        for n in walk_scope(node):
            if isinstance(n, ast.Global):
                self.global_decls.update(n.names)
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        self.local_binds.add(t.id)
            elif isinstance(n, ast.AnnAssign) and isinstance(
                n.target, ast.Name
            ):
                self.local_binds.add(n.target.id)
            elif isinstance(n, ast.NamedExpr) and isinstance(
                n.target, ast.Name
            ):
                self.local_binds.add(n.target.id)
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                if isinstance(n.target, ast.Name):
                    self.local_binds.add(n.target.id)
                elif isinstance(n.target, ast.Tuple):
                    for el in n.target.elts:
                        if isinstance(el, ast.Name):
                            self.local_binds.add(el.id)
            elif isinstance(n, ast.withitem) and isinstance(
                n.optional_vars, ast.Name
            ):
                self.local_binds.add(n.optional_vars.id)
        self.local_binds -= self.global_decls

    def _skip_nested(self, node):
        # nested defs/lambdas are scanned on their own when reachable
        # (walk_worker pushes resolved callees and callable arguments),
        # each with its OWN lock context: a closure defined inside a
        # `with lock:` block does not run under that lock
        return None

    visit_FunctionDef = _skip_nested
    visit_AsyncFunctionDef = _skip_nested
    visit_Lambda = _skip_nested

    def visit_With(self, node: ast.With):
        locked = any(
            _lockish(self.cg, self.info, item.context_expr)
            for item in node.items
        )
        for item in node.items:
            self.visit(item)
        if locked:
            self.lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if locked:
            self.lock_depth -= 1

    visit_AsyncWith = visit_With

    def _flag(self, node, what: str) -> None:
        self.findings.append(
            Finding(
                "race-unlocked-shared",
                self.mod.path,
                node.lineno,
                node.col_offset,
                f"{what} on the pull-engine worker slice without a lock: "
                "this function runs concurrently with the main thread "
                "(reachable from a PullEngine work()/on_start callable); "
                "guard the access with a threading.Lock (register it via "
                "lint.tsan.lock for the runtime sanitizer) or make the "
                "state thread-local",
            )
        )

    def _module_shared(self, root: str) -> bool:
        """Is ``root`` module-global mutable state (not shadowed by a
        local binding, not itself a lock)?"""
        if root in self.local_binds:
            return False
        mod = self.mod
        if root in mod.lock_globals or root in mod.tls_globals:
            return False
        if root in mod.module_globals:
            return True
        tgt = mod.from_names.get(root)
        if tgt is not None:
            m2 = self.cg.by_modname.get(tgt[0])
            if m2 is not None and tgt[1] in m2.module_globals:
                return (
                    tgt[1] not in m2.lock_globals
                    and tgt[1] not in m2.tls_globals
                )
        return False

    def _self_shared(self, expr: ast.AST) -> bool:
        """A write rooted at ``self`` in a method of a lock-owning class
        (the class declares shared mutable state by owning a lock);
        exempt __init__ (not yet shared), lock attrs themselves, and
        anything reached through a threading.local() attribute."""
        owner = self.info.owner_class
        if owner is None or not owner.lock_attrs:
            return False
        if self.info.name == "__init__":
            return False
        chain = _attr_chain(expr)
        if not chain:
            return False
        if any(a in owner.tls_attrs for a in chain):
            return False
        if chain[0] in owner.lock_attrs:
            return False
        return True

    def _check_store(self, target: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._check_store(el)
            return
        if self.lock_depth > 0:
            return
        if isinstance(target, ast.Name):
            if target.id in self.global_decls:
                self._flag(
                    target, f"write to module global {target.id!r}"
                )
            return
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            root = _root_name(target)
            if root is None:
                return
            if root == "self":
                if self._self_shared(target):
                    self._flag(
                        target,
                        "write to shared attribute "
                        f"'self.{'.'.join(_attr_chain(target))}' of a "
                        "lock-owning class",
                    )
            elif self._module_shared(root):
                self._flag(
                    target,
                    f"write through module global {root!r}",
                )

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._check_store(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_store(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._check_store(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            self._check_store(t)
        self.generic_visit(node)

    def _custom_method(self, recv: ast.AST, attr: str) -> bool:
        """Receiver is an instance of a linted class that defines
        ``attr`` as a method (``counters.add(...)``): the method body is
        scanned on its own, so the call site is not a container
        mutation."""
        from dbscan_tpu.lint import callgraph as cg_mod

        t = cg_mod.expr_type(self.cg, self.info, recv)
        return t is not None and attr in t.methods

    def visit_Call(self, node: ast.Call):
        f = node.func
        if (
            self.lock_depth == 0
            and isinstance(f, ast.Attribute)
            and f.attr in _MUTATORS
            and not self._custom_method(f.value, f.attr)
        ):
            root = _root_name(f.value)
            if root == "self":
                if self._self_shared(f.value):
                    self._flag(
                        node,
                        f".{f.attr}() mutation of shared attribute "
                        f"'self.{'.'.join(_attr_chain(f.value))}'",
                    )
            elif root is not None and self._module_shared(root):
                self._flag(
                    node,
                    f".{f.attr}() mutation through module global "
                    f"{root!r}",
                )
        elif (
            self.lock_depth == 0
            and isinstance(f, ast.Name)
            and f.id == "setattr"
            and node.args
        ):
            obj = node.args[0]
            root = _root_name(obj)
            if root == "self":
                owner = self.info.owner_class
                if (
                    owner is not None
                    and owner.lock_attrs
                    and self.info.name != "__init__"
                ):
                    self._flag(node, "setattr() on shared self")
            elif root is not None and self._module_shared(root):
                self._flag(
                    node, f"setattr() through module global {root!r}"
                )
        self.generic_visit(node)


def _check_unlocked_shared(pkg: Package, findings: List[Finding]) -> None:
    cg = pkg.callgraph
    seen: Set[int] = set()
    for info in cg.worker_funcs():
        if id(info.node) in seen:
            continue
        seen.add(id(info.node))
        scanner = _SharedWriteScanner(cg, info, findings)
        body = getattr(info.node, "body", [])
        for stmt in body if isinstance(body, list) else [body]:
            scanner.visit(stmt)


# --- race-lock-order ---------------------------------------------------


def _function_lock_facts(cg, info):
    """(direct_acquires, with_edges, call_sites_under_lock) for one
    function. with_edges are (outer_id, inner_id, node) from lexical
    nesting; call_sites_under_lock are (outer_id, callee FuncInfo,
    node) for later transitive-edge expansion. Also detects
    self-reacquisition of a non-reentrant lock."""
    from dbscan_tpu.lint import callgraph as cg_mod

    direct: Set[str] = set()
    edges: List[Tuple[str, str, ast.AST]] = []
    calls: List[Tuple[str, object, ast.AST]] = []
    self_deadlocks: List[Tuple[str, ast.AST]] = []
    types = cg_mod.local_types(cg, info)

    def walk(node, held: Tuple[Tuple[str, bool], ...]):
        if node is not info.node and isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
             ast.ClassDef),
        ):
            return  # nested defs have their own facts
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                ident = _lock_identity(cg, info, item.context_expr)
                if ident is not None:
                    acquired.append(ident)
            for ident, reentrant in acquired:
                direct.add(ident)
                for outer, outer_re in held:
                    if outer == ident:
                        if not (reentrant and outer_re):
                            self_deadlocks.append((ident, node))
                    else:
                        edges.append((outer, ident, node))
            new_held = held + tuple(acquired)
            for item in node.items:
                walk(item, held)
            for stmt in node.body:
                walk(stmt, new_held)
            return
        if isinstance(node, ast.Call) and held:
            callee = cg_mod.resolve_callable(cg, info, node.func, types)
            if callee is not None:
                for outer, _re in held:
                    calls.append((outer, callee, node))
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    walk(info.node, ())
    return direct, edges, calls, self_deadlocks


def _check_lock_order(pkg: Package, findings: List[Finding]) -> None:
    cg = pkg.callgraph
    facts: Dict[int, tuple] = {}
    all_funcs = []
    for mod in cg.modules.values():
        for info in mod.all_functions:
            if id(info.node) in facts:
                continue
            facts[id(info.node)] = _function_lock_facts(cg, info)
            all_funcs.append(info)

    # transitive acquisition sets (fixed point over the call graph)
    from dbscan_tpu.lint import callgraph as cg_mod

    trans: Dict[int, Set[str]] = {
        nid: set(f[0]) for nid, f in facts.items()
    }
    callees: Dict[int, Set[int]] = {}
    for info in all_funcs:
        types = cg_mod.local_types(cg, info)
        outs: Set[int] = set()
        # scope-bounded: a call INSIDE a nested def is the nested
        # scope's acquisition, not this function's — attributing it
        # here would invent lock-order edges for closures that are
        # merely constructed (not run) under a lock
        for node in cg_mod.walk_scope(info.node):
            if isinstance(node, ast.Call):
                callee = cg_mod.resolve_callable(
                    cg, info, node.func, types
                )
                if callee is not None and id(callee.node) in facts:
                    outs.add(id(callee.node))
        callees[id(info.node)] = outs
    for _ in range(24):  # bounded fixed point
        changed = False
        for nid, outs in callees.items():
            cur = trans[nid]
            before = len(cur)
            for o in outs:
                cur |= trans.get(o, set())
            changed = changed or len(cur) != before
        if not changed:
            break

    # edge graph: lexical nesting + locks acquired by calls under a lock
    graph: Dict[Tuple[str, str], Tuple[str, int, int]] = {}
    self_dead: List[Tuple[str, str, int, int]] = []
    reentrant_locks: Dict[str, bool] = {}
    for mod in cg.modules.values():
        for n, r in mod.lock_globals.items():
            reentrant_locks[f"{mod.modname}.{n}"] = r
        for cls in mod.classes.values():
            for a in cls.lock_attrs:
                reentrant_locks[f"{cls.qualname}.{a}"] = (
                    a in cls.rlock_attrs
                )
    for info in all_funcs:
        direct, edges, calls, dead = facts[id(info.node)]
        for outer, inner, node in edges:
            graph.setdefault(
                (outer, inner), (info.path, node.lineno, node.col_offset)
            )
        for outer, callee, node in calls:
            for inner in trans.get(id(callee.node), ()):
                if inner != outer:
                    graph.setdefault(
                        (outer, inner),
                        (info.path, node.lineno, node.col_offset),
                    )
                elif not reentrant_locks.get(inner, False):
                    # call-transitive re-acquire of a held non-reentrant
                    # lock: `with L: helper()` where helper takes L —
                    # the same guaranteed deadlock as lexical nesting
                    self_dead.append(
                        (inner, info.path, node.lineno, node.col_offset)
                    )
        for ident, node in dead:
            self_dead.append(
                (ident, info.path, node.lineno, node.col_offset)
            )

    for ident, path, line, col in self_dead:
        findings.append(
            Finding(
                "race-lock-order",
                path,
                line,
                col,
                f"non-reentrant lock {ident!r} re-acquired while already "
                "held (self-deadlock); use an RLock or restructure so "
                "the inner acquisition happens outside the outer block",
            )
        )

    # cycle detection over the order graph
    adj: Dict[str, Set[str]] = {}
    for a, b in graph:
        adj.setdefault(a, set()).add(b)
    in_cycle: Set[Tuple[str, str]] = set()
    for a, b in graph:
        # is a reachable from b? then a->b closes a cycle
        stack, seen = [b], set()
        while stack:
            n = stack.pop()
            if n == a:
                in_cycle.add((a, b))
                break
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj.get(n, ()))
    for a, b in sorted(in_cycle):
        path, line, col = graph[(a, b)]
        findings.append(
            Finding(
                "race-lock-order",
                path,
                line,
                col,
                f"lock-order cycle: {a!r} is acquired before {b!r} here, "
                "but the reverse order also exists in the repo — two "
                "threads taking the two paths deadlock; pick one global "
                "order and restructure the other site",
            )
        )


# --- race-sync-under-lock ----------------------------------------------


def _check_sync_under_lock(pkg: Package, findings: List[Finding]) -> None:
    cg = pkg.callgraph
    for mod in cg.modules.values():
        for info in mod.all_functions:

            def walk(node, depth, info=info):
                if node is not info.node and isinstance(
                    node,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    return
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    d = depth
                    if any(
                        _lockish(cg, info, item.context_expr)
                        for item in node.items
                    ):
                        d = depth + 1
                    for item in node.items:
                        walk(item, depth, info)
                    for stmt in node.body:
                        walk(stmt, d, info)
                    return
                if (
                    depth > 0
                    and isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS
                    and not (node.func.attr == "item" and node.args)
                ):
                    findings.append(
                        Finding(
                            "race-sync-under-lock",
                            mod.path,
                            node.lineno,
                            node.col_offset,
                            f"blocking device sync "
                            f"'.{node.func.attr}()' while holding a "
                            "lock: a multi-second device wait under a "
                            "lock stalls (or deadlocks against) every "
                            "thread that needs it — move the sync "
                            "outside the locked region",
                        )
                    )
                for child in ast.iter_child_nodes(node):
                    walk(child, depth, info)

            depth0 = 1 if info.name.endswith("_locked") else 0
            body = getattr(info.node, "body", [])
            for stmt in body if isinstance(body, list) else [body]:
                walk(stmt, depth0)


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    _check_unlocked_shared(pkg, findings)
    _check_lock_order(pkg, findings)
    _check_sync_under_lock(pkg, findings)
    return findings


# --- the static worker-slice model (consumed by the tsan tests) -------


def worker_tsan_sites(pkg: Package) -> Set[str]:
    """Site-name literals of every ``tsan.access("<site>", ...)`` hook
    located in a worker-reachable function — the STATIC model of the
    shared state the pull worker may touch. tests/test_tsan.py asserts
    the runtime sanitizer's observed worker access set is contained in
    this (divergence = the static model went stale = test failure)."""
    cg = pkg.callgraph
    sites: Set[str] = set()
    for info in cg.worker_funcs():
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and f.attr == "access"
                and isinstance(f.value, ast.Name)
                and "tsan" in f.value.id
            ):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) and (
                isinstance(node.args[0].value, str)
            ):
                sites.add(node.args[0].value)
    return sites
