"""Package call graph: jit roots, trace-time reachability, worker-slice
reachability (graftcheck), and jit call-site metadata.

What counts as a jit root (a function whose body runs under tracing):

- ``@jax.jit`` / ``@jit`` decorated functions;
- ``@functools.partial(jax.jit, static_argnums=/static_argnames=...)``
  (and the bare ``partial`` spelling);
- functions WRAPPED at a call site — ``jax.jit(block)``,
  ``jax.jit(jax.shard_map(block, ...))`` (the shard_map/vmap/pmap
  wrapper is transparent), ``jax.jit(lambda ...)``. Name lookup is
  scope-aware: the repo's builder idiom defines a local ``block``/``fn``
  per builder, so ``jax.jit(fn)`` resolves through the lexical scope
  chain, not a flat module table;

plus everything transitively called from a root through names the
import maps and scope chains can resolve WITHIN the linted file set
(jnp./lax. calls resolve nowhere and stop the walk, by design). The
reachable set is what the host-sync rules scan: a ``.item()`` there
either breaks under trace or silently syncs the host when the helper
is also used outside jit — both reportable.

Also exported for runtime use: :func:`tracked_call_sites` maps every
``obs_compile.tracked_call("<family>", ...)`` literal to its file:line,
which `obs/compile.py` folds into the recompile-storm warning so the
log names the dispatch site, not just the family.

graftcheck extensions (PR 6) — the race/collective rule families need
more resolving power than the jit walk:

- **classes and methods**: every ``ClassDef`` gets a :class:`ClassInfo`
  with its method table, the attribute types its ``__init__`` pins
  (``self.x = <annotated param>`` / ``self.x = ClassName(...)``), and
  its lock/thread-local attributes (``self._lock = threading.Lock()``,
  ``self._cv = tsan.condition(...)``, ``self._tls = threading.local()``)
  — the tables the race rules consult for "provably under a lock";
- **instance typing**: a lightweight flow pass (:func:`local_types` /
  :func:`expr_type`) resolves ``x = ClassName(...)``, module-level
  singletons (``counters = FaultCounters()``), annotated module globals
  (``_state: Optional[ObsState]``), attribute chains through the class
  attr-type tables, and calls through return annotations
  (``def get_registry() -> FaultRegistry``) — which is what lets the
  worker walk follow ``obs.count`` into ``st.metrics.count`` and
  ``reg.next_ordinal`` into ``FaultRegistry.next_ordinal``;
- **the worker slice** (:func:`walk_worker`, ``cg.worker_reachable``):
  every function reachable from a PullEngine worker callable — the
  ``work``/``on_start`` arguments of ``<engine>.submit(...)`` calls
  (receivers assigned from ``get_engine()``/``PullEngine(...)``) and
  ``threading.Thread(target=...)`` targets — walked with CALLABLE
  ARGUMENTS propagated (``supervised(site, lambda _b: ...)`` puts the
  lambda on the worker), because that code runs concurrently with the
  main thread and is what the ``race-*`` rules scan.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

_JIT_NAMES = ("jit",)  # attribute or bare name
_WRAPPER_ATTRS = ("shard_map", "pmap", "vmap", "checkpoint", "remat")


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` as an expression."""
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    return False


def _jit_statics(call: ast.Call) -> bool:
    """Whether a ``jax.jit(...)`` / ``partial(jax.jit, ...)`` call names
    static_argnums/static_argnames."""
    return any(
        kw.arg in ("static_argnums", "static_argnames")
        for kw in call.keywords
        if kw.arg
    )


def _is_partial(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr == "partial"
    if isinstance(node, ast.Name):
        return node.id == "partial"
    return False


class FuncInfo:
    """One function definition in the linted set."""

    def __init__(
        self,
        module: "ModuleInfo",
        node: ast.AST,
        qualname: str,
        scope_node: ast.AST,
    ):
        self.module = module
        self.node = node  # FunctionDef | AsyncFunctionDef | Lambda
        self.qualname = qualname
        self.scope_node = scope_node  # enclosing module/function node
        self.is_jit_root = False
        self.jit_has_statics = False
        self.static_params: Set[str] = set()
        self.jit_site: Optional[Tuple[str, int]] = None
        self.owner_class: Optional["ClassInfo"] = None  # method owner

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")

    @property
    def path(self) -> str:
        return self.module.path


class ClassInfo:
    """One class definition: method table plus the attribute facts the
    graftcheck race rules consult (attr types, lock attrs, thread-local
    attrs)."""

    def __init__(self, module: "ModuleInfo", node: ast.ClassDef, qualname: str):
        self.module = module
        self.node = node
        self.qualname = qualname
        self.methods: Dict[str, FuncInfo] = {}
        #: self.<attr> -> ClassInfo, from __init__ assignments of
        #: annotated params / direct ClassName(...) constructions
        self.attr_types: Dict[str, "ClassInfo"] = {}
        #: self.<attr> assigned threading.Lock/RLock/Condition() or
        #: tsan.lock/rlock/condition(...) — holding one of these is the
        #: "provably locked" evidence the race rules accept
        self.lock_attrs: Set[str] = set()
        #: lock attrs whose constructor is reentrant (RLock/tsan.rlock)
        self.rlock_attrs: Set[str] = set()
        #: self.<attr> assigned threading.local() — per-thread, exempt
        self.tls_attrs: Set[str] = set()

    @property
    def name(self) -> str:
        return self.node.name


class ModuleInfo:
    """Per-module function index, lexical scope tables, import maps."""

    def __init__(self, path: str, modname: str, tree: ast.Module):
        self.path = path
        self.modname = modname
        self.tree = tree
        #: module-level simple-name table (outermost def wins)
        self.functions: Dict[str, FuncInfo] = {}
        #: id(scope node) -> {simple name -> FuncInfo} for every scope
        self.scopes: Dict[int, Dict[str, FuncInfo]] = {id(tree): {}}
        self.all_functions: List[FuncInfo] = []
        self.import_alias: Dict[str, str] = {}  # alias -> module dotted
        self.from_names: Dict[str, Tuple[str, str]] = {}  # name -> (mod, orig)
        self.classes: Dict[str, ClassInfo] = {}
        #: module-global name -> ClassInfo for names bound to an
        #: instance (``counters = FaultCounters()``), including ones
        #: assigned through ``global`` inside functions
        self.instance_types: Dict[str, ClassInfo] = {}
        #: module-level AnnAssign types (``_state: Optional[ObsState]``)
        self.global_types: Dict[str, ClassInfo] = {}
        #: module-level string constants (``PARTS_AXIS = "parts"``)
        self.constants: Dict[str, str] = {}
        #: every module-global binding name (top-level assignments plus
        #: any name a function declares ``global``) — the shared-state
        #: roots the race rules watch
        self.module_globals: Set[str] = set()
        #: module-global locks: name -> reentrant? (threading/tsan ctors)
        self.lock_globals: Dict[str, bool] = {}
        #: module globals assigned ``threading.local()`` — per-thread
        #: state, exempt from the shared-write rules
        self.tls_globals: Set[str] = set()

    def resolve_scoped(
        self, name: str, scope_chain: List[ast.AST]
    ) -> Optional[FuncInfo]:
        """Look ``name`` up through the lexical scope chain (innermost
        first), falling back to the module table."""
        for scope in reversed(scope_chain):
            info = self.scopes.get(id(scope), {}).get(name)
            if info is not None:
                return info
        return self.functions.get(name)


class CallGraph:
    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}  # path -> module
        self.by_modname: Dict[str, ModuleInfo] = {}
        self.reachable: Set[int] = set()  # id(FuncInfo.node)
        self.func_of_node: Dict[int, FuncInfo] = {}
        #: names bound to jitted callables (decorated functions and
        #: ``g = jax.jit(f)`` assignments): (module path, name) ->
        #: has_statics — the recompile scalar-arg rule's lookup table
        self.jitted_names: Dict[Tuple[str, str], bool] = {}
        #: id(FuncInfo.node) reachable from PullEngine worker callables
        self.worker_reachable: Set[int] = set()
        self.worker_roots: List[FuncInfo] = []
        self._types_cache: Dict[int, Dict[str, ClassInfo]] = {}

    def func_for(self, node: ast.AST) -> Optional[FuncInfo]:
        return self.func_of_node.get(id(node))

    def in_reachable(self, node: ast.AST) -> bool:
        return id(node) in self.reachable

    def in_worker(self, node: ast.AST) -> bool:
        return id(node) in self.worker_reachable

    def worker_funcs(self):
        """Worker-slice FuncInfos in a stable (path, lineno) order."""
        out = [
            self.func_of_node[i]
            for i in self.worker_reachable
            if i in self.func_of_node
        ]
        out.sort(key=lambda f: (f.path, getattr(f.node, "lineno", 0)))
        return out


def module_name_for(path: str) -> str:
    """Dotted module name by walking up through __init__.py packages;
    a bare file (fixtures) is just its stem."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    parts.reverse()
    if parts[-1] == "__init__":
        parts.pop()
    return ".".join(parts)


def _resolve_relative(modname: str, level: int, target: str) -> str:
    """Resolve ``from ..a import b`` inside module ``modname``."""
    base = modname.split(".")
    base = base[: max(0, len(base) - level)]
    if target:
        base = base + target.split(".")
    return ".".join(base)


def _index_module(path: str, tree: ast.Module) -> ModuleInfo:
    mod = ModuleInfo(path, module_name_for(path), tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.import_alias[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            src = node.module or ""
            if node.level:
                src = _resolve_relative(mod.modname, node.level, src)
            for a in node.names:
                if a.name == "*":
                    continue
                mod.from_names[a.asname or a.name] = (src, a.name)

    def visit(node, scope_node, prefix, owner_cls=None):
        # one walker: a new lexical scope opens ONLY at a function def;
        # classes qualify names but defs inside if/try/loop bodies (and
        # class bodies) register into the enclosing scope_node's table
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                info = FuncInfo(
                    mod, child, f"{mod.modname}.{q}", scope_node
                )
                if owner_cls is not None:
                    info.owner_class = owner_cls
                    owner_cls.methods.setdefault(child.name, info)
                mod.scopes.setdefault(id(scope_node), {}).setdefault(
                    child.name, info
                )
                mod.functions.setdefault(child.name, info)
                mod.all_functions.append(info)
                visit(child, child, q + ".")
            elif isinstance(child, ast.ClassDef):
                # methods are not bare-name callable: park them in the
                # class node's (unreachable) scope table
                cls = ClassInfo(
                    mod, child, f"{mod.modname}.{prefix}{child.name}"
                )
                mod.classes.setdefault(child.name, cls)
                visit(child, child, f"{prefix}{child.name}.", owner_cls=cls)
            else:
                visit(child, scope_node, prefix, owner_cls)

    visit(tree, tree, "")
    _index_globals(mod)
    return mod


def terminal_name(expr: ast.AST) -> Optional[str]:
    """The callee-ish terminal identifier of an expression — the attr
    of an Attribute, the id of a Name, else None. The ONE extraction
    every analyzer applies to call targets (do not re-spell it)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def walk_scope(root: ast.AST):
    """``ast.walk`` bounded to one lexical scope: yields ``root`` and
    its descendants but does NOT descend into nested function/lambda/
    class definitions. Per-function analyses (local bindings, lock
    facts, type seeding) must use this — a nested def's locals,
    ``global`` declarations, and calls belong to the NESTED scope, and
    attributing them to the enclosing function produces both false
    negatives (a nested local shadowing a module global) and false
    positives (a nested def's lock acquisition charged to the parent)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef),
            ):
                continue
            stack.append(child)


_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": False}
_TSAN_LOCK_CTORS = {"lock": False, "rlock": True, "condition": False}


def _lock_ctor(value: ast.AST) -> Optional[bool]:
    """Is ``value`` a lock construction? Returns reentrancy (True for
    RLock/tsan.rlock), or None when it is not a lock constructor.
    Recognized: ``threading.Lock/RLock/Condition()`` (any receiver
    spelling, bare from-imports too) and the graftcheck runtime's
    ``tsan.lock/rlock/condition("site")`` wrappers."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    attr = terminal_name(f)
    if attr in _LOCK_CTORS:
        return _LOCK_CTORS[attr]
    if attr in _TSAN_LOCK_CTORS and isinstance(f, ast.Attribute):
        recv = f.value
        if isinstance(recv, ast.Name) and "tsan" in recv.id:
            return _TSAN_LOCK_CTORS[attr]
    return None


def _is_tls_ctor(value: ast.AST) -> bool:
    """``threading.local()`` (or bare ``local()`` from-import)."""
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    attr = terminal_name(f)
    return attr == "local"


def _index_globals(mod: ModuleInfo) -> None:
    """Module-global binding facts: top-level names, string constants,
    lock globals, and names any function rebinds via ``global``."""
    for stmt in mod.tree.body:
        targets = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ):
            targets = [stmt.target]
            value = stmt.value
        elif isinstance(stmt, ast.AugAssign) and isinstance(
            stmt.target, ast.Name
        ):
            targets = [stmt.target]
        for t in targets:
            mod.module_globals.add(t.id)
            reentrant = _lock_ctor(value) if value is not None else None
            if reentrant is not None:
                mod.lock_globals[t.id] = reentrant
            elif value is not None and _is_tls_ctor(value):
                mod.tls_globals.add(t.id)
            elif isinstance(value, ast.Constant) and isinstance(
                value.value, str
            ):
                mod.constants[t.id] = value.value
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Global):
            mod.module_globals.update(node.names)


def resolve_class(
    cg: CallGraph, mod: ModuleInfo, expr: ast.AST
) -> Optional[ClassInfo]:
    """Resolve a class-valued expression (the func of a construction
    call, or a bare annotation name) to a ClassInfo in the linted set."""
    if isinstance(expr, ast.Name):
        cls = mod.classes.get(expr.id)
        if cls is not None:
            return cls
        tgt = mod.from_names.get(expr.id)
        if tgt is not None:
            m2 = cg.by_modname.get(tgt[0])
            if m2 is not None:
                return m2.classes.get(tgt[1])
        return None
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        modname = mod.import_alias.get(expr.value.id)
        if modname is None and expr.value.id in mod.from_names:
            src, orig = mod.from_names[expr.value.id]
            modname = f"{src}.{orig}"
        if modname is not None:
            m2 = cg.by_modname.get(modname)
            if m2 is not None:
                return m2.classes.get(expr.attr)
    return None


def resolve_annotation(
    cg: CallGraph, mod: ModuleInfo, ann: Optional[ast.AST]
) -> Optional[ClassInfo]:
    """Type annotation -> ClassInfo: plain names, dotted names, string
    annotations, and ``Optional[X]`` wrappers."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        text = ann.value.strip()
        if text.startswith("Optional[") and text.endswith("]"):
            text = text[len("Optional[") : -1]
        text = text.strip("\"' ")
        try:
            ann = ast.parse(text, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        head = ann.value
        headname = terminal_name(head)
        if headname == "Optional":
            return resolve_annotation(cg, mod, ann.slice)
        return None
    return resolve_class(cg, mod, ann)


def _index_class_attrs(cg: CallGraph) -> None:
    """Second pass (needs every module indexed for cross-module class
    resolution): fill each class's attr_types / lock_attrs / tls_attrs
    from ``self.x = ...`` assignments in its methods."""
    for mod in cg.modules.values():
        for cls in mod.classes.values():
            for meth in cls.methods.values():
                params = {}
                args = getattr(meth.node, "args", None)
                if args is not None:
                    for a in list(args.args) + list(args.kwonlyargs):
                        if a.annotation is not None:
                            params[a.arg] = a.annotation
                for node in ast.walk(meth.node):
                    tgt = None
                    value = None
                    if isinstance(node, ast.Assign) and len(node.targets) == 1:
                        tgt, value = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign):
                        tgt, value = node.target, node.value
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    attr = tgt.attr
                    if value is None:
                        if isinstance(node, ast.AnnAssign):
                            t = resolve_annotation(cg, mod, node.annotation)
                            if t is not None:
                                cls.attr_types.setdefault(attr, t)
                        continue
                    reentrant = _lock_ctor(value)
                    if reentrant is not None:
                        cls.lock_attrs.add(attr)
                        if reentrant:
                            cls.rlock_attrs.add(attr)
                        continue
                    if _is_tls_ctor(value):
                        cls.tls_attrs.add(attr)
                        continue
                    if isinstance(value, ast.Call):
                        t = resolve_class(cg, mod, value.func)
                        if t is not None:
                            cls.attr_types.setdefault(attr, t)
                    elif isinstance(value, ast.Name) and value.id in params:
                        t = resolve_annotation(cg, mod, params[value.id])
                        if t is not None:
                            cls.attr_types.setdefault(attr, t)


def _index_instance_globals(cg: CallGraph) -> None:
    """Module-global instance types: ``name = ClassName(...)`` anywhere
    the name is module-global (top level, or rebound via ``global`` the
    way the lazy singletons — ``_registry``, ``_engine``, ``_state`` —
    are), plus module-level annotated globals."""
    for mod in cg.modules.values():
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                t = resolve_annotation(cg, mod, stmt.annotation)
                if t is not None:
                    mod.global_types.setdefault(stmt.target.id, t)
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                continue
            name = node.targets[0].id
            if name not in mod.module_globals:
                continue
            t = resolve_class(cg, mod, node.value.func)
            if t is not None:
                mod.instance_types.setdefault(name, t)


def local_types(cg: CallGraph, info: FuncInfo) -> Dict[str, ClassInfo]:
    """Best-effort name -> ClassInfo typing inside one function:
    annotated params, ``self``/``cls``, and simple local assignments
    (two passes so ``st = _state; m = st.metrics`` chains resolve).
    Cached per function node."""
    cached = cg._types_cache.get(id(info.node))
    if cached is not None:
        return cached
    types: Dict[str, ClassInfo] = {}
    cg._types_cache[id(info.node)] = types  # pre-publish (cycles)
    args = getattr(info.node, "args", None)
    if args is not None:
        for a in list(args.args) + list(args.kwonlyargs):
            t = resolve_annotation(cg, info.module, a.annotation)
            if t is not None:
                types[a.arg] = t
    if info.owner_class is not None:
        types.setdefault("self", info.owner_class)
        types.setdefault("cls", info.owner_class)
    # Closure variables: a nested def reads the enclosing frame's
    # locals, so inherit the outer frame's inferred types for names
    # this frame neither takes as a parameter nor binds itself.
    scope = info.scope_node
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        outer = next(
            (f for f in info.module.all_functions if f.node is scope), None
        )
        if outer is not None:
            bound: Set[str] = set(types)
            if args is not None:
                for a in (
                    list(getattr(args, "posonlyargs", []))
                    + list(args.args)
                    + list(args.kwonlyargs)
                ):
                    bound.add(a.arg)
                for va in (args.vararg, args.kwarg):
                    if va is not None:
                        bound.add(va.arg)
            for node in walk_scope(info.node):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            bound.add(tgt.id)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    if isinstance(node.target, ast.Name):
                        bound.add(node.target.id)
            for name, t in local_types(cg, outer).items():
                if name not in bound:
                    types.setdefault(name, t)
    for _ in range(2):
        for node in walk_scope(info.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                t = expr_type(cg, info, node.value, types)
                if t is not None:
                    types[node.targets[0].id] = t
    return types


def expr_type(
    cg: CallGraph,
    info: FuncInfo,
    expr: ast.AST,
    types: Optional[Dict[str, ClassInfo]] = None,
) -> Optional[ClassInfo]:
    """Type of an expression, where the lightweight inference can tell:
    typed locals, module singletons (own and via module alias / from-
    import), class attr chains, constructor calls, and calls to
    functions with resolvable return annotations."""
    mod = info.module
    if types is None:
        types = local_types(cg, info)
    if isinstance(expr, ast.Name):
        t = types.get(expr.id)
        if t is not None:
            return t
        t = mod.instance_types.get(expr.id) or mod.global_types.get(expr.id)
        if t is not None:
            return t
        tgt = mod.from_names.get(expr.id)
        if tgt is not None:
            m2 = cg.by_modname.get(tgt[0])
            if m2 is not None:
                return m2.instance_types.get(tgt[1]) or m2.global_types.get(
                    tgt[1]
                )
        return None
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name):
            modname = mod.import_alias.get(base.id)
            if modname is None and base.id in mod.from_names:
                src, orig = mod.from_names[base.id]
                modname = f"{src}.{orig}"
            if modname is not None:
                m2 = cg.by_modname.get(modname)
                if m2 is not None:
                    t = m2.instance_types.get(expr.attr) or m2.global_types.get(
                        expr.attr
                    )
                    if t is not None:
                        return t
        bt = expr_type(cg, info, base, types)
        if bt is not None:
            return bt.attr_types.get(expr.attr)
        return None
    if isinstance(expr, ast.Call):
        cls = resolve_class(cg, mod, expr.func)
        if cls is not None:
            return cls
        callee = resolve_callable(cg, info, expr.func, types)
        if callee is not None:
            ret = getattr(callee.node, "returns", None)
            return resolve_annotation(cg, callee.module, ret)
    return None


def resolve_callable(
    cg: CallGraph,
    info: FuncInfo,
    expr: ast.AST,
    types: Optional[Dict[str, ClassInfo]] = None,
) -> Optional[FuncInfo]:
    """Resolve a callable EXPRESSION inside ``info`` — superset of
    :func:`resolve_call`'s func handling, adding method resolution
    (``self.m`` / typed-object ``x.m`` / module-singleton
    ``faults.counters.add``) and ``functools.partial`` unwrapping."""
    mod = info.module
    if isinstance(expr, ast.Name):
        target = mod.resolve_scoped(expr.id, _scope_chain_of(info))
        if target is not None:
            return target
        tgt = mod.from_names.get(expr.id)
        if tgt is not None:
            m2 = cg.by_modname.get(tgt[0])
            if m2 is not None:
                return m2.functions.get(tgt[1])
        return None
    if isinstance(expr, ast.Attribute):
        recv = expr.value
        # plain module-alias function call (the resolve_call case)
        if isinstance(recv, ast.Name):
            alias = recv.id
            modname = mod.import_alias.get(alias)
            if modname is None and alias in mod.from_names:
                src, orig = mod.from_names[alias]
                modname = f"{src}.{orig}"
            if modname is not None:
                m2 = cg.by_modname.get(modname)
                if m2 is not None:
                    fn = m2.functions.get(expr.attr)
                    if fn is not None:
                        return fn
        # method on a typed receiver (self, typed local, singleton,
        # attr chain)
        bt = expr_type(cg, info, recv, types)
        if bt is not None:
            return bt.methods.get(expr.attr)
    return None


def callable_argument(
    cg: CallGraph,
    info: FuncInfo,
    expr: ast.AST,
    types: Optional[Dict[str, ClassInfo]] = None,
) -> Optional[FuncInfo]:
    """A callable passed AS AN ARGUMENT (worker submit / Thread target /
    higher-order call): resolves Names/attributes to functions, unwraps
    ``functools.partial(f, ...)``, and synthesizes a FuncInfo for a
    Lambda so its body joins the walk."""
    if isinstance(expr, ast.Lambda):
        existing = cg.func_for(expr)
        if existing is not None:
            return existing
        fi = FuncInfo(
            info.module,
            expr,
            f"{info.qualname}.<lambda>",
            info.node,
        )
        # a lambda in a method closes over the method's self: carry the
        # owner class so `self.<attr>` chains type-resolve in its body
        fi.owner_class = info.owner_class
        cg.func_of_node[id(expr)] = fi
        info.module.all_functions.append(fi)
        return fi
    if isinstance(expr, ast.Call):
        f = expr.func
        attr = terminal_name(f)
        if attr == "partial" and expr.args:
            return callable_argument(cg, info, expr.args[0], types)
        return None
    if isinstance(expr, (ast.Name, ast.Attribute)):
        return resolve_callable(cg, info, expr, types)
    return None


def _static_params(fn_node, call: Optional[ast.Call]) -> Set[str]:
    """Parameter names marked static on the jit wrapping, resolved
    against the function's positional signature for static_argnums."""
    if call is None:
        return set()
    out: Set[str] = set()
    args = [a.arg for a in fn_node.args.args] if hasattr(fn_node, "args") else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    if 0 <= el.value < len(args):
                        out.add(args[el.value])
    return out


def _unwrap_jit_target(call: ast.Call) -> Optional[ast.AST]:
    """The expression jax.jit ultimately compiles: unwraps transparent
    wrappers (shard_map/vmap/pmap/partial) down to a Name or Lambda."""
    if not call.args:
        return None
    target = call.args[0]
    depth = 0
    while isinstance(target, ast.Call) and depth < 6:
        f = target.func
        attr = terminal_name(f)
        if attr in _WRAPPER_ATTRS or attr == "partial":
            if not target.args:
                return None
            target = target.args[0]
            depth += 1
            continue
        break
    return target


class _JitSiteVisitor(ast.NodeVisitor):
    """Scope-tracking pass that marks call-site jit wrappings."""

    def __init__(self, cg: CallGraph, mod: ModuleInfo):
        self.cg = cg
        self.mod = mod
        self.scope_chain: List[ast.AST] = [mod.tree]

    def _enter(self, node):
        self.scope_chain.append(node)
        self.generic_visit(node)
        self.scope_chain.pop()

    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter

    def visit_Call(self, node: ast.Call):
        if _is_jax_jit(node.func):
            has_statics = _jit_statics(node)
            target = _unwrap_jit_target(node)
            if isinstance(target, ast.Lambda):
                info = FuncInfo(
                    self.mod,
                    target,
                    f"{self.mod.modname}.<lambda>",
                    self.scope_chain[-1],
                )
                info.is_jit_root = True
                info.jit_has_statics = has_statics
                info.jit_site = (self.mod.path, node.lineno)
                self.cg.func_of_node[id(target)] = info
                self.mod.all_functions.append(info)
            elif isinstance(target, ast.Name):
                info = self.mod.resolve_scoped(target.id, self.scope_chain)
                if info is not None:
                    info.is_jit_root = True
                    info.jit_has_statics = (
                        info.jit_has_statics or has_statics
                    )
                    info.static_params |= _static_params(info.node, node)
                    info.jit_site = info.jit_site or (
                        self.mod.path,
                        node.lineno,
                    )
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        if isinstance(node.value, ast.Call) and _is_jax_jit(
            node.value.func
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.cg.jitted_names[(self.mod.path, t.id)] = (
                        _jit_statics(node.value)
                    )
        self.generic_visit(node)


def _mark_jit_roots(cg: CallGraph) -> None:
    for mod in cg.modules.values():
        # decorated roots
        for info in mod.all_functions:
            node = info.node
            for dec in getattr(node, "decorator_list", []):
                if _is_jax_jit(dec):
                    info.is_jit_root = True
                    info.jit_site = (mod.path, dec.lineno)
                elif (
                    isinstance(dec, ast.Call)
                    and _is_partial(dec.func)
                    and dec.args
                    and _is_jax_jit(dec.args[0])
                ):
                    info.is_jit_root = True
                    info.jit_has_statics = _jit_statics(dec)
                    info.static_params = _static_params(node, dec)
                    info.jit_site = (mod.path, dec.lineno)
                elif isinstance(dec, ast.Call) and _is_jax_jit(dec.func):
                    info.is_jit_root = True
                    info.jit_has_statics = _jit_statics(dec)
                    info.static_params = _static_params(node, dec)
                    info.jit_site = (mod.path, dec.lineno)
            if info.is_jit_root:
                cg.jitted_names[(mod.path, info.name)] = info.jit_has_statics
        _JitSiteVisitor(cg, mod).visit(mod.tree)


def _scope_chain_of(info: FuncInfo) -> List[ast.AST]:
    """Rebuild the lexical chain module -> ... -> info.node by walking
    scope_node links."""
    chain: List[ast.AST] = [info.node]
    node = info.scope_node
    mod = info.module
    guard = 0
    while node is not None and guard < 32:
        chain.append(node)
        if node is mod.tree:
            break
        owner = mod.tree
        found = None
        for f in mod.all_functions:
            if f.node is node:
                found = f.scope_node
                break
        node = found if found is not None else owner
        guard += 1
    chain.reverse()
    return chain


def resolve_call(
    cg: CallGraph, info: FuncInfo, call: ast.Call
) -> Optional[FuncInfo]:
    """Resolve a call expression inside ``info`` to a FuncInfo in the
    linted set, via the lexical scope chain, from-imports, and module
    aliases. Unresolvable calls (jnp.*, builtins) return None and stop
    the walk there."""
    mod = info.module
    f = call.func
    if isinstance(f, ast.Name):
        target = mod.resolve_scoped(f.id, _scope_chain_of(info))
        if target is not None:
            return target
        tgt = mod.from_names.get(f.id)
        if tgt is not None:
            m2 = cg.by_modname.get(tgt[0])
            if m2 is not None:
                return m2.functions.get(tgt[1])
        return None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        alias = f.value.id
        modname = mod.import_alias.get(alias)
        if modname is None and alias in mod.from_names:
            src, orig = mod.from_names[alias]
            modname = f"{src}.{orig}"
        if modname is not None:
            m2 = cg.by_modname.get(modname)
            if m2 is not None:
                return m2.functions.get(f.attr)
    return None


def _walk_reachable(cg: CallGraph) -> None:
    stack = [
        info
        for mod in cg.modules.values()
        for info in mod.all_functions
        if info.is_jit_root
    ]
    while stack:
        info = stack.pop()
        if id(info.node) in cg.reachable:
            continue
        cg.reachable.add(id(info.node))
        cg.func_of_node.setdefault(id(info.node), info)
        body = getattr(info.node, "body", None)
        nodes = body if isinstance(body, list) else [info.node.body]
        for stmt in nodes:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    callee = resolve_call(cg, info, node)
                    if callee is not None and id(callee.node) not in cg.reachable:
                        stack.append(callee)


_ENGINE_CTORS = ("get_engine", "PullEngine")


class DispatchSiteVisitor(ast.NodeVisitor):
    """Scope-tracking base for call sites that hand callables to
    ANOTHER execution context (worker submits, Thread targets,
    shard_map/pjit wrappings): subclasses implement
    :meth:`candidate_exprs` returning the callable expressions of a
    matched call; resolution (incl. the synthetic module-level context)
    is shared here so a fix to context handling lands in every
    root-finder at once."""

    def __init__(self, cg: CallGraph, mod: ModuleInfo):
        self.cg = cg
        self.mod = mod
        self.scope_chain: List[ast.AST] = [mod.tree]
        self.roots: List[FuncInfo] = []

    def _enter(self, node):
        self.scope_chain.append(node)
        self.generic_visit(node)
        self.scope_chain.pop()

    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter

    def candidate_exprs(self, node: ast.Call) -> list:
        raise NotImplementedError

    def context_info(self) -> Optional[FuncInfo]:
        for scope in reversed(self.scope_chain):
            fi = self.cg.func_for(scope)
            if fi is not None:
                return fi
        return None

    def _add(self, expr: ast.AST) -> None:
        ctx = self.context_info()
        if ctx is None:
            # module-level dispatch site: synthesize a module context
            ctx = FuncInfo(
                self.mod, self.mod.tree, f"{self.mod.modname}.<module>",
                self.mod.tree,
            )
        fi = callable_argument(self.cg, ctx, expr)
        if fi is not None:
            self.roots.append(fi)

    def visit_Call(self, node: ast.Call):
        for expr in self.candidate_exprs(node):
            self._add(expr)
        self.generic_visit(node)


class _WorkerRootVisitor(DispatchSiteVisitor):
    """Worker-dispatch sites: ``.submit`` calls on pull-engine
    receivers and ``threading.Thread(target=...)`` constructions."""

    def __init__(self, cg: CallGraph, mod: ModuleInfo, engine_names):
        super().__init__(cg, mod)
        self.engine_names = engine_names

    def candidate_exprs(self, node: ast.Call) -> list:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "submit":
            recv = f.value
            recv_name = recv.id if isinstance(recv, ast.Name) else None
            recv_type = None
            ctx = self.context_info()
            if ctx is not None:
                recv_type = expr_type(self.cg, ctx, recv)
            if (recv_name in self.engine_names) or (
                recv_type is not None and recv_type.name == "PullEngine"
            ):
                return list(node.args[:1]) + [
                    kw.value
                    for kw in node.keywords
                    if kw.arg in ("work", "on_start")
                ]
            return []
        if terminal_name(f) == "Thread":
            return [
                kw.value for kw in node.keywords if kw.arg == "target"
            ]
        return []


def _find_worker_roots(cg: CallGraph) -> List[FuncInfo]:
    roots: List[FuncInfo] = []
    for mod in cg.modules.values():
        engine_names = set()
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
            ):
                continue
            f = node.value.func
            attr = terminal_name(f)
            if attr in _ENGINE_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        engine_names.add(t.id)
        v = _WorkerRootVisitor(cg, mod, engine_names)
        v.visit(mod.tree)
        roots.extend(v.roots)
    return roots


def reach_closure(
    cg: CallGraph, roots, include_nested_defs: bool = False
) -> Dict[int, FuncInfo]:
    """Transitive closure over resolvable calls WITH callable-argument
    propagation (a lambda handed to ``faults.supervised`` runs even
    though supervised's ``attempt_fn(budget)`` call is unresolvable) —
    the ONE traversal shared by the worker slice and the collective
    regions, so a propagation fix lands in both. With
    ``include_nested_defs``, lexically nested defs of a reached
    function join too (trace-time helpers in shard_map bodies)."""
    out: Dict[int, FuncInfo] = {}
    stack = list(roots)
    while stack:
        info = stack.pop()
        if id(info.node) in out:
            continue
        out[id(info.node)] = info
        cg.func_of_node.setdefault(id(info.node), info)
        if include_nested_defs:
            for mod_info in info.module.all_functions:
                if mod_info.scope_node is info.node and id(
                    mod_info.node
                ) not in out:
                    stack.append(mod_info)
        types = local_types(cg, info)
        body = getattr(info.node, "body", None)
        nodes = body if isinstance(body, list) else [info.node.body]
        for stmt in nodes:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                callee = resolve_callable(cg, info, node.func, types)
                if callee is not None and id(callee.node) not in out:
                    stack.append(callee)
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    fi = callable_argument(cg, info, arg, types)
                    if fi is not None and id(fi.node) not in out:
                        stack.append(fi)
    return out


def walk_worker(cg: CallGraph) -> None:
    """Mark ``cg.worker_reachable``: everything callable from the
    PullEngine worker roots (see :func:`reach_closure`)."""
    cg.worker_roots = _find_worker_roots(cg)
    cg.worker_reachable = set(reach_closure(cg, cg.worker_roots))


def build(pkg) -> CallGraph:
    """Build the call graph for a parsed :class:`core.Package`."""
    cg = CallGraph()
    for src in pkg.files:
        if src.tree is None:
            continue
        mod = _index_module(src.path, src.tree)
        cg.modules[src.path] = mod
        cg.by_modname[mod.modname] = mod
        for info in mod.all_functions:
            cg.func_of_node[id(info.node)] = info
    _index_class_attrs(cg)
    _index_instance_globals(cg)
    _mark_jit_roots(cg)
    _walk_reachable(cg)
    walk_worker(cg)
    return cg


def tracked_call_sites(
    package_dir: Optional[str] = None,
) -> Dict[str, List[Tuple[str, int]]]:
    """Static map of ``tracked_call("<family>", ...)`` literals to their
    (file, line) call sites, for the recompile-storm warning. Best
    effort: unreadable/unparseable files are skipped."""
    if package_dir is None:
        import dbscan_tpu

        package_dir = os.path.dirname(os.path.abspath(dbscan_tpu.__file__))
    out: Dict[str, List[Tuple[str, int]]] = {}
    for root, dirs, names in os.walk(package_dir):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for n in sorted(names):
            if not n.endswith(".py"):
                continue
            path = os.path.join(root, n)
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except (OSError, SyntaxError, ValueError):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                attr = terminal_name(fn)
                if attr not in ("tracked_call", "note_compile"):
                    continue
                if node.args and isinstance(node.args[0], ast.Constant) and (
                    isinstance(node.args[0].value, str)
                ):
                    out.setdefault(node.args[0].value, []).append(
                        (os.path.relpath(path, package_dir), node.lineno)
                    )
    return out
