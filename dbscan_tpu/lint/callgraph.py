"""Package call graph: jit roots, trace-time reachability, and jit
call-site metadata.

What counts as a jit root (a function whose body runs under tracing):

- ``@jax.jit`` / ``@jit`` decorated functions;
- ``@functools.partial(jax.jit, static_argnums=/static_argnames=...)``
  (and the bare ``partial`` spelling);
- functions WRAPPED at a call site — ``jax.jit(block)``,
  ``jax.jit(jax.shard_map(block, ...))`` (the shard_map/vmap/pmap
  wrapper is transparent), ``jax.jit(lambda ...)``. Name lookup is
  scope-aware: the repo's builder idiom defines a local ``block``/``fn``
  per builder, so ``jax.jit(fn)`` resolves through the lexical scope
  chain, not a flat module table;

plus everything transitively called from a root through names the
import maps and scope chains can resolve WITHIN the linted file set
(jnp./lax. calls resolve nowhere and stop the walk, by design). The
reachable set is what the host-sync rules scan: a ``.item()`` there
either breaks under trace or silently syncs the host when the helper
is also used outside jit — both reportable.

Also exported for runtime use: :func:`tracked_call_sites` maps every
``obs_compile.tracked_call("<family>", ...)`` literal to its file:line,
which `obs/compile.py` folds into the recompile-storm warning so the
log names the dispatch site, not just the family.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

_JIT_NAMES = ("jit",)  # attribute or bare name
_WRAPPER_ATTRS = ("shard_map", "pmap", "vmap", "checkpoint", "remat")


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` as an expression."""
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    return False


def _jit_statics(call: ast.Call) -> bool:
    """Whether a ``jax.jit(...)`` / ``partial(jax.jit, ...)`` call names
    static_argnums/static_argnames."""
    return any(
        kw.arg in ("static_argnums", "static_argnames")
        for kw in call.keywords
        if kw.arg
    )


def _is_partial(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr == "partial"
    if isinstance(node, ast.Name):
        return node.id == "partial"
    return False


class FuncInfo:
    """One function definition in the linted set."""

    def __init__(
        self,
        module: "ModuleInfo",
        node: ast.AST,
        qualname: str,
        scope_node: ast.AST,
    ):
        self.module = module
        self.node = node  # FunctionDef | AsyncFunctionDef | Lambda
        self.qualname = qualname
        self.scope_node = scope_node  # enclosing module/function node
        self.is_jit_root = False
        self.jit_has_statics = False
        self.static_params: Set[str] = set()
        self.jit_site: Optional[Tuple[str, int]] = None

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")

    @property
    def path(self) -> str:
        return self.module.path


class ModuleInfo:
    """Per-module function index, lexical scope tables, import maps."""

    def __init__(self, path: str, modname: str, tree: ast.Module):
        self.path = path
        self.modname = modname
        self.tree = tree
        #: module-level simple-name table (outermost def wins)
        self.functions: Dict[str, FuncInfo] = {}
        #: id(scope node) -> {simple name -> FuncInfo} for every scope
        self.scopes: Dict[int, Dict[str, FuncInfo]] = {id(tree): {}}
        self.all_functions: List[FuncInfo] = []
        self.import_alias: Dict[str, str] = {}  # alias -> module dotted
        self.from_names: Dict[str, Tuple[str, str]] = {}  # name -> (mod, orig)

    def resolve_scoped(
        self, name: str, scope_chain: List[ast.AST]
    ) -> Optional[FuncInfo]:
        """Look ``name`` up through the lexical scope chain (innermost
        first), falling back to the module table."""
        for scope in reversed(scope_chain):
            info = self.scopes.get(id(scope), {}).get(name)
            if info is not None:
                return info
        return self.functions.get(name)


class CallGraph:
    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}  # path -> module
        self.by_modname: Dict[str, ModuleInfo] = {}
        self.reachable: Set[int] = set()  # id(FuncInfo.node)
        self.func_of_node: Dict[int, FuncInfo] = {}
        #: names bound to jitted callables (decorated functions and
        #: ``g = jax.jit(f)`` assignments): (module path, name) ->
        #: has_statics — the recompile scalar-arg rule's lookup table
        self.jitted_names: Dict[Tuple[str, str], bool] = {}

    def func_for(self, node: ast.AST) -> Optional[FuncInfo]:
        return self.func_of_node.get(id(node))

    def in_reachable(self, node: ast.AST) -> bool:
        return id(node) in self.reachable


def module_name_for(path: str) -> str:
    """Dotted module name by walking up through __init__.py packages;
    a bare file (fixtures) is just its stem."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    parts.reverse()
    if parts[-1] == "__init__":
        parts.pop()
    return ".".join(parts)


def _resolve_relative(modname: str, level: int, target: str) -> str:
    """Resolve ``from ..a import b`` inside module ``modname``."""
    base = modname.split(".")
    base = base[: max(0, len(base) - level)]
    if target:
        base = base + target.split(".")
    return ".".join(base)


def _index_module(path: str, tree: ast.Module) -> ModuleInfo:
    mod = ModuleInfo(path, module_name_for(path), tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.import_alias[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            src = node.module or ""
            if node.level:
                src = _resolve_relative(mod.modname, node.level, src)
            for a in node.names:
                if a.name == "*":
                    continue
                mod.from_names[a.asname or a.name] = (src, a.name)

    def visit(node, scope_node, prefix):
        # one walker: a new lexical scope opens ONLY at a function def;
        # classes qualify names but defs inside if/try/loop bodies (and
        # class bodies) register into the enclosing scope_node's table
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                info = FuncInfo(
                    mod, child, f"{mod.modname}.{q}", scope_node
                )
                mod.scopes.setdefault(id(scope_node), {}).setdefault(
                    child.name, info
                )
                mod.functions.setdefault(child.name, info)
                mod.all_functions.append(info)
                visit(child, child, q + ".")
            elif isinstance(child, ast.ClassDef):
                # methods are not bare-name callable: park them in the
                # class node's (unreachable) scope table
                visit(child, child, f"{prefix}{child.name}.")
            else:
                visit(child, scope_node, prefix)

    visit(tree, tree, "")
    return mod


def _static_params(fn_node, call: Optional[ast.Call]) -> Set[str]:
    """Parameter names marked static on the jit wrapping, resolved
    against the function's positional signature for static_argnums."""
    if call is None:
        return set()
    out: Set[str] = set()
    args = [a.arg for a in fn_node.args.args] if hasattr(fn_node, "args") else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    if 0 <= el.value < len(args):
                        out.add(args[el.value])
    return out


def _unwrap_jit_target(call: ast.Call) -> Optional[ast.AST]:
    """The expression jax.jit ultimately compiles: unwraps transparent
    wrappers (shard_map/vmap/pmap/partial) down to a Name or Lambda."""
    if not call.args:
        return None
    target = call.args[0]
    depth = 0
    while isinstance(target, ast.Call) and depth < 6:
        f = target.func
        attr = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if attr in _WRAPPER_ATTRS or attr == "partial":
            if not target.args:
                return None
            target = target.args[0]
            depth += 1
            continue
        break
    return target


class _JitSiteVisitor(ast.NodeVisitor):
    """Scope-tracking pass that marks call-site jit wrappings."""

    def __init__(self, cg: CallGraph, mod: ModuleInfo):
        self.cg = cg
        self.mod = mod
        self.scope_chain: List[ast.AST] = [mod.tree]

    def _enter(self, node):
        self.scope_chain.append(node)
        self.generic_visit(node)
        self.scope_chain.pop()

    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter

    def visit_Call(self, node: ast.Call):
        if _is_jax_jit(node.func):
            has_statics = _jit_statics(node)
            target = _unwrap_jit_target(node)
            if isinstance(target, ast.Lambda):
                info = FuncInfo(
                    self.mod,
                    target,
                    f"{self.mod.modname}.<lambda>",
                    self.scope_chain[-1],
                )
                info.is_jit_root = True
                info.jit_has_statics = has_statics
                info.jit_site = (self.mod.path, node.lineno)
                self.cg.func_of_node[id(target)] = info
                self.mod.all_functions.append(info)
            elif isinstance(target, ast.Name):
                info = self.mod.resolve_scoped(target.id, self.scope_chain)
                if info is not None:
                    info.is_jit_root = True
                    info.jit_has_statics = (
                        info.jit_has_statics or has_statics
                    )
                    info.static_params |= _static_params(info.node, node)
                    info.jit_site = info.jit_site or (
                        self.mod.path,
                        node.lineno,
                    )
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        if isinstance(node.value, ast.Call) and _is_jax_jit(
            node.value.func
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.cg.jitted_names[(self.mod.path, t.id)] = (
                        _jit_statics(node.value)
                    )
        self.generic_visit(node)


def _mark_jit_roots(cg: CallGraph) -> None:
    for mod in cg.modules.values():
        # decorated roots
        for info in mod.all_functions:
            node = info.node
            for dec in getattr(node, "decorator_list", []):
                if _is_jax_jit(dec):
                    info.is_jit_root = True
                    info.jit_site = (mod.path, dec.lineno)
                elif (
                    isinstance(dec, ast.Call)
                    and _is_partial(dec.func)
                    and dec.args
                    and _is_jax_jit(dec.args[0])
                ):
                    info.is_jit_root = True
                    info.jit_has_statics = _jit_statics(dec)
                    info.static_params = _static_params(node, dec)
                    info.jit_site = (mod.path, dec.lineno)
                elif isinstance(dec, ast.Call) and _is_jax_jit(dec.func):
                    info.is_jit_root = True
                    info.jit_has_statics = _jit_statics(dec)
                    info.static_params = _static_params(node, dec)
                    info.jit_site = (mod.path, dec.lineno)
            if info.is_jit_root:
                cg.jitted_names[(mod.path, info.name)] = info.jit_has_statics
        _JitSiteVisitor(cg, mod).visit(mod.tree)


def _scope_chain_of(info: FuncInfo) -> List[ast.AST]:
    """Rebuild the lexical chain module -> ... -> info.node by walking
    scope_node links."""
    chain: List[ast.AST] = [info.node]
    node = info.scope_node
    mod = info.module
    guard = 0
    while node is not None and guard < 32:
        chain.append(node)
        if node is mod.tree:
            break
        owner = mod.tree
        found = None
        for f in mod.all_functions:
            if f.node is node:
                found = f.scope_node
                break
        node = found if found is not None else owner
        guard += 1
    chain.reverse()
    return chain


def resolve_call(
    cg: CallGraph, info: FuncInfo, call: ast.Call
) -> Optional[FuncInfo]:
    """Resolve a call expression inside ``info`` to a FuncInfo in the
    linted set, via the lexical scope chain, from-imports, and module
    aliases. Unresolvable calls (jnp.*, builtins) return None and stop
    the walk there."""
    mod = info.module
    f = call.func
    if isinstance(f, ast.Name):
        target = mod.resolve_scoped(f.id, _scope_chain_of(info))
        if target is not None:
            return target
        tgt = mod.from_names.get(f.id)
        if tgt is not None:
            m2 = cg.by_modname.get(tgt[0])
            if m2 is not None:
                return m2.functions.get(tgt[1])
        return None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        alias = f.value.id
        modname = mod.import_alias.get(alias)
        if modname is None and alias in mod.from_names:
            src, orig = mod.from_names[alias]
            modname = f"{src}.{orig}"
        if modname is not None:
            m2 = cg.by_modname.get(modname)
            if m2 is not None:
                return m2.functions.get(f.attr)
    return None


def _walk_reachable(cg: CallGraph) -> None:
    stack = [
        info
        for mod in cg.modules.values()
        for info in mod.all_functions
        if info.is_jit_root
    ]
    while stack:
        info = stack.pop()
        if id(info.node) in cg.reachable:
            continue
        cg.reachable.add(id(info.node))
        cg.func_of_node.setdefault(id(info.node), info)
        body = getattr(info.node, "body", None)
        nodes = body if isinstance(body, list) else [info.node.body]
        for stmt in nodes:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    callee = resolve_call(cg, info, node)
                    if callee is not None and id(callee.node) not in cg.reachable:
                        stack.append(callee)


def build(pkg) -> CallGraph:
    """Build the call graph for a parsed :class:`core.Package`."""
    cg = CallGraph()
    for src in pkg.files:
        if src.tree is None:
            continue
        mod = _index_module(src.path, src.tree)
        cg.modules[src.path] = mod
        cg.by_modname[mod.modname] = mod
        for info in mod.all_functions:
            cg.func_of_node[id(info.node)] = info
    _mark_jit_roots(cg)
    _walk_reachable(cg)
    return cg


def tracked_call_sites(
    package_dir: Optional[str] = None,
) -> Dict[str, List[Tuple[str, int]]]:
    """Static map of ``tracked_call("<family>", ...)`` literals to their
    (file, line) call sites, for the recompile-storm warning. Best
    effort: unreadable/unparseable files are skipped."""
    if package_dir is None:
        import dbscan_tpu

        package_dir = os.path.dirname(os.path.abspath(dbscan_tpu.__file__))
    out: Dict[str, List[Tuple[str, int]]] = {}
    for root, dirs, names in os.walk(package_dir):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for n in sorted(names):
            if not n.endswith(".py"):
                continue
            path = os.path.join(root, n)
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except (OSError, SyntaxError, ValueError):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                attr = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None
                )
                if attr not in ("tracked_call", "note_compile"):
                    continue
                if node.args and isinstance(node.args[0], ast.Constant) and (
                    isinstance(node.args[0].value, str)
                ):
                    out.setdefault(node.args[0].value, []).append(
                        (os.path.relpath(path, package_dir), node.lineno)
                    )
    return out
