"""graftshape abstract-interpretation core: symbolic dims, dtypes, and
the byte algebra the shape/HBM rules and the runtime cross-check share.

The reference paper's whole partitioning discipline is a memory budget
(rectangles sized so no executor exceeds ``maxPointsPerPartition``);
our port enforces it dynamically — padding ratchets, RESOURCE_EXHAUSTED
budget halving — which means a shape or HBM contract violation is
discovered by a recompile storm or an OOM on hardware. This module is
the static half of closing that gap: a small symbolic domain

- **dims** (:class:`E`): linear-ish integer expressions ``k + sum(c *
  prod(syms))`` over named :class:`Sym` dimensions. Symbols carry a
  ``source`` tag (``"data"`` for values derived from array contents /
  lengths, ``"ratchet"`` for values that passed through a sanctioned
  padding function) — the tag the ``shape-unratcheted-dim`` rule reads.
- **values** (:class:`Arr` / :class:`IntVal` / :class:`Lit` /
  :class:`Tup` / :data:`UNKNOWN`): abstract results of expressions,
  with numpy-vs-jnp provenance (``Arr.device``) and explicit-float64
  provenance (``Arr.explicit_f64``) for the dtype-flow rule.
- **an interpreter** (:class:`Interp`): one abstract pass over a
  function body that models the jnp/np surface the kernels actually
  use (creation ops, broadcasting, concat/stack, dot, reshape,
  reductions, astype, ``.shape`` flow) and reports provable conflicts
  through a findings callback. Conservative by construction: a dim it
  cannot prove concrete unifies with anything, so every emitted
  finding is a real arithmetic impossibility, not a modeling guess.
- **unification + byte algebra**: :func:`unify_dim` binds model
  symbols against observed concrete dims (solving single-unknown
  monomials like ``512*NB`` against an observed ``1024``), and
  :func:`nbytes` / :meth:`E.evaluate` turn symbolic shapes into the
  footprint predictions ``lint/shapes.py`` gates statically and
  ``lint/shapecheck.py`` asserts at runtime.

Stdlib-only on purpose (ast + math): the linter and the runtime
cross-check import this without touching jax.
"""

from __future__ import annotations

import ast
import itertools
from typing import Callable, Dict, List, Optional, Tuple

#: set True by tests so interpreter bugs surface as test failures
#: instead of being swallowed by the per-function guard in shapes.py
STRICT = False

_sym_counter = itertools.count()


class Sym:
    """One symbolic dimension. ``source`` tags provenance: ``"data"``
    (derived from array contents or a data-dependent count — the dims
    the ratchet rule watches), ``"ratchet"`` (passed through a
    sanctioned padding function), or None (model/parameter symbols)."""

    __slots__ = ("name", "source")

    def __init__(self, name: str, source: Optional[str] = None):
        self.name = name
        self.source = source

    def __repr__(self):
        return self.name


def fresh(prefix: str = "d", source: Optional[str] = None) -> Sym:
    return Sym(f"{prefix}{next(_sym_counter)}", source)


class E:
    """Normalized integer expression: ``k + sum(coeff * prod(syms))``.
    ``terms`` is a tuple of ``(coeff, (Sym, ...))`` with the symbol
    tuple sorted by name; construction folds constants and merges like
    terms, so structural equality is semantic equality for this form."""

    __slots__ = ("k", "terms")

    def __init__(self, k: int = 0, terms: Tuple = ()):
        self.k = int(k)
        self.terms = terms

    # --- constructors -------------------------------------------------

    @staticmethod
    def of(x) -> "E":
        if isinstance(x, E):
            return x
        if isinstance(x, Sym):
            return E(0, ((1, (x,)),))
        if isinstance(x, (int, bool)):
            return E(int(x))
        raise TypeError(f"not a dim: {x!r}")

    @staticmethod
    def _norm(k: int, raw: List[Tuple[int, Tuple[Sym, ...]]]) -> "E":
        acc: Dict[Tuple[Sym, ...], int] = {}
        for c, syms in raw:
            if c == 0:
                continue
            key = tuple(sorted(syms, key=lambda s: (s.name, id(s))))
            acc[key] = acc.get(key, 0) + c
        terms = tuple(
            (c, syms)
            for syms, c in sorted(
                acc.items(), key=lambda kv: [s.name for s in kv[0]]
            )
            if c != 0
        )
        return E(k, terms)

    def __add__(self, other) -> "E":
        o = E.of(other)
        return E._norm(self.k + o.k, list(self.terms) + list(o.terms))

    def __mul__(self, other) -> "E":
        o = E.of(other)
        raw: List[Tuple[int, Tuple[Sym, ...]]] = []
        k = self.k * o.k
        for c, syms in self.terms:
            if o.k:
                raw.append((c * o.k, syms))
        for c, syms in o.terms:
            if self.k:
                raw.append((c * self.k, syms))
        for c1, s1 in self.terms:
            for c2, s2 in o.terms:
                raw.append((c1 * c2, s1 + s2))
        return E._norm(k, raw)

    def __sub__(self, other) -> "E":
        return self + (E.of(other) * E(-1))

    # --- queries ------------------------------------------------------

    def const(self) -> Optional[int]:
        """The concrete value when the expression has no symbols."""
        return self.k if not self.terms else None

    def syms(self) -> List[Sym]:
        out = []
        for _c, syms in self.terms:
            for s in syms:
                if s not in out:
                    out.append(s)
        return out

    def evaluate(self, env: Dict[str, int]) -> Optional[int]:
        """Concrete value under ``env`` (symbol name -> int); None when
        any symbol is unbound."""
        total = self.k
        for c, syms in self.terms:
            p = c
            for s in syms:
                v = env.get(s.name)
                if v is None:
                    return None
                p *= v
            total += p
        return total

    def substitute(self, env: Dict[str, int]) -> "E":
        """Partial evaluation: bound symbols fold away."""
        out = E(self.k)
        for c, syms in self.terms:
            coeff = c
            rest: List[Sym] = []
            for s in syms:
                v = env.get(s.name)
                if v is None:
                    rest.append(s)
                else:
                    coeff *= v
            out = out + (E(coeff) if not rest else E(0, ((coeff, tuple(rest)),)))
        return out

    def render(self) -> str:
        parts = []
        for c, syms in self.terms:
            body = "*".join(s.name for s in syms)
            parts.append(body if c == 1 else f"{c}*{body}")
        if self.k or not parts:
            parts.append(str(self.k))
        return " + ".join(parts)

    def __repr__(self):
        return f"E({self.render()})"

    def __eq__(self, other):
        return (
            isinstance(other, E)
            and self.k == other.k
            and self.terms == other.terms
        )

    def __hash__(self):
        return hash((self.k, self.terms))


def dim_of(x) -> E:
    """ints / Syms / Es as a normalized :class:`E`."""
    return E.of(x)


def unify_dim(model, observed: int, subst: Dict[str, int]) -> bool:
    """Unify a model dim against an observed concrete dim, extending
    ``subst`` (symbol name -> int) in place.

    Returns False only on a PROVABLE conflict: a fully-bound model dim
    that differs from the observation, or a single-unknown monomial
    (``512*NB`` vs an observed 1000) with no nonnegative integer
    solution — the shard-block-division case. A model dim with 2+
    unbound symbols cannot be refuted by one observation and unifies.
    """
    e = E.of(model).substitute(subst)
    c = e.const()
    if c is not None:
        return c == int(observed)
    free = e.syms()
    if len(free) == 1 and len(e.terms) == 1 and len(e.terms[0][1]) == 1:
        coeff = e.terms[0][0]
        rem = int(observed) - e.k
        if coeff == 0 or rem % coeff != 0 or rem // coeff < 0:
            return False
        subst[free[0].name] = rem // coeff
        return True
    return True  # under-determined: not refutable from one dim


# --- dtypes ------------------------------------------------------------

DTYPE_BYTES = {
    "bool": 1, "i8": 1, "u8": 1, "i16": 2, "u16": 2,
    "bf16": 2, "f16": 2, "i32": 4, "u32": 4, "f32": 4,
    "i64": 8, "u64": 8, "f64": 8,
}

_DTYPE_NAMES = {
    "float64": "f64", "double": "f64", "float32": "f32", "float16": "f16",
    "bfloat16": "bf16", "int64": "i64", "int32": "i32", "int16": "i16",
    "int8": "i8", "uint64": "u64", "uint32": "u32", "uint16": "u16",
    "uint8": "u8", "bool": "bool", "bool_": "bool",
}

FLOATS = ("bf16", "f16", "f32", "f64")
INTS = ("i8", "u8", "i16", "u16", "i32", "u32", "i64", "u64")


def dtype_name(raw: str) -> Optional[str]:
    """Canonical short dtype name for a numpy/jnp spelling."""
    return _DTYPE_NAMES.get(str(raw))


def promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Simplified jax promotion: higher float wins, floats beat ints,
    ints beat bool; unknown stays unknown."""
    if a is None or b is None:
        return None
    for lat in (("f64", "f32", "f16", "bf16"),):
        for d in lat:
            if a == d or b == d:
                return d
    if a in INTS or b in INTS:
        ia = INTS.index(a) if a in INTS else -1
        ib = INTS.index(b) if b in INTS else -1
        return INTS[max(ia, ib)]
    return a


def nbytes(shape: Tuple, dtype: Optional[str]) -> Optional[E]:
    """Symbolic byte count of an array; None when rank or dtype is
    unknown."""
    if shape is None:
        return None
    size = DTYPE_BYTES.get(dtype or "", None)
    if size is None:
        return None
    total = E(size)
    for d in shape:
        total = total * E.of(d)
    return total


# --- abstract values ---------------------------------------------------


class _Unknown:
    def __repr__(self):
        return "UNKNOWN"


UNKNOWN = _Unknown()


class Arr:
    """Abstract array: ``shape`` is a tuple of dims (:class:`E`) or
    None when the rank itself is unknown; ``device`` tags jnp-produced
    values; ``explicit_f64`` marks values whose float64-ness was
    EXPLICITLY requested (np.float64 ctor, dtype=float64, astype) —
    the only f64 the dtype-flow rule reports (numpy's silent f64
    defaults are host idiom, not drift)."""

    __slots__ = ("shape", "dtype", "device", "explicit_f64", "weak")

    def __init__(
        self, shape=None, dtype=None, device=False,
        explicit_f64=False, weak=False,
    ):
        self.shape = (
            None if shape is None else tuple(E.of(d) for d in shape)
        )
        self.dtype = dtype
        self.device = device
        self.explicit_f64 = explicit_f64
        self.weak = weak

    def rank(self) -> Optional[int]:
        return None if self.shape is None else len(self.shape)

    def with_(self, **kw) -> "Arr":
        out = Arr(
            self.shape, self.dtype, self.device, self.explicit_f64,
            self.weak,
        )
        for k, v in kw.items():
            setattr(out, k, v)
        return out

    def __repr__(self):
        dims = (
            "?" if self.shape is None
            else ",".join(d.render() for d in self.shape)
        )
        return f"Arr[{dims}]{self.dtype or '?'}"


class IntVal:
    """A Python int whose VALUE is a (possibly symbolic) dimension —
    the bridge that lets ``n = len(x); jnp.zeros((n, 4))`` carry x's
    leading dim (and its data/ratchet provenance) into a shape."""

    __slots__ = ("e",)

    def __init__(self, e):
        self.e = E.of(e)

    def __repr__(self):
        return f"IntVal({self.e.render()})"


class Lit:
    """A Python literal (str/float/bool/None) — ints use IntVal."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __repr__(self):
        return f"Lit({self.v!r})"


class Tup:
    __slots__ = ("items",)

    def __init__(self, items):
        self.items = list(items)

    def __repr__(self):
        return f"Tup({self.items})"


class DTypeVal:
    """A dtype OBJECT (``jnp.float64``, ``np.int32``) flowing as a
    value — what astype/dtype= arguments carry."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f"DTypeVal({self.name})"


def broadcast(a: Tuple, b: Tuple) -> Tuple[Optional[Tuple], Optional[Tuple]]:
    """Numpy broadcasting over two dim tuples. Returns (result_shape,
    conflict) where conflict is the offending (dim_a, dim_b) pair when
    two CONCRETE dims disagree and neither is 1; symbolic dims unify
    (the longer/other dim wins for the result)."""
    out: List[E] = []
    ra, rb = list(a)[::-1], list(b)[::-1]
    for i in range(max(len(ra), len(rb))):
        da = ra[i] if i < len(ra) else E(1)
        db = rb[i] if i < len(rb) else E(1)
        ca, cb = da.const(), db.const()
        if ca == 1:
            out.append(db)
        elif cb == 1:
            out.append(da)
        elif ca is not None and cb is not None and ca != cb:
            return None, (da, db)
        else:
            # equal constants, or at least one symbolic: keep the more
            # informative dim (a concrete one if present)
            out.append(da if ca is not None else db)
    return tuple(out[::-1]), None


# --- the interpreter ---------------------------------------------------

_NP_MODULES = ("numpy",)
_JNP_MODULES = ("jax.numpy",)

_CREATION = ("zeros", "ones", "empty", "full")
_REDUCERS = (
    "sum", "max", "min", "mean", "prod", "any", "all", "argmax",
    "argmin", "count_nonzero",
)
_DATA_DEPENDENT = (
    # calls whose RESULT LENGTH depends on array contents: the dims the
    # shape ratchet exists to pin before they reach a jit signature
    "flatnonzero", "nonzero", "unique", "where_single", "bincount",
    "searchsorted_none",
)


class Interp:
    """One abstract pass over a function body.

    Parameters:
      emit: ``emit(rule, node, message)`` findings sink.
      module_aliases: import-alias map (``{"jnp": "jax.numpy"}``) from
        the enclosing module, used to classify receivers as numpy/jnp;
        the conventional names work without it.
      intrinsics: ``{callable_terminal_name: handler(interp, node,
        args, kwargs) -> AVal}`` — how shapes.py injects the repo's
        idioms (``_ratchet``, ``shard_host_array``, ...).
      kernel: True inside kernel code (ops/, spill_device.py): enables
        the dtype-flow-drift checks.
    """

    def __init__(
        self,
        emit: Callable,
        module_aliases: Optional[Dict[str, str]] = None,
        intrinsics: Optional[Dict[str, Callable]] = None,
        kernel: bool = False,
        on_call: Optional[Callable] = None,
    ):
        self.emit = emit
        self.aliases = module_aliases or {}
        self.intrinsics = intrinsics or {}
        self.kernel = kernel
        #: optional ``on_call(interp, node, name, args, kwargs)`` —
        #: shapes.py's window onto every evaluated call (jit-boundary
        #: ratchet checks, HBM checks on constructed arrays)
        self.on_call = on_call
        self.env: Dict[str, object] = {}
        self._flagged: set = set()  # (rule, lineno) dedup within one run

    # --- receiver classification --------------------------------------

    def _mod_kind(self, name: str) -> Optional[str]:
        """'np' / 'jnp' / None for a receiver name."""
        target = self.aliases.get(name, "")
        if target in _JNP_MODULES or name == "jnp":
            return "jnp"
        if target in _NP_MODULES or name in ("np", "numpy"):
            return "np"
        return None

    # --- entry points --------------------------------------------------

    def run(self, fn_node: ast.AST, params: Dict[str, object]) -> None:
        """Interpret one function body with ``params`` pre-bound.
        Lambda bodies (a bare expression) evaluate directly."""
        self.env = dict(params)
        body = getattr(fn_node, "body", None)
        if isinstance(body, list):
            for stmt in body:
                self.stmt(stmt)
        elif body is not None:
            self.expr(body)

    # --- statements -----------------------------------------------------

    def stmt(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign):
            val = self.expr(node.value)
            for t in node.targets:
                self._bind(t, val)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._bind(node.target, self.expr(node.value))
        elif isinstance(node, ast.AugAssign):
            cur = (
                self.env.get(node.target.id, UNKNOWN)
                if isinstance(node.target, ast.Name)
                else UNKNOWN
            )
            new = self._binop(cur, self.expr(node.value), node.op, node)
            self._bind(node.target, new)
        elif isinstance(node, ast.Expr):
            self.expr(node.value)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self.expr(node.value)
        elif isinstance(node, (ast.If, ast.While)):
            self.expr(node.test)
            for s in node.body:
                self.stmt(s)
            for s in getattr(node, "orelse", []):
                self.stmt(s)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self.expr(node.iter)
            self._bind(node.target, UNKNOWN)
            for s in node.body + node.orelse:
                self.stmt(s)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self.expr(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, UNKNOWN)
            for s in node.body:
                self.stmt(s)
        elif isinstance(node, ast.Try):
            for s in (
                node.body
                + node.orelse
                + node.finalbody
                + [s for h in node.handlers for s in h.body]
            ):
                self.stmt(s)
        # nested defs/classes are their own scopes: skipped on purpose

    def _bind(self, target: ast.AST, val) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            items = (
                val.items
                if isinstance(val, Tup) and len(val.items) == len(target.elts)
                else [UNKNOWN] * len(target.elts)
            )
            for t, v in zip(target.elts, items):
                self._bind(t, v)
        # attribute/subscript targets: no store tracking

    # --- expressions ----------------------------------------------------

    def expr(self, node: ast.AST):
        try:
            return self._expr(node)
        except Exception:
            if STRICT:
                raise
            return UNKNOWN

    def _expr(self, node: ast.AST):
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return Lit(node.value)
            if isinstance(node.value, int):
                return IntVal(node.value)
            return Lit(node.value)
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, (ast.Tuple, ast.List)):
            return Tup([self.expr(e) for e in node.elts])
        if isinstance(node, ast.Attribute):
            return self._attribute(node)
        if isinstance(node, ast.Subscript):
            return self._subscript(node)
        if isinstance(node, ast.BinOp):
            return self._binop(
                self.expr(node.left), self.expr(node.right), node.op, node
            )
        if isinstance(node, ast.UnaryOp):
            v = self.expr(node.operand)
            if isinstance(v, IntVal) and isinstance(node.op, ast.USub):
                return IntVal(v.e * E(-1))
            return v
        if isinstance(node, ast.Compare):
            for c in [node.left] + list(node.comparators):
                self.expr(c)
            left = self.expr(node.left)
            if isinstance(left, Arr):
                return left.with_(dtype="bool", explicit_f64=False)
            return UNKNOWN
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self.expr(v)
            return UNKNOWN
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.IfExp):
            self.expr(node.test)
            a = self.expr(node.body)
            b = self.expr(node.orelse)
            return a if repr(a) == repr(b) else UNKNOWN
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            # evaluate the element once with loop targets unknown, so
            # calls inside comprehensions are still modeled
            for gen in node.generators:
                self.expr(gen.iter)
                self._bind(gen.target, UNKNOWN)
            self.expr(node.elt)
            return UNKNOWN
        if isinstance(node, ast.Lambda):
            return UNKNOWN
        if isinstance(node, ast.JoinedStr):
            return Lit("")
        return UNKNOWN

    # --- attributes -----------------------------------------------------

    def _attribute(self, node: ast.Attribute):
        attr = node.attr
        if isinstance(node.value, ast.Name):
            kind = self._mod_kind(node.value.id)
            if kind is not None:
                dn = dtype_name(attr)
                if dn is not None:
                    return DTypeVal(dn)
                return UNKNOWN
        base = self.expr(node.value)
        if isinstance(base, Arr):
            if attr == "shape":
                if base.shape is None:
                    return UNKNOWN
                return Tup([IntVal(d) for d in base.shape])
            if attr == "ndim":
                return (
                    UNKNOWN if base.shape is None
                    else IntVal(len(base.shape))
                )
            if attr == "size":
                if base.shape is None:
                    return UNKNOWN
                total = E(1)
                for d in base.shape:
                    total = total * d
                return IntVal(total)
            if attr == "dtype":
                return (
                    DTypeVal(base.dtype) if base.dtype else UNKNOWN
                )
            if attr == "T":
                return base.with_(
                    shape=(
                        None if base.shape is None
                        else tuple(reversed(base.shape))
                    )
                )
        return UNKNOWN

    # --- subscripts -----------------------------------------------------

    def _subscript(self, node: ast.Subscript):
        base = self.expr(node.value)
        if isinstance(base, Tup):
            idx = self.expr(node.slice)
            if isinstance(idx, IntVal):
                c = idx.e.const()
                if c is not None and -len(base.items) <= c < len(base.items):
                    return base.items[c]
            return UNKNOWN
        if isinstance(base, Arr) and base.shape is not None:
            sl = node.slice
            idx = self.expr(sl)
            if isinstance(sl, ast.Slice):
                return self._slice1(base, sl)
            if isinstance(idx, IntVal):
                # integer index drops the leading dim
                return base.with_(shape=base.shape[1:])
            if isinstance(idx, Arr) and idx.shape is not None:
                if idx.dtype == "bool":
                    # boolean mask: data-dependent result length
                    return base.with_(
                        shape=(E.of(fresh("m", "data")),) + base.shape[1:]
                    )
                return base.with_(shape=idx.shape + base.shape[1:])
            if isinstance(sl, ast.Tuple):
                shape = list(base.shape)
                out: List[E] = []
                i = 0
                for el in sl.elts:
                    if isinstance(el, ast.Slice):
                        if i < len(shape):
                            d = self._slice_dim(shape[i], el)
                            out.append(d)
                        i += 1
                    elif (
                        isinstance(el, ast.Constant) and el.value is None
                    ):
                        out.append(E(1))
                    elif isinstance(el, ast.Constant) and el.value is Ellipsis:
                        # ellipsis: give up on precise tracking
                        return base.with_(shape=None)
                    else:
                        ev = self.expr(el)
                        if isinstance(ev, Arr) and ev.shape is not None:
                            out.extend(ev.shape)
                        i += 1
                out.extend(shape[i:])
                return base.with_(shape=tuple(out))
            return base.with_(shape=None)
        return UNKNOWN

    def _slice_dim(self, dim: E, sl: ast.Slice) -> E:
        if sl.lower is None and sl.upper is None:
            return dim
        if sl.lower is None and sl.step is None:
            up = self.expr(sl.upper)
            if isinstance(up, IntVal):
                return up.e  # x[:n] -> n (clamp ignored: upper bound)
        return E.of(fresh("s"))

    def _slice1(self, base: Arr, sl: ast.Slice) -> Arr:
        return base.with_(
            shape=(self._slice_dim(base.shape[0], sl),) + base.shape[1:]
        )

    # --- operators ------------------------------------------------------

    def _binop(self, left, right, op, node):
        if isinstance(left, IntVal) and isinstance(right, IntVal):
            if isinstance(op, ast.Add):
                return IntVal(left.e + right.e)
            if isinstance(op, ast.Sub):
                return IntVal(left.e - right.e)
            if isinstance(op, ast.Mult):
                return IntVal(left.e * right.e)
            if isinstance(op, ast.FloorDiv):
                lc, rc = left.e.const(), right.e.const()
                if lc is not None and rc not in (None, 0):
                    return IntVal(lc // rc)
                return IntVal(E.of(fresh("q", self._prov(left.e))))
            if isinstance(
                op, (ast.Mod, ast.Pow, ast.LShift, ast.RShift,
                     ast.BitOr, ast.BitAnd, ast.BitXor)
            ):
                lc, rc = left.e.const(), right.e.const()
                if lc is not None and rc is not None:
                    try:
                        ops = {
                            ast.Mod: lambda a, b: a % b,
                            ast.Pow: lambda a, b: a**b,
                            ast.LShift: lambda a, b: a << b,
                            ast.RShift: lambda a, b: a >> b,
                            ast.BitOr: lambda a, b: a | b,
                            ast.BitAnd: lambda a, b: a & b,
                            ast.BitXor: lambda a, b: a ^ b,
                        }
                        return IntVal(ops[type(op)](lc, rc))
                    except (ZeroDivisionError, OverflowError):
                        return UNKNOWN
            return UNKNOWN
        if isinstance(left, Arr) or isinstance(right, Arr):
            a = left if isinstance(left, Arr) else right
            b = right if isinstance(left, Arr) else left
            if isinstance(b, Arr):
                shape = None
                if a.shape is not None and b.shape is not None:
                    shape, conflict = broadcast(a.shape, b.shape)
                    if conflict is not None:
                        self._emit(
                            "shape-mismatch",
                            node,
                            "operands cannot broadcast: dim "
                            f"{conflict[0].render()} vs "
                            f"{conflict[1].render()} (shapes "
                            f"[{','.join(d.render() for d in a.shape)}] "
                            f"and "
                            f"[{','.join(d.render() for d in b.shape)}])",
                        )
                        shape = None
                self._dtype_flow(node, a, b)
                return Arr(
                    shape,
                    promote(a.dtype, b.dtype),
                    a.device or b.device,
                    a.explicit_f64 or b.explicit_f64,
                )
            # array op scalar
            self._dtype_flow(node, a, b)
            dt = a.dtype
            exp = a.explicit_f64
            if self._is_explicit_f64(b):
                dt, exp = "f64", True
            return Arr(a.shape, dt, a.device, exp)
        return UNKNOWN

    @staticmethod
    def _prov(e: E) -> Optional[str]:
        for s in e.syms():
            if s.source == "data":
                return "data"
        for s in e.syms():
            if s.source == "ratchet":
                return "ratchet"
        return None

    # --- dtype flow -----------------------------------------------------

    @staticmethod
    def _is_explicit_f64(v) -> bool:
        # the explicit_f64 flag is maintained as an invariant: set only
        # by explicit-f64 sources, cleared when a cast/comparison moves
        # the value off f64 — so the flag alone decides, even when the
        # dtype itself got lost through an unmodeled op
        if isinstance(v, Arr):
            return v.explicit_f64
        if isinstance(v, DTypeVal):
            return v.name == "f64"
        return False

    def _dtype_flow(self, node, a, b) -> None:
        """A device array meeting an EXPLICIT f64 value in kernel code:
        the flow half of dtype-flow-drift (the call-boundary half lives
        in :meth:`_call`)."""
        if not self.kernel:
            return
        dev = (isinstance(a, Arr) and a.device) or (
            isinstance(b, Arr) and b.device
        )
        if not dev:
            return
        for v in (a, b):
            if self._is_explicit_f64(v) and not (
                isinstance(v, Arr) and v.device
            ):
                self._emit(
                    "dtype-flow-drift",
                    node,
                    "explicit float64 value flows into device "
                    "arithmetic: the kernels are f32/bf16 "
                    "(config.Precision); a float64 operand upcasts or "
                    "retraces — cast with the configured dtype",
                )

    def _emit(self, rule: str, node: ast.AST, msg: str) -> None:
        key = (rule, getattr(node, "lineno", 0))
        if key in self._flagged:
            return
        self._flagged.add(key)
        self.emit(rule, node, msg)

    # --- calls ----------------------------------------------------------

    def _shape_from(self, v) -> Optional[Tuple]:
        if isinstance(v, Tup):
            dims = []
            for it in v.items:
                if isinstance(it, IntVal):
                    dims.append(it.e)
                else:
                    dims.append(E.of(fresh("d")))
            return tuple(dims)
        if isinstance(v, IntVal):
            return (v.e,)
        return None

    def _dtype_from(self, v) -> Tuple[Optional[str], bool]:
        """(dtype, explicit) from a dtype-position argument."""
        if isinstance(v, DTypeVal):
            return v.name, True
        if isinstance(v, Lit) and isinstance(v.v, str):
            dn = dtype_name(v.v)
            return dn, dn is not None
        return None, False

    def _call(self, node: ast.Call):
        f = node.func
        args = [self.expr(a) for a in node.args]
        kwargs = {kw.arg: self.expr(kw.value) for kw in node.keywords if kw.arg}
        for kw in node.keywords:
            if kw.arg is None:
                self.expr(kw.value)

        # terminal callee name + receiver classification
        if isinstance(f, ast.Attribute):
            name = f.attr
            recv_kind = (
                self._mod_kind(f.value.id)
                if isinstance(f.value, ast.Name)
                else None
            )
            recv_val = None if recv_kind else self.expr(f.value)
        elif isinstance(f, ast.Name):
            name = f.id
            recv_kind = None
            recv_val = None
            bound = self.env.get(name)
            # a name bound to a modeled callable object (shapes.py's
            # JitFn): give it the call
            handler = getattr(bound, "absint_call", None)
            if handler is not None:
                return handler(self, node, args, kwargs)
        else:
            return UNKNOWN

        if self.on_call is not None:
            self.on_call(self, node, name, args, kwargs)
        if name in self.intrinsics:
            return self.intrinsics[name](self, node, args, kwargs)

        # builtins ------------------------------------------------------
        if recv_kind is None and recv_val is None:
            if name == "len" and args:
                a = args[0]
                if isinstance(a, Arr) and a.shape:
                    return IntVal(a.shape[0])
                if isinstance(a, Tup):
                    return IntVal(len(a.items))
                return IntVal(E.of(fresh("n", "data")))
            if name in ("int", "round") and args:
                a = args[0]
                if isinstance(a, IntVal):
                    return a
                return IntVal(E.of(fresh("n", self._arg_prov(a))))
            if name in ("min", "max") and len(args) >= 2:
                if all(isinstance(a, IntVal) for a in args):
                    cs = [a.e.const() for a in args]
                    if all(c is not None for c in cs):
                        return IntVal(min(cs) if name == "min" else max(cs))
                    return IntVal(
                        E.of(fresh("n", self._prov(args[0].e) or
                                   self._prov(args[1].e)))
                    )
            return UNKNOWN

        # array methods -------------------------------------------------
        if recv_val is not None:
            if isinstance(recv_val, Arr):
                return self._array_method(node, recv_val, name, args, kwargs)
            return UNKNOWN

        # np./jnp. functions --------------------------------------------
        device = recv_kind == "jnp"
        if self.kernel and device:
            # call-boundary half of dtype-flow-drift: explicit f64
            # VALUES or dtype literals entering a jnp call
            for v in list(args) + list(kwargs.values()):
                if self._is_explicit_f64(v) or (
                    isinstance(v, Lit) and v.v == "float64"
                ):
                    self._emit(
                        "dtype-flow-drift",
                        node,
                        f"float64 reaches device op jnp.{name}: the "
                        "kernels are f32/bf16 (config.Precision); a "
                        "float64 input upcasts or retraces — use the "
                        "configured dtype",
                    )
                    break
        return self._np_call(node, name, device, args, kwargs)

    @staticmethod
    def _arg_prov(a) -> Optional[str]:
        if isinstance(a, IntVal):
            return Interp._prov(a.e)
        if isinstance(a, Arr):
            return "data"
        return None

    def _array_method(self, node, arr: Arr, name, args, kwargs):
        if name == "astype" and args:
            dn, explicit = self._dtype_from(args[0])
            if (
                self.kernel
                and arr.device
                and dn == "f64"
                and explicit
            ):
                self._emit(
                    "dtype-flow-drift",
                    node,
                    "astype(float64) on a device array in kernel code: "
                    "the kernels are f32/bf16 (config.Precision) — use "
                    "the configured dtype",
                )
            return arr.with_(
                dtype=dn or arr.dtype,
                explicit_f64=(dn == "f64" and explicit),
            )
        if name == "reshape":
            shape_arg = (
                args[0]
                if len(args) == 1 and isinstance(args[0], (Tup, IntVal))
                else Tup(args)
            )
            return self._reshape(node, arr, shape_arg)
        if name in _REDUCERS:
            return self._reduce(arr, args, kwargs, name)
        if name in ("copy", "block_until_ready", "clip", "round"):
            return arr
        if name == "item":
            return UNKNOWN
        if name in ("tolist", "flatten", "ravel"):
            if name in ("flatten", "ravel") and arr.shape is not None:
                total = E(1)
                for d in arr.shape:
                    total = total * d
                return arr.with_(shape=(total,))
            return UNKNOWN
        return UNKNOWN

    def _reshape(self, node, arr: Arr, shape_val):
        target = self._shape_from(shape_val)
        if target is None:
            return arr.with_(shape=None)
        # resolve a single -1 when the source size is fully concrete
        dims = list(target)
        holes = [
            i for i, d in enumerate(dims)
            if d.const() is not None and d.const() == -1
        ]
        if holes and arr.shape is not None:
            total = E(1)
            for d in arr.shape:
                total = total * d
            tc = total.const()
            rest = E(1)
            for i, d in enumerate(dims):
                if i != holes[0]:
                    rest = rest * d
            rc = rest.const()
            if len(holes) == 1 and tc is not None and rc not in (None, 0):
                if tc % rc == 0:
                    dims[holes[0]] = E(tc // rc)
                else:
                    self._emit(
                        "shape-mismatch",
                        node,
                        f"reshape cannot fold {tc} elements into "
                        f"blocks of {rc}",
                    )
                    return arr.with_(shape=None)
            else:
                dims[holes[0]] = E.of(fresh("r"))
        elif holes:
            dims[holes[0]] = E.of(fresh("r"))
        # fully-concrete sanity check
        if arr.shape is not None and not holes:
            total = E(1)
            for d in arr.shape:
                total = total * d
            tgt = E(1)
            for d in dims:
                tgt = tgt * d
            tc, gc = total.const(), tgt.const()
            if tc is not None and gc is not None and tc != gc:
                self._emit(
                    "shape-mismatch",
                    node,
                    f"reshape of {tc} elements to a {gc}-element shape",
                )
                return arr.with_(shape=None)
        return arr.with_(shape=tuple(dims))

    def _reduce(self, arr: Arr, args, kwargs, name):
        int_out = name in ("argmax", "argmin", "count_nonzero")
        bool_out = name in ("any", "all")
        dtype = "i64" if int_out else ("bool" if bool_out else arr.dtype)
        axis = kwargs.get("axis")
        if axis is None and args:
            axis = args[0] if isinstance(args[0], IntVal) else None
        if axis is None:
            # full reduction: a scalar whose VALUE is data-dependent
            if name in ("sum", "count_nonzero", "argmax", "argmin") and (
                arr.dtype in INTS or arr.dtype == "bool" or True
            ):
                return IntVal(E.of(fresh("n", "data")))
            return Arr((), dtype, arr.device, arr.explicit_f64)
        if (
            isinstance(axis, IntVal)
            and axis.e.const() is not None
            and arr.shape is not None
        ):
            ax = axis.e.const()
            if -len(arr.shape) <= ax < len(arr.shape):
                shape = list(arr.shape)
                shape.pop(ax)
                return Arr(
                    tuple(shape), dtype, arr.device, arr.explicit_f64
                )
        return Arr(None, dtype, arr.device, arr.explicit_f64)

    def _np_call(self, node, name, device, args, kwargs):
        exp64 = False
        dt, explicit = self._dtype_from(kwargs.get("dtype", UNKNOWN))
        if dt is None:
            # positional dtype (np.zeros(shape, np.float32))
            for a in args[1:]:
                dt, explicit = self._dtype_from(a)
                if dt is not None:
                    break
        exp64 = dt == "f64" and explicit

        if name in _CREATION or name in ("zeros_like", "ones_like",
                                         "full_like", "empty_like"):
            if name.endswith("_like") and args and isinstance(args[0], Arr):
                src = args[0]
                return Arr(
                    src.shape, dt or src.dtype, device, exp64
                )
            shape = self._shape_from(args[0]) if args else None
            if dt is None:
                dt = "f32" if device else "f64"
                explicit = False
            return Arr(shape, dt, device, exp64)
        if name == "arange":
            if args and isinstance(args[0], IntVal) and len(args) == 1:
                return Arr((args[0].e,), dt or "i64", device, exp64)
            return Arr((E.of(fresh("n")),), dt or "i64", device, exp64)
        if name in ("asarray", "array", "ascontiguousarray"):
            if args and isinstance(args[0], Arr):
                src = args[0]
                return Arr(
                    src.shape,
                    dt or src.dtype,
                    device or src.device,
                    exp64 or (src.explicit_f64 and dt is None),
                )
            if args and isinstance(args[0], Tup):
                return Arr(
                    (E(len(args[0].items)),), dt, device, exp64
                )
            return Arr(None, dt, device, exp64)
        if name in ("float64", "float32", "float16", "bfloat16", "int32",
                    "int64", "int16", "int8", "uint8", "uint16", "uint32",
                    "uint64"):
            dn = dtype_name(name)
            return Arr((), dn, device, dn == "f64")
        if name in ("concatenate", "stack", "hstack", "vstack",
                    "column_stack"):
            return self._concat(node, name, args, kwargs, device)
        if name in ("dot", "matmul"):
            return self._dot(node, args, device)
        if name == "where" and len(args) == 3:
            shape = None
            arrs = [a for a in args if isinstance(a, Arr)]
            if len(arrs) >= 2:
                cur = arrs[0]
                for other in arrs[1:]:
                    if cur.shape is None or other.shape is None:
                        cur = cur.with_(shape=None)
                        continue
                    s, conflict = broadcast(cur.shape, other.shape)
                    if conflict is not None:
                        self._emit(
                            "shape-mismatch",
                            node,
                            "where operands cannot broadcast: dim "
                            f"{conflict[0].render()} vs "
                            f"{conflict[1].render()}",
                        )
                        s = None
                    cur = cur.with_(shape=s)
                shape = cur.shape
            dts = [a.dtype for a in arrs[1:] if a.dtype] or [None]
            out_dt = dts[0]
            for d in dts[1:]:
                out_dt = promote(out_dt, d)
            return Arr(shape, out_dt, device,
                       any(a.explicit_f64 for a in arrs[1:]))
        if name == "where" and len(args) == 1:
            return Arr((E.of(fresh("m", "data")),), "i64", device)
        if name in ("flatnonzero",):
            return Arr((E.of(fresh("m", "data")),), "i64", device)
        if name in ("nonzero",):
            return UNKNOWN
        if name in ("unique", "bincount"):
            return Arr((E.of(fresh("u", "data")),), "i64", device)
        if name in ("broadcast_to",) and len(args) >= 2:
            shape = self._shape_from(args[1])
            src = args[0] if isinstance(args[0], Arr) else None
            return Arr(shape, src.dtype if src else None, device,
                       src.explicit_f64 if src else False)
        if name in ("reshape",) and len(args) >= 2 and isinstance(
            args[0], Arr
        ):
            return self._reshape(node, args[0], args[1])
        if name in _REDUCERS and args and isinstance(args[0], Arr):
            return self._reduce(args[0], args[1:], kwargs, name)
        if name in ("abs", "sqrt", "exp", "log", "floor", "ceil", "clip",
                    "maximum", "minimum", "mod", "power", "square", "sign",
                    "logical_and", "logical_or", "logical_not", "isfinite",
                    "sin", "cos", "tan", "arcsin", "arctan2", "radians"):
            arrs = [a for a in args if isinstance(a, Arr)]
            if len(arrs) == 2 and name in ("maximum", "minimum", "mod",
                                           "power", "arctan2",
                                           "logical_and", "logical_or"):
                return self._binop(arrs[0], arrs[1], ast.Add(), node)
            if arrs:
                a = arrs[0]
                if name in ("logical_and", "logical_or", "logical_not",
                            "isfinite"):
                    return a.with_(dtype="bool", explicit_f64=False)
                return a
            return UNKNOWN
        if name in ("repeat", "tile", "pad", "cumsum", "sort", "argsort",
                    "take", "searchsorted", "einsum", "unpackbits",
                    "packbits", "lexsort", "split"):
            # modeled weakly on purpose: result shapes are data/arg
            # dependent in ways the rules do not need
            src = next((a for a in args if isinstance(a, Arr)), None)
            if name == "cumsum" and src is not None:
                return src
            if src is not None:
                return src.with_(shape=None)
            return UNKNOWN
        return UNKNOWN

    def _concat(self, node, name, args, kwargs, device):
        seq = args[0] if args else None
        if not isinstance(seq, Tup):
            return Arr(None, None, device)
        arrs = [a for a in seq.items if isinstance(a, Arr)]
        if len(arrs) != len(seq.items) or not arrs:
            return Arr(None, None, device)
        axis_v = kwargs.get("axis") or (
            args[1] if len(args) > 1 else None
        )
        axis = 0
        if isinstance(axis_v, IntVal) and axis_v.e.const() is not None:
            axis = axis_v.e.const()
        dt = arrs[0].dtype
        exp = any(a.explicit_f64 for a in arrs)
        for a in arrs[1:]:
            dt = promote(dt, a.dtype)
        if name == "stack":
            base = arrs[0].shape
            for a in arrs[1:]:
                if base is None or a.shape is None:
                    base = None
                    break
                for d1, d2 in zip(base, a.shape):
                    c1, c2 = d1.const(), d2.const()
                    if c1 is not None and c2 is not None and c1 != c2:
                        self._emit(
                            "shape-mismatch",
                            node,
                            f"stack of unequal shapes: dim {c1} vs {c2}",
                        )
                        base = None
                        break
                if base is None:
                    break
            if base is None:
                return Arr(None, dt, device, exp)
            return Arr((E(len(arrs)),) + tuple(base), dt, device, exp)
        # concatenate family
        shapes = [a.shape for a in arrs]
        if any(s is None for s in shapes):
            return Arr(None, dt, device, exp)
        rank = len(shapes[0])
        if any(len(s) != rank for s in shapes[1:]):
            self._emit(
                "shape-mismatch",
                node,
                "concatenate of arrays with different ranks: "
                + " vs ".join(str(len(s)) for s in shapes),
            )
            return Arr(None, dt, device, exp)
        if not (-rank <= axis < rank):
            return Arr(None, dt, device, exp)
        out: List[E] = []
        for i in range(rank):
            if i == axis % rank:
                total = E(0)
                for s in shapes:
                    total = total + s[i]
                out.append(total)
                continue
            dim = shapes[0][i]
            for s in shapes[1:]:
                c1, c2 = dim.const(), s[i].const()
                if c1 is not None and c2 is not None and c1 != c2:
                    self._emit(
                        "shape-mismatch",
                        node,
                        f"concatenate: off-axis dim {c1} vs {c2} "
                        f"(axis {axis})",
                    )
                    return Arr(None, dt, device, exp)
                if c1 is None:
                    dim = s[i]
            out.append(dim)
        return Arr(tuple(out), dt, device, exp)

    def _dot(self, node, args, device):
        arrs = [a for a in args if isinstance(a, Arr)]
        if len(arrs) != 2:
            return Arr(None, None, device)
        a, b = arrs
        if a.shape is None or b.shape is None or not a.shape or not b.shape:
            return Arr(None, promote(a.dtype, b.dtype), device)
        ka = a.shape[-1]
        kb = b.shape[-2] if len(b.shape) >= 2 else b.shape[0]
        c1, c2 = ka.const(), kb.const()
        if c1 is not None and c2 is not None and c1 != c2:
            self._emit(
                "shape-mismatch",
                node,
                f"dot/matmul contraction mismatch: {c1} vs {c2} "
                f"(shapes [{','.join(d.render() for d in a.shape)}] "
                f"x [{','.join(d.render() for d in b.shape)}])",
            )
            return Arr(None, promote(a.dtype, b.dtype), device)
        out = tuple(a.shape[:-1]) + (
            tuple(b.shape[:-2]) + (b.shape[-1],)
            if len(b.shape) >= 2
            else ()
        )
        return Arr(
            out, promote(a.dtype, b.dtype), device,
            a.explicit_f64 or b.explicit_f64,
        )
