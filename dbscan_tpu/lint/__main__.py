import sys

from dbscan_tpu.lint.cli import main

if __name__ == "__main__":
    sys.exit(main())
