"""graftcheck collective-safety rules: the hazards that deadlock every
chip at once.

These gate ROADMAP item 1 (true multi-chip scale-out): a mis-placed
collective inside a ``shard_map``/``pjit`` body does not crash one
process — it hangs ALL of them, because the other chips sit inside the
matching collective forever. The reference's driver-side merge sidesteps
executor coordination entirely (MR-DBSCAN, DBSCAN.scala); device-
parallel DBSCAN has to get it right (Prokopenko et al. 2103.05162), so
we machine-check it before the multichip PR lands, not after it hangs an
8-chip run.

**Collective regions**: functions passed to ``shard_map``/``pjit``
(directly, via ``functools.partial``, or as lambdas), their lexically
nested defs, and everything transitively called — with callable
arguments propagated (``lax.map(one, ...)`` runs ``one`` under the same
trace).

- ``collective-in-branch``: a collective (``psum``/``all_gather``/
  ``ppermute``/...) under an ``if``/``while`` whose test can DIVERGE
  across processes — it references a traced parameter of the enclosing
  region function, an array-op result, or a per-process host source
  (``process_index``, environment reads, ``random``/``time``). A
  conditional on uniform host config (a closure over the builder's
  ``mesh`` argument — the repo idiom) is fine: every process traces the
  same branch. Divergent tests mean some processes issue the collective
  and others never do: deadlock.
- ``collective-axis-undeclared``: the collective's ``axis_name``
  resolves to a literal that is not among the mesh axis names declared
  anywhere in the linted set (``Mesh(devices, ("parts",))`` /
  ``axis_names=`` — module string constants like ``PARTS_AXIS`` are
  resolved through imports). A typo'd axis fails at trace time only on
  the multichip path nobody runs in CI. Skipped entirely when the
  linted set declares no mesh (fixture snippets).
- ``pull-in-collective``: a host pull (``pull_to_host`` /
  ``copy_to_host_async`` / ``device_get``) reachable from a collective
  region — the static form of the "pull engine forces itself off in
  multi-process runs" invariant: pulls from inside the region would
  interleave cross-host collectives nondeterministically per process.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dbscan_tpu.lint.callgraph import DispatchSiteVisitor, terminal_name
from dbscan_tpu.lint.core import Finding, Package

_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "pbroadcast",
}
_REGION_WRAPPERS = ("shard_map", "pjit")
_PULLS = {"pull_to_host", "copy_to_host_async", "device_get"}
_DIVERGENT_CALLS = {
    "process_index", "getenv", "environ", "random", "randint", "time",
    "perf_counter", "urandom", "uniform",
}
_ARRAY_MODULES = ("jnp", "lax", "jax")


def _collective_name(call: ast.Call) -> Optional[str]:
    f = call.func
    attr = terminal_name(f)
    return attr if attr in _COLLECTIVES else None


class _RegionRootVisitor(DispatchSiteVisitor):
    """shard_map/pjit wrapping sites, on the shared
    :class:`callgraph.DispatchSiteVisitor` machinery."""

    def candidate_exprs(self, node: ast.Call) -> list:
        if terminal_name(node.func) in _REGION_WRAPPERS:
            return list(node.args[:1])
        return []


def _region_roots(cg) -> List:
    """FuncInfos passed to shard_map/pjit anywhere in the linted set."""
    roots = []
    for mod in cg.modules.values():
        v = _RegionRootVisitor(cg, mod)
        v.visit(mod.tree)
        roots.extend(v.roots)
    return roots


def _region_funcs(cg) -> Dict[int, object]:
    """Transitive closure of the collective regions: roots + nested
    defs (trace-time helpers) + resolvable callees + callable
    arguments — the shared :func:`callgraph.reach_closure` traversal."""
    from dbscan_tpu.lint import callgraph as cg_mod

    return cg_mod.reach_closure(
        cg, _region_roots(cg), include_nested_defs=True
    )


def _params_of(node) -> Set[str]:
    args = getattr(node, "args", None)
    if args is None:
        return set()
    names = {
        a.arg
        for a in list(args.args)
        + list(args.kwonlyargs)
        + list(args.posonlyargs)
    }
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def _divergent_test(test: ast.AST, traced_params: Set[str]) -> Optional[str]:
    """Why this branch test can diverge across processes, or None when
    it is (as far as the analysis can tell) uniform host config."""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in traced_params:
            return f"references traced parameter {node.id!r}"
        if isinstance(node, ast.Call):
            f = node.func
            attr = terminal_name(f)
            if attr in _DIVERGENT_CALLS:
                return f"calls per-process source {attr!r}()"
            if isinstance(f, ast.Attribute):
                root = f.value
                while isinstance(root, (ast.Attribute, ast.Subscript)):
                    root = root.value
                if isinstance(root, ast.Name) and (
                    root.id in _ARRAY_MODULES
                ):
                    return f"computes on traced arrays ({root.id}.{f.attr})"
        if isinstance(node, ast.Subscript):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr == "environ":
                return "reads the process environment"
    return None


def _resolve_axis(cg, mod, expr) -> List[str]:
    """Axis-name literals an axis argument resolves to ([] when it
    cannot be resolved — no finding then)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for el in expr.elts:
            out.extend(_resolve_axis(cg, mod, el))
        return out
    if isinstance(expr, ast.Name):
        if expr.id in mod.constants:
            return [mod.constants[expr.id]]
        tgt = mod.from_names.get(expr.id)
        if tgt is not None:
            m2 = cg.by_modname.get(tgt[0])
            if m2 is not None and tgt[1] in m2.constants:
                return [m2.constants[tgt[1]]]
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        modname = mod.import_alias.get(expr.value.id)
        if modname is not None:
            m2 = cg.by_modname.get(modname)
            if m2 is not None and expr.attr in m2.constants:
                return [m2.constants[expr.attr]]
    return []


def _declared_axes(cg) -> Tuple[Set[str], bool]:
    """(axis names declared by Mesh constructions in the linted set,
    any-mesh-seen)."""
    axes: Set[str] = set()
    seen = False
    for mod in cg.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = terminal_name(f)
            if attr not in ("Mesh", "make_mesh", "AbstractMesh"):
                continue
            seen = True
            cands = []
            if len(node.args) >= 2:
                cands.append(node.args[1])
            for kw in node.keywords:
                if kw.arg == "axis_names":
                    cands.append(kw.value)
            for c in cands:
                axes.update(_resolve_axis(cg, mod, c))
    return axes, seen


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    cg = pkg.callgraph
    region = _region_funcs(cg)
    if not region:
        return findings
    axes, mesh_seen = _declared_axes(cg)

    for info in region.values():
        mod = info.module
        traced = _params_of(info.node)

        def walk(node, branch_reason, info=info, mod=mod, traced=traced):
            if node is not info.node and isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
            ):
                return  # separate region entries
            reason = branch_reason
            if isinstance(node, (ast.If, ast.While)):
                r = _divergent_test(node.test, traced)
                if r is not None:
                    reason = reason or r
            if isinstance(node, ast.Call):
                cname = _collective_name(node)
                if cname is not None:
                    if reason is not None:
                        findings.append(
                            Finding(
                                "collective-in-branch",
                                mod.path,
                                node.lineno,
                                node.col_offset,
                                f"collective {cname!r} under a "
                                f"conditional that {reason}: processes "
                                "taking different branches deadlock "
                                "every chip in the matching collective "
                                "— hoist the collective out of the "
                                "branch or make the branch "
                                "data-independent (lax.cond with both "
                                "sides collective-free, or uniform "
                                "host config)",
                            )
                        )
                    axis_exprs = []
                    if len(node.args) >= 2:
                        axis_exprs.append(node.args[1])
                    for kw in node.keywords:
                        if kw.arg == "axis_name":
                            axis_exprs.append(kw.value)
                    if mesh_seen:
                        for expr in axis_exprs:
                            for name in _resolve_axis(cg, mod, expr):
                                if name not in axes:
                                    findings.append(
                                        Finding(
                                            "collective-axis-undeclared",
                                            mod.path,
                                            node.lineno,
                                            node.col_offset,
                                            f"collective {cname!r} "
                                            f"names axis {name!r}, "
                                            "which no Mesh declaration "
                                            "in the linted set provides "
                                            "— a typo'd axis only "
                                            "fails on the multichip "
                                            "path (declared axes are "
                                            "deliberately not listed "
                                            "here: baselines match on "
                                            "message text, and a new "
                                            "unrelated mesh axis must "
                                            "not resurrect baselined "
                                            "findings)",
                                        )
                                    )
                else:
                    f = node.func
                    attr = terminal_name(f)
                    if attr in _PULLS:
                        findings.append(
                            Finding(
                                "pull-in-collective",
                                mod.path,
                                node.lineno,
                                node.col_offset,
                                f"host pull {attr!r} reachable from a "
                                "shard_map/pjit collective region: in "
                                "a multi-process run this interleaves "
                                "cross-host transfers with the "
                                "collective sequence "
                                "nondeterministically — pull at the "
                                "driver boundary instead (the pull "
                                "pipeline already disables itself "
                                "there; keep pulls out of the region)",
                            )
                        )
            for child in ast.iter_child_nodes(node):
                walk(child, reason, info, mod, traced)

        body = getattr(info.node, "body", [])
        for stmt in body if isinstance(body, list) else [body]:
            walk(stmt, None)
    return findings
