"""host-sync rules: implicit device->host synchronization in trace-time
code.

Scope: functions in the jit-reachable set (lint/callgraph.py). Inside
traced code a ``.item()`` / ``float(arr)`` / ``np.asarray(tracer)``
either fails at trace time (so it lurks in a branch the tests never
trace) or — when the same helper is also called outside jit — silently
drags a device sync into a hot path the driver believes is async.

- ``host-sync-item``: any ``X.item()`` call;
- ``host-sync-cast``: ``float()/int()/bool()`` applied to an array
  expression (a ``jnp.*``/``lax.*`` call result, a name assigned from
  one, or a non-static parameter of a jit root). ``len(...)`` and
  ``x.shape[...]`` operands are exempt — those are Python ints under
  trace;
- ``host-sync-asarray``: ``np.asarray``/``np.array`` applied to an
  array expression (literal-built arrays are fine).
"""

from __future__ import annotations

import ast
from typing import List, Set

from dbscan_tpu.lint.core import Finding, Package

_ARRAY_MODULES = ("jnp", "lax", "jax")
_CASTS = ("float", "int", "bool")
_NP_NAMES = ("np", "numpy")


def _root_name(expr: ast.AST):
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _is_array_call(expr: ast.AST) -> bool:
    """A call into jnp./lax./jax.* — its result is a traced array."""
    if not isinstance(expr, ast.Call):
        return False
    f = expr.func
    root = _root_name(f) if isinstance(f, ast.Attribute) else None
    return root in _ARRAY_MODULES


def _shape_or_len(expr: ast.AST) -> bool:
    """``x.shape[i]`` / ``len(x)`` / ``x.ndim`` — ints under trace."""
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name):
        if expr.func.id == "len":
            return True
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in (
            "shape",
            "ndim",
            "size",
        ):
            return True
    return False


class _FnScanner(ast.NodeVisitor):
    def __init__(self, src_path: str, fn_info, findings: List[Finding]):
        self.path = src_path
        self.findings = findings
        self.array_names: Set[str] = set()
        node = fn_info.node
        if fn_info.is_jit_root and hasattr(node, "args"):
            params = {a.arg for a in node.args.args}
            params |= {a.arg for a in node.args.kwonlyargs}
            self.array_names |= params - fn_info.static_params
        # seed assigned-from-jnp names (single forward pass is enough
        # for straight-line kernel code)
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Assign) and _is_array_call(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.array_names.add(t.id)
            elif isinstance(stmt, ast.AugAssign) and _is_array_call(
                stmt.value
            ):
                if isinstance(stmt.target, ast.Name):
                    self.array_names.add(stmt.target.id)

    def _arrayish(self, expr: ast.AST) -> bool:
        if _is_array_call(expr):
            return True
        if isinstance(expr, (ast.Attribute, ast.Subscript, ast.Name)):
            return _root_name(expr) in self.array_names
        if isinstance(expr, ast.BinOp):
            return self._arrayish(expr.left) or self._arrayish(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self._arrayish(expr.operand)
        if isinstance(expr, ast.Call) and isinstance(
            expr.func, ast.Attribute
        ):
            # method on an array expression (x.sum(), x.astype(...))
            return self._arrayish(expr.func.value)
        return False

    def visit_Call(self, node: ast.Call):
        f = node.func
        # X.item()
        if isinstance(f, ast.Attribute) and f.attr == "item" and not node.args:
            self.findings.append(
                Finding(
                    "host-sync-item",
                    self.path,
                    node.lineno,
                    node.col_offset,
                    ".item() forces a device->host sync in jit-reachable "
                    "code; return the array and pull at the driver "
                    "boundary instead",
                )
            )
        # float(E) / int(E) / bool(E)
        elif (
            isinstance(f, ast.Name)
            and f.id in _CASTS
            and len(node.args) == 1
        ):
            arg = node.args[0]
            if (
                self._arrayish(arg)
                and not _shape_or_len(arg)
                and not isinstance(arg, ast.Constant)
            ):
                self.findings.append(
                    Finding(
                        "host-sync-cast",
                        self.path,
                        node.lineno,
                        node.col_offset,
                        f"{f.id}() on an array expression host-syncs "
                        "under jit; keep it as a 0-d array (or mark the "
                        "argument static)",
                    )
                )
        # np.asarray / np.array on array values
        elif (
            isinstance(f, ast.Attribute)
            and f.attr in ("asarray", "array")
            and isinstance(f.value, ast.Name)
            and f.value.id in _NP_NAMES
            and node.args
        ):
            arg = node.args[0]
            if self._arrayish(arg):
                self.findings.append(
                    Finding(
                        "host-sync-asarray",
                        self.path,
                        node.lineno,
                        node.col_offset,
                        f"np.{f.attr}() on a traced array fails (or "
                        "host-syncs) in jit-reachable code; use "
                        "jnp.asarray or hoist the conversion to the host "
                        "boundary",
                    )
                )
        self.generic_visit(node)


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    cg = pkg.callgraph
    seen = set()
    for mod in cg.modules.values():
        for info in mod.all_functions:
            if not cg.in_reachable(info.node) or id(info.node) in seen:
                continue
            seen.add(id(info.node))
            scanner = _FnScanner(mod.path, info, findings)
            body = getattr(info.node, "body", [])
            for stmt in body if isinstance(body, list) else [body]:
                scanner.visit(stmt)
    return findings
