"""graftlint engine: file collection, findings, suppressions, runner.

The rule modules (:mod:`hostsync`, :mod:`recompile`, :mod:`telemetry`,
:mod:`envvars`, and the graftcheck families :mod:`races` /
:mod:`collectives`) are pure functions ``(Package) -> list[Finding]``
over a parsed :class:`Package`; this module owns everything around them —
reading sources, per-line ``# graftlint: disable=RULE  <reason>``
suppressions (the reason text is REQUIRED; a bare disable keeps the
finding and adds a ``suppress-no-reason`` one), and deterministic
ordering of the output.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, anchored to a source position."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# matches "graftlint: disable=<rule>[,<rule>]  <reason>" in a comment
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=(?P<rules>[a-z0-9_,-]+)(?P<reason>.*)$"
)


@dataclasses.dataclass
class Suppression:
    rules: Tuple[str, ...]
    reason: str
    line: int


class SourceFile:
    """One parsed source file: text, AST, and its suppression table."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.parse_error = e
        self.suppressions: Dict[int, Suppression] = {}
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        # tokenize so a "# graftlint:" inside a string literal is not a
        # suppression; fall back to the regex per line on token errors
        comments: List[Tuple[int, str]] = []
        try:
            import io

            for tok in tokenize.generate_tokens(
                io.StringIO(self.text).readline
            ):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.string))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = [
                (i + 1, ln) for i, ln in enumerate(self.lines) if "#" in ln
            ]
        for line_no, comment in comments:
            m = _SUPPRESS_RE.search(comment)
            if m is None:
                continue
            rules = tuple(
                r.strip() for r in m.group("rules").split(",") if r.strip()
            )
            self.suppressions[line_no] = Suppression(
                rules=rules,
                reason=m.group("reason").strip(),
                line=line_no,
            )


class Package:
    """The linted file set plus the cross-file indexes rules consume.

    ``callgraph`` is attached lazily by the runner (built once, shared
    by the host-sync and recompile families).
    """

    def __init__(self, files: List[SourceFile]):
        self.files = files
        self.callgraph = None  # set by run_rules

    def by_path(self, path: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.path == path:
                return f
        return None


def collect_files(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into a sorted .py file list (skips
    __pycache__ and hidden directories)."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d
                    for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for n in sorted(names):
                    if n.endswith(".py"):
                        out.append(os.path.join(root, n))
        else:
            raise FileNotFoundError(p)
    # stable, deduplicated
    seen = set()
    uniq = []
    for p in out:
        rp = os.path.normpath(p)
        if rp not in seen:
            seen.add(rp)
            uniq.append(rp)
    return uniq


def load_package(paths: Iterable[str]) -> Package:
    files = []
    for p in collect_files(paths):
        with open(p, encoding="utf-8") as f:
            files.append(SourceFile(p, f.read()))
    return Package(files)


def apply_suppressions(
    pkg: Package,
    findings: List[Finding],
    known_rules: Iterable[str],
    aliases: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Drop findings covered by a same-line suppression WITH a reason;
    emit ``suppress-no-reason`` / ``suppress-unknown-rule`` findings for
    malformed suppressions. ``aliases`` (retired rule id -> successor)
    lets a suppression naming the OLD id keep silencing the successor's
    findings, and keeps the old id "known"."""
    aliases = aliases or {}
    known = set(known_rules) | set(aliases)
    out: List[Finding] = []
    for f in findings:
        src = pkg.by_path(f.path)
        sup = src.suppressions.get(f.line) if src else None
        if sup:
            sup_rules = {aliases.get(r, r) for r in sup.rules}
            if f.rule in sup_rules or "all" in sup_rules:
                if sup.reason:
                    continue  # properly suppressed
        out.append(f)
    for src in pkg.files:
        for sup in src.suppressions.values():
            if not sup.reason:
                out.append(
                    Finding(
                        "suppress-no-reason",
                        src.path,
                        sup.line,
                        0,
                        "suppression requires a reason: "
                        "# graftlint: disable=RULE  <why this is intended>",
                    )
                )
            for r in sup.rules:
                if r != "all" and r not in known:
                    out.append(
                        Finding(
                            "suppress-unknown-rule",
                            src.path,
                            sup.line,
                            0,
                            f"unknown rule id {r!r} in suppression",
                        )
                    )
    return out


def run_rules(
    pkg: Package, rule_fns, known_rules, aliases=None
) -> List[Finding]:
    """Run every rule family over the package, then apply suppressions
    and sort (path, line, col, rule). Unparseable files surface as
    ``parse-error`` findings rather than crashing the run."""
    findings: List[Finding] = []
    for src in pkg.files:
        if src.parse_error is not None:
            e = src.parse_error
            findings.append(
                Finding(
                    "parse-error",
                    src.path,
                    e.lineno or 1,
                    e.offset or 0,
                    f"cannot parse: {e.msg}",
                )
            )
    from dbscan_tpu.lint import callgraph as cg

    pkg.callgraph = cg.build(pkg)
    for fn in rule_fns:
        findings.extend(fn(pkg))
    findings = apply_suppressions(pkg, findings, known_rules, aliases)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
