"""graftlint/graftcheck command line: ``python -m dbscan_tpu.lint``.

Exit-code contract (pinned by tests/test_lint.py, gate-able in CI like
``obs.regress --check-schema``), IDENTICAL with and without
``--rules``/``--baseline``:

- **0** — clean: no findings after the ``--rules`` filter and the
  ``--baseline`` subtraction. With ``--baseline`` this means "no NEW
  findings": baselined ones are suppressed but re-counted in the
  summary line.
- **1** — findings (text mode prints one ``path:line:col: rule
  message`` per line; with ``--baseline``, only the new ones).
- **2** — usage/IO error: missing lint path, unreadable/invalid
  baseline file, or a ``--rules`` filter that matches no known rule
  (a typo'd glob silently gating nothing would be a broken CI gate).

``--rules GLOBS`` runs the full analysis but keeps only findings whose
rule id matches one of the comma-separated fnmatch globs (e.g.
``--rules 'race-*,collective-*'``) — how CI can gate new rule families
strictly while older ones are still being burned down.

``--baseline PATH`` subtracts previously recorded findings (matched on
rule + normalized path + message as a MULTISET — line numbers excluded
so unrelated edits don't resurrect them, occurrence-counted so a new
duplicate of a baselined finding still fails) and exits by the
remainder: the incremental-adoption gate. Create/refresh the file with ``--write-baseline PATH`` (writes
the CURRENT post-filter findings and exits 0).

Retired rule ids (``lint.ALIASES``, e.g. ``dtype-drift`` ->
``dtype-flow-drift``) stay valid everywhere a rule id appears: a glob
or baseline naming the old id matches the successor's findings, so
renaming a rule never silently un-gates a CI pipeline.

``--format sarif`` emits SARIF 2.1.0 (one run, one result per finding)
so the 0/1/2 exit contract can surface as inline annotations in CI
code-scanning UIs; exit semantics are identical to text/json.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import sys

from dbscan_tpu import lint as lint_mod

_BASELINE_VERSION = 1


def _norm_path(path: str) -> str:
    """Repo-portable finding path for baseline keys: relative to the
    cwd when underneath it, else absolute — so a baseline written by
    ``... dbscan_tpu/`` (relative findings) matches one consumed by a
    no-args run (absolute findings) from the same directory."""
    import os

    ap = os.path.abspath(path)
    rp = os.path.relpath(ap)
    return ap if rp.startswith("..") else rp


def _baseline_key(f) -> tuple:
    # line/col excluded deliberately: a baseline must survive unrelated
    # edits above the finding; rule+normalized path+message is stable.
    # Rule ids canonicalize through lint.ALIASES; rows RECORDED under a
    # retired id additionally match message-agnostically (_read_baseline
    # wildcards their message), since the successor's messages differ.
    return (lint_mod.canonical_rule(f.rule), _norm_path(f.path), f.message)


def _write_baseline(path: str, findings) -> None:
    import os

    payload = {
        "version": _BASELINE_VERSION,
        "findings": [
            {
                "rule": f.rule,
                "path": _norm_path(f.path),
                "message": f.message,
            }
            for f in findings
        ],
    }
    # tmp-then-replace: a run killed mid-write must not leave a
    # truncated baseline silently un-gating CI (atomic-write-violation
    # discipline — this CLI is linted by its own rule)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def _read_baseline(path: str) -> dict:
    """Baseline as a MULTISET (key -> count): one baselined occurrence
    must not suppress newly introduced duplicates of the same finding
    in the same file (their keys are identical by design — line numbers
    are excluded for edit-stability).

    Rows recorded under a RETIRED rule id (lint.ALIASES) key on
    rule+path with the message WILDCARDED: the successor rule emits
    different message text by design, so exact-message matching would
    resurrect every baselined old-rule finding the moment the rename
    ships. Rows under current ids keep the exact rule+path+message
    multiset semantics."""
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    if not isinstance(payload, dict) or "findings" not in payload:
        raise ValueError("not a graftlint baseline (missing 'findings')")
    out: dict = {}
    for row in payload["findings"]:
        retired = row["rule"] in lint_mod.ALIASES
        key = (
            lint_mod.canonical_rule(row["rule"]),
            _norm_path(row["path"]),
            None if retired else row["message"],
        )
        out[key] = out.get(key, 0) + 1
    return out


_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _sarif(findings, n_files) -> dict:
    """Findings as a SARIF 2.1.0 log: one run, the rule catalog limited
    to rules that actually fired (keeps the document small), one result
    per finding with a 1-based column region."""
    fired = sorted({f.rule for f in findings})
    return {
        "version": "2.1.0",
        "$schema": _SARIF_SCHEMA,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri":
                            "https://github.com/tpu-dbscan/tpu-dbscan",
                        "rules": [
                            {
                                "id": r,
                                "shortDescription": {
                                    "text": lint_mod.RULES.get(r, r)
                                },
                            }
                            for r in fired
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "warning",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {
                                        "uri": _norm_path(f.path)
                                    },
                                    "region": {
                                        "startLine": max(1, f.line),
                                        "startColumn": f.col + 1,
                                    },
                                }
                            }
                        ],
                    }
                    for f in findings
                ],
                "properties": {"filesScanned": n_files},
            }
        ],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.lint",
        description="graftlint/graftcheck: AST-based static analysis "
        "for TPU hazards (host-sync, recompile), declared-contract "
        "drift (telemetry schema, env-var registry), and "
        "concurrency/collective safety (races, collectives).",
        epilog="Exit codes: 0 clean (no new findings under --baseline), "
        "1 findings, 2 usage/IO error (bad path, unreadable baseline, "
        "or a --rules glob matching no known rule). The contract is "
        "identical with and without --rules/--baseline, so CI can gate "
        "on any combination.",
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the installed "
        "dbscan_tpu package)",
    )
    p.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default="text",
        help="output format (default text: path:line:col: rule "
        "message; sarif emits SARIF 2.1.0 for CI inline annotations)",
    )
    p.add_argument(
        "--rules",
        metavar="GLOBS",
        help="comma-separated fnmatch globs over rule ids; only "
        "matching findings count (e.g. 'race-*,collective-*'); a "
        "pattern matching no known rule is exit 2",
    )
    p.add_argument(
        "--baseline",
        metavar="PATH",
        help="subtract findings recorded in this baseline file "
        "(rule+path+message match); exit 0 means NO NEW findings; a "
        "missing/invalid file is exit 2",
    )
    p.add_argument(
        "--write-baseline",
        metavar="PATH",
        help="write the current (post --rules) findings to PATH as a "
        "baseline and exit 0",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    p.add_argument(
        "--env-table",
        action="store_true",
        help="print the PARITY.md env-var table generated from "
        "config.ENV_VARS and exit (paste it over the PARITY section "
        "when the registry changes)",
    )
    p.add_argument(
        "--shape-table",
        action="store_true",
        help="print the PARITY.md per-dispatch-family predicted-"
        "footprint table generated from lint/shapes.py FAMILY_MODELS "
        "and the live budget knobs, and exit",
    )
    p.add_argument(
        "--fault-table",
        action="store_true",
        help="print the PARITY.md fault-surface table generated from "
        "faults.SITES and the statically-resolved supervised "
        "consumptions/drills, and exit",
    )
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in sorted(lint_mod.RULES):
            print(f"{rule:<28} {lint_mod.RULES[rule]}")
        for alias in sorted(lint_mod.ALIASES):
            print(
                f"{alias:<28} (alias of {lint_mod.ALIASES[alias]})"
            )
        return 0
    if args.env_table:
        from dbscan_tpu.config import parity_env_table

        print(parity_env_table())
        return 0
    if args.shape_table:
        from dbscan_tpu.lint.shapes import shape_table

        print(shape_table())
        return 0
    if args.fault_table:
        from dbscan_tpu.lint.faultsurface import fault_table

        print(fault_table())
        return 0

    # a glob matches a rule through its current id OR a retired alias
    known_ids = set(lint_mod.RULES) | set(lint_mod.ALIASES)
    globs = None
    if args.rules:
        globs = [g.strip() for g in args.rules.split(",") if g.strip()]
        for g in globs:
            if not fnmatch.filter(known_ids, g):
                print(
                    f"graftlint: --rules glob {g!r} matches no known "
                    "rule (see --list-rules)",
                    file=sys.stderr,
                )
                return 2

    try:
        if args.paths:
            findings, n_files = lint_mod.lint_paths(args.paths)
        else:
            findings, n_files = lint_mod.lint_package()
    except FileNotFoundError as e:
        print(f"graftlint: no such path: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if globs is not None:
        # aliases of a finding's rule count as its names for matching
        def _names_of(rule: str):
            yield rule
            for alias, target in lint_mod.ALIASES.items():
                if target == rule:
                    yield alias

        findings = [
            f
            for f in findings
            if any(
                fnmatch.fnmatch(n, g)
                for g in globs
                for n in _names_of(f.rule)
            )
        ]

    if args.write_baseline:
        try:
            _write_baseline(args.write_baseline, findings)
        except OSError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        print(
            f"graftlint: baseline of {len(findings)} finding(s) "
            f"written to {args.write_baseline}",
            file=sys.stderr,
        )
        return 0

    n_baselined = 0
    if args.baseline:
        try:
            known = _read_baseline(args.baseline)
        except (
            OSError,
            ValueError,
            KeyError,
            TypeError,
            json.JSONDecodeError,
        ) as e:
            print(
                f"graftlint: cannot read baseline {args.baseline}: {e}",
                file=sys.stderr,
            )
            return 2
        kept = []
        for f in findings:
            key = _baseline_key(f)
            wild = (key[0], key[1], None)  # retired-id rows, see above
            if known.get(key, 0) > 0:
                known[key] -= 1
                n_baselined += 1
            elif known.get(wild, 0) > 0:
                known[wild] -= 1
                n_baselined += 1
            else:
                kept.append(f)
        findings = kept

    if args.format == "json":
        print(
            json.dumps(
                {
                    "files_scanned": n_files,
                    "baselined": n_baselined,
                    "findings": [f.to_dict() for f in findings],
                }
            )
        )
    elif args.format == "sarif":
        print(json.dumps(_sarif(findings, n_files)))
    else:
        for f in findings:
            print(f.render())
        extra = (
            f" ({n_baselined} baselined)" if args.baseline else ""
        )
        print(
            f"graftlint: {len(findings)} finding(s){extra} in "
            f"{n_files} file(s)",
            file=sys.stderr,
        )
    return 1 if findings else 0
