"""graftlint command line: ``python -m dbscan_tpu.lint``.

Exit-code contract (pinned by tests/test_lint.py, gate-able in CI like
``obs.regress --check-schema``): 0 = clean, 1 = findings (one rule id +
file:line per line in text mode), 2 = usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import sys

from dbscan_tpu import lint as lint_mod


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.lint",
        description="graftlint: AST-based static analysis for TPU "
        "hazards (host-sync, recompile) and declared-contract drift "
        "(telemetry schema, env-var registry).",
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the installed "
        "dbscan_tpu package)",
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (default text: path:line:col: rule message)",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    p.add_argument(
        "--env-table",
        action="store_true",
        help="print the PARITY.md env-var table generated from "
        "config.ENV_VARS and exit (paste it over the PARITY section "
        "when the registry changes)",
    )
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in sorted(lint_mod.RULES):
            print(f"{rule:<24} {lint_mod.RULES[rule]}")
        return 0
    if args.env_table:
        from dbscan_tpu.config import parity_env_table

        print(parity_env_table())
        return 0

    try:
        if args.paths:
            findings, n_files = lint_mod.lint_paths(args.paths)
        else:
            findings, n_files = lint_mod.lint_package()
    except FileNotFoundError as e:
        print(f"graftlint: no such path: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(
            json.dumps(
                {
                    "files_scanned": n_files,
                    "findings": [f.to_dict() for f in findings],
                }
            )
        )
    else:
        for f in findings:
            print(f.render())
        print(
            f"graftlint: {len(findings)} finding(s) in {n_files} file(s)",
            file=sys.stderr,
        )
    return 1 if findings else 0
