"""telemetry-schema rules: every emitted counter/gauge/span/event name
must be declared in :mod:`dbscan_tpu.obs.schema`.

The obs framework modules (``obs/__init__.py``, ``obs/trace.py``,
``obs/metrics.py``, ``obs/export.py``) forward caller-supplied names
and are exempt; everywhere else the linter resolves the name argument
of each emission call:

- string literal -> exact membership (``schema-counter`` /
  ``schema-gauge`` / ``schema-span`` / ``schema-event`` on a miss);
- f-string / ``"prefix" + expr`` -> the literal head must prefix some
  declared name of that kind (``schema-dynamic`` on a miss, also
  raised when there is no literal head at all);
- conditional expressions check both arms;
- ``tracked_call``/``note_compile`` family literals must be in
  ``schema.COMPILE_FAMILIES`` and ``obs.memory.sample`` site literals
  in ``schema.MEMORY_SITES`` (``schema-family``) — that is what makes
  the dynamic ``compiles.<family>`` / ``memory.at.<site>`` expansions
  exactly as pinned as the exact names.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from dbscan_tpu.lint.core import Finding, Package
from dbscan_tpu.obs import schema

_EXEMPT_SUFFIXES = (
    "obs/__init__.py",
    "obs/trace.py",
    "obs/metrics.py",
    "obs/export.py",
)

#: method name -> telemetry kind, guarded by the receiver check below
_OBS_METHODS = {
    "count": "counter",
    "timed_count": "counter",
    "gauge": "gauge",
    "span": "span",
    "add_span": "span",
    "event": "event",
}
_REGISTRY_METHODS = {
    "metrics": {"count": "counter", "gauge": "gauge"},
    "tracer": {"span": "span", "add_span": "span", "instant": "event"},
}
_MEMORY_RECEIVERS = ("obs_memory", "_obs_memory", "memory")


def _emission_kind(node: ast.Call) -> Optional[str]:
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    recv = f.value
    if isinstance(recv, ast.Name) and recv.id == "obs":
        return _OBS_METHODS.get(f.attr)
    if isinstance(recv, ast.Attribute):
        table = _REGISTRY_METHODS.get(recv.attr)
        if table is not None:
            return table.get(f.attr)
    return None


def _literal_or_prefix(expr: ast.AST) -> List[Tuple[Optional[str], bool]]:
    """Resolve a name expression to [(text, is_exact)] alternatives;
    ``(None, False)`` marks an unresolvable dynamic name."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [(expr.value, True)]
    if isinstance(expr, ast.JoinedStr):
        head = ""
        for part in expr.values:
            if isinstance(part, ast.Constant) and isinstance(
                part.value, str
            ):
                head += part.value
            else:
                break
        return [(head or None, False)]
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _literal_or_prefix(expr.left)
        if len(left) == 1 and left[0][0] is not None:
            return [(left[0][0], False)]
        return [(None, False)]
    if isinstance(expr, ast.IfExp):
        return _literal_or_prefix(expr.body) + _literal_or_prefix(
            expr.orelse
        )
    return [(None, False)]


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    for src in pkg.files:
        if src.tree is None:
            continue
        norm = src.path.replace("\\", "/")
        if any(norm.endswith(sfx) for sfx in _EXEMPT_SUFFIXES):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else None
            # compile-family and memory-site literal checks
            if attr in ("tracked_call", "note_compile") and node.args:
                for name, exact in _literal_or_prefix(node.args[0]):
                    if (
                        exact
                        and name not in schema.COMPILE_FAMILIES
                    ):
                        findings.append(
                            Finding(
                                "schema-family",
                                src.path,
                                node.lineno,
                                node.col_offset,
                                f"compile family {name!r} is not in "
                                "obs.schema.COMPILE_FAMILIES",
                            )
                        )
                continue
            if (
                attr == "sample"
                and isinstance(f.value, ast.Name)
                and f.value.id in _MEMORY_RECEIVERS
                and node.args
            ):
                for name, exact in _literal_or_prefix(node.args[0]):
                    if exact and name not in schema.MEMORY_SITES:
                        findings.append(
                            Finding(
                                "schema-family",
                                src.path,
                                node.lineno,
                                node.col_offset,
                                f"memory sample site {name!r} is not in "
                                "obs.schema.MEMORY_SITES",
                            )
                        )
                continue
            kind = _emission_kind(node)
            if kind is None or not node.args:
                continue
            for name, exact in _literal_or_prefix(node.args[0]):
                if exact:
                    if not schema.is_declared(kind, name):
                        findings.append(
                            Finding(
                                f"schema-{kind}",
                                src.path,
                                node.lineno,
                                node.col_offset,
                                f"{kind} name {name!r} is not declared in "
                                "dbscan_tpu/obs/schema.py — declare it "
                                "(with a doc line) or fix the emission",
                            )
                        )
                elif name is None:
                    findings.append(
                        Finding(
                            "schema-dynamic",
                            src.path,
                            node.lineno,
                            node.col_offset,
                            f"dynamic {kind} name with no literal head "
                            "cannot be checked against the schema; "
                            "anchor it with a literal prefix",
                        )
                    )
                elif not schema.prefix_declared(kind, name):
                    findings.append(
                        Finding(
                            "schema-dynamic",
                            src.path,
                            node.lineno,
                            node.col_offset,
                            f"dynamic {kind} name prefix {name!r} matches "
                            "no declared name in dbscan_tpu/obs/schema.py",
                        )
                    )
    return findings
