"""env-registry rules: every ``DBSCAN_*`` environment read goes through
the declared table in :mod:`dbscan_tpu.config`.

- ``env-direct-read``: ``os.environ.get``/``os.getenv``/
  ``os.environ[...]`` of a ``DBSCAN_*`` literal anywhere but
  ``config.py`` — route it through ``config.env`` so the name, type,
  default, and doc live in one place;
- ``env-undeclared``: a ``config.env("DBSCAN_X")`` call naming a
  variable missing from ``config.ENV_VARS`` — declaring the table row
  IS the registration;
- ``env-parity``: a declared variable whose generated table ROW
  (``| `NAME` | ...``) is missing from PARITY.md — a plain substring
  check would be satisfied by prose mentions or by longer names that
  contain this one (``DBSCAN_TRACE`` inside ``DBSCAN_TRACE_MAX_SPANS``),
  so the row marker is what's required (regenerate with
  ``python -m dbscan_tpu.lint --env-table``). Only checked when the
  linted set includes the real package (fixture runs in temp dirs
  skip it);
- ``env-tunable-undeclared``: a ``config.TUNABLES`` entry (the
  autotuner's declared search space, ``python -m dbscan_tpu.bench
  --tune``) naming a knob missing from ``ENV_VARS``, disagreeing with
  the declared row's type, or declaring an empty range — every knob
  the tuner may set must be a first-class registry row, so a tuned
  profile can never smuggle an undeclared/untyped variable into the
  process. Only checked when the linted set includes the real
  package, like ``env-parity``.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from dbscan_tpu.lint.core import Finding, Package

_ENV_FN_NAMES = ("env", "_env")
_CONFIG_RECEIVERS = ("config", "config_mod", "_config")


def _declared_names():
    from dbscan_tpu.config import ENV_VARS

    return ENV_VARS


def _environ_read_name(node: ast.AST) -> Optional[ast.AST]:
    """The name-argument expression of a direct environment read, or
    None when ``node`` is not one."""
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "getenv":
                return node.args[0] if node.args else None
            if f.attr == "get" and isinstance(f.value, ast.Attribute) and (
                f.value.attr == "environ"
            ):
                return node.args[0] if node.args else None
            if f.attr == "get" and isinstance(f.value, ast.Name) and (
                f.value.id == "environ"
            ):
                return node.args[0] if node.args else None
        elif isinstance(f, ast.Name) and f.id == "getenv":
            return node.args[0] if node.args else None
    elif isinstance(node, ast.Subscript) and isinstance(
        node.ctx, ast.Load
    ):
        # Load context only: os.environ["DBSCAN_X"] = ... is a WRITE —
        # setting a knob (drill CLIs, test harnesses) is not a registry
        # bypass, since the value is read back through config.env
        v = node.value
        is_environ = (
            isinstance(v, ast.Attribute) and v.attr == "environ"
        ) or (isinstance(v, ast.Name) and v.id == "environ")
        if is_environ:
            return node.slice
    return None


def _dbscan_literal(expr: Optional[ast.AST]) -> Optional[str]:
    if (
        isinstance(expr, ast.Constant)
        and isinstance(expr.value, str)
        and expr.value.startswith("DBSCAN")
    ):
        return expr.value
    return None


def _is_config_env_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in _ENV_FN_NAMES
    if isinstance(f, ast.Attribute) and f.attr in _ENV_FN_NAMES:
        return isinstance(f.value, ast.Name) and (
            f.value.id in _CONFIG_RECEIVERS
        )
    return False


def _find_parity(start_dirs) -> Optional[str]:
    for d in start_dirs:
        d = os.path.abspath(d)
        for _ in range(6):
            cand = os.path.join(d, "PARITY.md")
            if os.path.exists(cand):
                return cand
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return None


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    declared = _declared_names()
    lints_real_config = False
    for src in pkg.files:
        if src.tree is None:
            continue
        is_config = os.path.basename(src.path) == "config.py" and (
            "dbscan_tpu" in os.path.abspath(src.path).split(os.sep)
        )
        if is_config:
            lints_real_config = True
        for node in ast.walk(src.tree):
            name_expr = (
                _environ_read_name(node)
                if not is_config
                else None
            )
            name = _dbscan_literal(name_expr)
            if name is not None:
                findings.append(
                    Finding(
                        "env-direct-read",
                        src.path,
                        node.lineno,
                        node.col_offset,
                        f"direct environment read of {name!r}; route it "
                        "through dbscan_tpu.config.env so the knob is "
                        "declared once (name/type/default/doc)",
                    )
                )
                continue
            if isinstance(node, ast.Call) and _is_config_env_call(node):
                name = _dbscan_literal(node.args[0] if node.args else None)
                if name is not None and name not in declared:
                    findings.append(
                        Finding(
                            "env-undeclared",
                            src.path,
                            node.lineno,
                            node.col_offset,
                            f"{name!r} is not declared in "
                            "config.ENV_VARS — add the table row (and "
                            "its PARITY.md line)",
                        )
                    )
    if lints_real_config:
        from dbscan_tpu.config import TUNABLES

        config_path = next(
            f.path
            for f in pkg.files
            if os.path.basename(f.path) == "config.py"
        )
        for t in TUNABLES:
            spec = declared.get(t.name)
            if spec is None:
                findings.append(
                    Finding(
                        "env-tunable-undeclared",
                        config_path,
                        1,
                        0,
                        f"Tunable {t.name!r} is not declared in "
                        "config.ENV_VARS — the tuner's search space "
                        "and the env registry must be the same "
                        "surface (add the table row first)",
                    )
                )
                continue
            if spec.kind != t.kind:
                findings.append(
                    Finding(
                        "env-tunable-undeclared",
                        config_path,
                        1,
                        0,
                        f"Tunable {t.name!r} declares kind "
                        f"{t.kind!r} but the ENV_VARS row says "
                        f"{spec.kind!r} — a tuned profile would "
                        "write values the typed reader rejects",
                    )
                )
            if not t.choices:
                findings.append(
                    Finding(
                        "env-tunable-undeclared",
                        config_path,
                        1,
                        0,
                        f"Tunable {t.name!r} declares an empty "
                        "choice range — the successive-halving "
                        "search has nothing to explore; declare the "
                        "typed range/steps next to the ENV_VARS row",
                    )
                )
        parity = _find_parity(
            [os.path.dirname(f.path) for f in pkg.files]
        )
        if parity is not None:
            with open(parity, encoding="utf-8") as f:
                text = f.read()
            for name in sorted(declared):
                if f"| `{name}` |" not in text:
                    findings.append(
                        Finding(
                            "env-parity",
                            parity,
                            1,
                            0,
                            f"declared env var {name!r} has no table row "
                            "in PARITY.md — regenerate the table with "
                            "python -m dbscan_tpu.lint --env-table",
                        )
                    )
    return findings
