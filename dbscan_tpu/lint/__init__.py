"""graftlint + graftcheck: AST-based static analysis for TPU hazards,
telemetry contracts, concurrency/collective safety, and the fault
surface.

Eight rule families over the package source (no execution of the linted
code; the schema/env cross-checks import the DECLARED registries —
:mod:`dbscan_tpu.obs.schema` and ``config.ENV_VARS`` — not the linted
files)::

    python -m dbscan_tpu.lint [--format text|json] [--rules GLOBS]
                              [--baseline PATH] [paths...]

- **host-sync** (``host-sync-item`` / ``host-sync-cast`` /
  ``host-sync-asarray``): implicit device->host syncs in functions
  reachable from a jit site (lint/callgraph.py builds the trace-time
  call graph);
- **recompile** (``jit-in-loop`` / ``jit-scalar-arg``): patterns that
  mint fresh jit signatures;
- **telemetry-schema** (``schema-counter`` / ``schema-gauge`` /
  ``schema-span`` / ``schema-event`` / ``schema-dynamic`` /
  ``schema-family``): every emitted telemetry name must be declared in
  ``obs/schema.py``;
- **env-registry** (``env-direct-read`` / ``env-undeclared`` /
  ``env-parity``): every ``DBSCAN_*`` read goes through
  ``config.env`` against the declared table, which PARITY.md mirrors;
- **races** (``race-unlocked-shared`` / ``race-lock-order`` /
  ``race-sync-under-lock`` — graftcheck, lint/races.py): shared-state
  discipline on the PullEngine worker slice (lint/callgraph.py's
  ``walk_worker``), the whole-repo lock-acquisition-order graph, and
  device syncs under locks — validated at runtime by the opt-in thread
  sanitizer (``DBSCAN_TSAN=1``, lint/tsan.py);
- **collectives** (``collective-in-branch`` /
  ``collective-axis-undeclared`` / ``pull-in-collective`` — graftcheck,
  lint/collectives.py): divergence/axis/pull hazards inside
  ``shard_map``/``pjit`` bodies, gating the multichip scale-out work;
- **shapes** (``shape-mismatch`` / ``shape-unratcheted-dim`` /
  ``dtype-flow-drift`` / ``hbm-over-budget`` / ``shard-indivisible`` —
  graftshape, lint/shapes.py over the lint/absint.py symbolic
  interpreter): provable shape conflicts, data-dependent dims entering
  jit without a ratchet, explicit-f64 value flow into kernels
  (supersedes ``dtype-drift`` — kept as an alias, :data:`ALIASES`),
  and the per-dispatch-family HBM envelope / shard-divisibility gates
  — validated at runtime by the opt-in shape cross-check
  (``DBSCAN_SHAPECHECK=1``, lint/shapecheck.py);
- **fault surface** (``fault-retry-unsafe`` /
  ``fault-site-undeclared`` / ``fault-site-undrilled`` /
  ``fault-degrade-unreachable`` / ``atomic-write-violation`` —
  graftfault, lint/faultsurface.py over the lint/effects.py
  effect-purity interpreter): supervised callables that mutate
  caller-visible state before their success point, ``supervised(...)``
  site tokens missing from the declared ``faults.SITES`` registry or
  lacking a ``DBSCAN_FAULT_SPEC`` drill in tests/, degrade ladders
  unreachable from their call sites, and persistence writes without
  the write-tmp-then-``os.replace`` idiom — validated at runtime by
  the opt-in mutation-fingerprint cross-check (``DBSCAN_FAULTCHECK=1``,
  lint/faultcheck.py).

Suppress a finding on its line with a REQUIRED reason::

    x = arr.item()  # graftlint: disable=host-sync-item  single scalar at run end

Exit codes: 0 clean, 1 findings, 2 usage/IO error — the same contract
``tests/test_lint.py`` pins and CI gates on.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from dbscan_tpu.lint.core import (  # noqa: F401
    Finding,
    Package,
    load_package,
    run_rules,
)

#: rule id -> one-line description (the --list-rules catalog)
RULES = {
    "host-sync-item": ".item() in jit-reachable code (device->host sync)",
    "host-sync-cast": "float()/int()/bool() on an array expression in "
    "jit-reachable code",
    "host-sync-asarray": "np.asarray/np.array on a traced array in "
    "jit-reachable code",
    "jit-in-loop": "jax.jit(...) constructed inside a loop body",
    "jit-scalar-arg": "Python scalar/tuple literal passed positionally "
    "to a jit with no statics",
    "schema-counter": "emitted counter name not declared in obs/schema.py",
    "schema-gauge": "emitted gauge name not declared in obs/schema.py",
    "schema-span": "emitted span name not declared in obs/schema.py",
    "schema-event": "emitted event name not declared in obs/schema.py",
    "schema-dynamic": "dynamic telemetry name whose literal prefix "
    "matches nothing declared",
    "schema-family": "compile family / memory site literal not in the "
    "schema generator sets",
    "env-direct-read": "os.environ read of a DBSCAN_* name outside "
    "config.py",
    "env-undeclared": "config.env() of a name missing from "
    "config.ENV_VARS",
    "env-parity": "declared env var missing from PARITY.md",
    "env-tunable-undeclared": "config.TUNABLES knob missing from "
    "ENV_VARS, type-mismatched, or range-less (the autotuner search "
    "space must be a declared registry surface)",
    "race-unlocked-shared": "unlocked write to shared state from the "
    "pull-engine worker slice",
    "race-lock-order": "lock-acquisition-order cycle (or non-reentrant "
    "self-reacquire) in the whole-repo lock graph",
    "race-sync-under-lock": "blocking device sync while holding a lock",
    "collective-in-branch": "collective under a divergence-capable "
    "conditional inside a shard_map/pjit body",
    "collective-axis-undeclared": "collective axis name not declared by "
    "any Mesh in the linted set",
    "pull-in-collective": "host pull reachable from a shard_map/pjit "
    "collective region",
    "shape-mismatch": "provable broadcast/concat/reshape/dot shape "
    "conflict under symbolic dims",
    "shape-unratcheted-dim": "data-dependent leading dim enters a jit "
    "boundary without a shape ratchet",
    "dtype-flow-drift": "explicit float64 reaches device code in "
    "kernel files via value flow (supersedes dtype-drift)",
    "hbm-over-budget": "worst-case dispatch footprint exceeds the "
    "device HBM budget under the declared knobs",
    "shard-indivisible": "shard_map input dim not divisible by its "
    "mesh axis size",
    "fault-retry-unsafe": "supervised callable mutates caller-visible "
    "state before its success point (a retry double-applies it)",
    "fault-site-undeclared": "supervised()/next_ordinal() site token "
    "not declared in faults.SITES",
    "fault-site-undrilled": "declared fault site consumed in product "
    "code with no DBSCAN_FAULT_SPEC drill in tests/",
    "fault-degrade-unreachable": "supervised call reaching none of its "
    "site's declared degrade handler modes",
    "atomic-write-violation": "file opened for writing without the "
    "write-tmp-then-os.replace idiom (append mode exempt)",
    "suppress-no-reason": "graftlint suppression without a reason text",
    "suppress-unknown-rule": "graftlint suppression naming an unknown "
    "rule id",
    "parse-error": "file does not parse",
}

#: retired rule id -> its successor. An alias keeps old ``--rules``
#: globs, baselines, and suppressions working: findings are emitted
#: under the CANONICAL (new) id, but a glob/baseline/suppression
#: naming the alias matches them too (cli.py / core.py consult this).
ALIASES = {
    # dtype-drift was the literal-only scan (PR 4); dtype-flow-drift is
    # its flow-based superset (lint/shapes.py, this PR)
    "dtype-drift": "dtype-flow-drift",
}


def canonical_rule(rule: str) -> str:
    """Resolve a (possibly retired) rule id to its current one."""
    return ALIASES.get(rule, rule)


def _rule_fns():
    from dbscan_tpu.lint import (
        collectives,
        envvars,
        faultsurface,
        hostsync,
        races,
        recompile,
        shapes,
        telemetry,
    )

    return (
        hostsync.check,
        recompile.check,
        telemetry.check,
        envvars.check,
        races.check,
        collectives.check,
        shapes.check,
        faultsurface.check,
    )


def lint_paths(paths: Iterable[str]) -> Tuple[List[Finding], int]:
    """Lint files/directories; returns (findings, files_scanned)."""
    pkg = load_package(paths)
    findings = run_rules(pkg, _rule_fns(), RULES, ALIASES)
    # drop exact duplicates (a nested reachable function is visited via
    # its parent's body walk too)
    seen = set()
    uniq = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq, len(pkg.files)


def lint_package() -> Tuple[List[Finding], int]:
    """Lint the installed dbscan_tpu package directory."""
    import os

    import dbscan_tpu

    return lint_paths([os.path.dirname(os.path.abspath(dbscan_tpu.__file__))])
