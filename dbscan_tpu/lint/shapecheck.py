"""graftshape runtime cross-check: validate the static shape/HBM model
against a real run.

The static rules (``lint/shapes.py``) reason about shapes symbolically;
this module watches the same contract AT RUNTIME so the two check each
other: when ``DBSCAN_SHAPECHECK=1`` (or a test calls :func:`enable`),
every ``obs/compile.py::tracked_call`` dispatch records its concrete
argument shapes/dtypes and asserts

- **model instantiation**: the observed shapes unify with the family's
  declared symbolic model (``shapes.FAMILY_MODELS``) — rank, dim
  bindings consistent across arguments (the same ``P`` everywhere),
  dtype classes, and the declared constraints (``B == 512*NB`` shard-
  block division). A dispatch whose real shapes the model cannot
  explain is a violation: either the kernel changed (update the model
  — that IS the registration step) or a shape bug shipped;
- **HBM containment**: on backends with allocator stats (TPU/GPU), the
  per-call growth of ``bytes_in_use`` across the dispatch must stay
  within the model's predicted footprint (exact input bytes + the
  family's symbolic overhead evaluated at the observed dims). On
  stat-less backends (CPU) the memory half degrades to a no-op, the
  shape half still runs — which is what the tier-1 suite exercises.

Overhead contract (same discipline as tsan/obs): the DISABLED path is
one module-global truthiness check per dispatch; enabling costs a pure-
Python unification per tracked call (microseconds against millisecond-
scale dispatches) plus, where available, two allocator-stat probes.

Reports: :func:`report` (dict), :func:`assert_clean` (raises on any
violation), :func:`predicted_peak` (the static envelope bench.py turns
into the ``hbm_pred_ratio`` gate), and — under
``DBSCAN_SHAPECHECK_REPORT=path`` — an atexit JSON dump, which is how
the tier-1 rerun of the distributed + streaming suites asserts an
empty violation report from outside the process. :func:`emit_telemetry`
publishes the declared ``shapecheck.*`` counters/events when obs is
enabled.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import List, Optional, Tuple

from dbscan_tpu import config
from dbscan_tpu.lint import shapes

_rt: Optional["ShapecheckRuntime"] = None


def spec_of(x):
    """Observed spec of one dispatch argument: ``(shape, dtype)`` for
    arrays, a list of specs for tuples/lists (the postpass chunk-group
    idiom), ``("scalar", type name)`` markers otherwise."""
    if isinstance(x, (tuple, list)):
        return [spec_of(el) for el in x]
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        from dbscan_tpu.lint.absint import dtype_name

        return (tuple(int(d) for d in shape), dtype_name(str(dtype)))
    return ("scalar", type(x).__name__)


def _bytes_in_use() -> Optional[int]:
    """Summed live allocator bytes, or None on stat-less backends.
    Routed through obs/memory's probe (which latches availability, so
    CPU pays one probe per process)."""
    from dbscan_tpu.obs import memory as obs_memory

    if not obs_memory.available():
        return None
    stats = obs_memory.device_memory_stats()
    if not stats:
        return None
    return sum(int(s.get("bytes_in_use", 0)) for s in stats.values())


class ShapecheckRuntime:
    """Process-global cross-check state (see module docstring)."""

    def __init__(self):
        # a raw lock on purpose (like tsan's _mu): the runtime is
        # itself diagnostic machinery, invisible to the sanitizer
        self._mu = threading.Lock()
        self.checks = 0
        self.violations: List[dict] = []
        self.sites: dict = {}  # family -> per-site record
        self._pred_peak: Optional[int] = None
        #: max bytes_in_use observed at THIS runtime's dispatch-boundary
        #: probes — per-run by construction (a fresh runtime resets it),
        #: unlike the allocator's process-monotone peak_bytes_in_use,
        #: so bench's observed/predicted ratio compares like with like
        self._obs_peak: Optional[int] = None
        # telemetry watermark: emit_telemetry publishes deltas
        self._emitted = {"checks": 0, "violations": 0}

    # --- per-dispatch hooks --------------------------------------------

    def observe_call(self, family: str, args: Tuple) -> dict:
        """Pre-call hook: validate shapes against the static model and
        snapshot memory. Returns the handle :meth:`settle_call` takes."""
        specs = [spec_of(a) for a in args]
        subst, problems = shapes.validate_args(family, specs)
        model = shapes.FAMILY_MODELS.get(family)
        predicted = None
        if model is not None and not problems:
            exact_in = self._exact_bytes(specs)
            overhead = model.overhead_bytes(subst)
            if exact_in is not None and overhead is not None:
                predicted = exact_in + overhead
        pre = _bytes_in_use()
        with self._mu:
            self.checks += 1
            rec = self.sites.setdefault(
                family,
                {"calls": 0, "violations": 0, "shapes": [],
                 "predicted_bytes_max": None, "observed_delta_max": None},
            )
            rec["calls"] += 1
            sig = json.dumps(specs, default=str)
            if sig not in rec["shapes"] and len(rec["shapes"]) < 8:
                rec["shapes"].append(sig)
            if pre is not None:
                if self._obs_peak is None or pre > self._obs_peak:
                    self._obs_peak = pre
            if predicted is not None:
                rec["predicted_bytes_max"] = max(
                    rec["predicted_bytes_max"] or 0, predicted
                )
                if pre is not None:
                    peak = pre + predicted
                    if self._pred_peak is None or peak > self._pred_peak:
                        self._pred_peak = peak
            for p in problems:
                rec["violations"] += 1
                self.violations.append(
                    {"kind": "shape-model", "family": family,
                     "detail": p, "subst": dict(subst)}
                )
        if problems:
            _emit_violations(family, problems)
        return {"family": family, "pre": pre, "predicted": predicted}

    @staticmethod
    def _exact_bytes(specs) -> Optional[int]:
        from dbscan_tpu.lint.absint import DTYPE_BYTES

        total = 0
        for s in specs:
            if isinstance(s, list):
                sub = ShapecheckRuntime._exact_bytes(s)
                if sub is None:
                    return None
                total += sub
            elif isinstance(s, tuple) and len(s) == 2 and isinstance(
                s[0], tuple
            ):
                shape, dtype = s
                size = DTYPE_BYTES.get(dtype or "", None)
                if size is None:
                    return None
                n = size
                for d in shape:
                    n *= int(d)
                total += n
            # scalar markers cost nothing
        return total

    def settle_call(self, handle: dict) -> None:
        """Post-call hook: the allocator growth across the dispatch
        must stay within the predicted footprint (skipped where stats
        or a prediction are unavailable)."""
        pre = handle.get("pre")
        predicted = handle.get("predicted")
        if pre is None:
            return
        post = _bytes_in_use()
        if post is None:
            return
        delta = post - pre
        family = handle["family"]
        with self._mu:
            if self._obs_peak is None or post > self._obs_peak:
                self._obs_peak = post
            rec = self.sites.get(family)
            if rec is not None:
                rec["observed_delta_max"] = max(
                    rec["observed_delta_max"] or 0, delta
                )
            over = (
                rec is not None
                and predicted is not None
                and delta > predicted
            )
            if over:
                rec["violations"] += 1
                self.violations.append(
                    {
                        "kind": "hbm-over-prediction",
                        "family": family,
                        "detail": (
                            f"allocator grew {delta} bytes across the "
                            f"dispatch, static prediction {predicted}"
                        ),
                        "observed_delta": delta,
                        "predicted": predicted,
                    }
                )
        if over:
            _emit_violations(
                family,
                [f"observed HBM delta {delta} > predicted {predicted}"],
            )

    # --- reporting ------------------------------------------------------

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "enabled": True,
                "checks": self.checks,
                "sites": {
                    fam: dict(rec) for fam, rec in sorted(self.sites.items())
                },
                "violations": list(self.violations),
                "predicted_peak_bytes": self._pred_peak,
                "observed_peak_bytes": self._obs_peak,
            }


def _empty_report() -> dict:
    return {
        "enabled": False,
        "checks": 0,
        "sites": {},
        "violations": [],
        "predicted_peak_bytes": None,
        "observed_peak_bytes": None,
    }


def _emit_violations(family: str, problems: List[str]) -> None:
    """Publish violation events immediately when obs is live (counters
    ride :func:`emit_telemetry` deltas so totals stay exact)."""
    from dbscan_tpu import obs

    if not obs.active():
        return
    for p in problems:
        obs.event("shapecheck.violation", family=family, detail=p)


# --- public API --------------------------------------------------------


def runtime() -> Optional[ShapecheckRuntime]:
    """The live runtime, or None when disabled — the ONE check
    tracked_call pays on the disabled path."""
    return _rt


def enabled() -> bool:
    return _rt is not None


def enable() -> ShapecheckRuntime:
    """Turn the cross-check on (idempotent); returns the runtime."""
    global _rt
    if _rt is None:
        _rt = ShapecheckRuntime()
    return _rt


def disable() -> None:
    global _rt
    _rt = None


def reset() -> None:
    """Fresh runtime if enabled (drop recorded state, keep recording)."""
    global _rt
    if _rt is not None:
        _rt = ShapecheckRuntime()


def report() -> dict:
    """The current cross-check report (a disabled checker reports
    ``enabled: False`` with empty tables)."""
    rt = _rt
    if rt is None:
        return _empty_report()
    return rt.snapshot()


def assert_clean() -> None:
    """Raise AssertionError when the run recorded any model or HBM
    violation (the test-suite gate)."""
    rep = report()
    if rep["violations"]:
        raise AssertionError(
            f"shapecheck found {len(rep['violations'])} violation(s): "
            + json.dumps(rep["violations"], indent=2, default=str)
        )


def predicted_peak() -> Optional[int]:
    """Max over observed dispatches of (pre-dispatch occupancy + the
    static footprint prediction): the envelope observed HBM peaks are
    gated against (bench.py's ``hbm_pred_ratio``). None without
    allocator stats (CPU) or before the first tracked dispatch."""
    rt = _rt
    if rt is None:
        return None
    with rt._mu:
        return rt._pred_peak


def observed_peak() -> Optional[int]:
    """Max ``bytes_in_use`` sampled at THIS runtime's dispatch-boundary
    probes — the observed half of ``hbm_pred_ratio``. Deliberately NOT
    the allocator's ``peak_bytes_in_use``: that figure is process-
    monotone (PR 3), so a second bench run in the same process would
    inherit the first run's peak and spuriously break the <= 1.0 cap;
    this one resets with the runtime and samples exactly where the
    predictions apply."""
    rt = _rt
    if rt is None:
        return None
    with rt._mu:
        return rt._obs_peak


def emit_telemetry() -> None:
    """Publish the declared ``shapecheck.*`` counters (no-op unless
    both the checker and obs are enabled). Emits DELTAS since the last
    call, so periodic publication never double-counts."""
    rt = _rt
    if rt is None:
        return
    from dbscan_tpu import obs

    if not obs.active():
        return
    with rt._mu:
        checks, nviol = rt.checks, len(rt.violations)
        done = dict(rt._emitted)
        rt._emitted = {"checks": checks, "violations": nviol}
    obs.count("shapecheck.checks", checks - done["checks"])
    obs.count("shapecheck.violations", nviol - done["violations"])


def write_report(path: str) -> str:
    """Write the JSON report atomically; returns the path. Publishes
    pending ``shapecheck.*`` telemetry deltas first (the one product
    call site — the ``DBSCAN_SHAPECHECK_REPORT`` atexit hook)."""
    emit_telemetry()
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=1, sort_keys=True, default=str)
    os.replace(tmp, path)
    return path


def _env_init() -> None:
    """Activate from the environment at import: ``DBSCAN_SHAPECHECK=1``
    turns recording on; ``DBSCAN_SHAPECHECK_REPORT=path`` additionally
    dumps the JSON report at process exit (how the tier-1 subprocess
    rerun of the distributed/streaming suites is asserted clean from
    outside)."""
    if config.env("DBSCAN_SHAPECHECK"):
        enable()
        path = config.env("DBSCAN_SHAPECHECK_REPORT")
        if path:
            atexit.register(write_report, path)


_env_init()
