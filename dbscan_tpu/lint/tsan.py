"""graftcheck runtime thread sanitizer: validate the static race model
against a real run.

The static rules (``lint/races.py``) reason about locks lexically; this
module watches the same discipline AT RUNTIME, so the two check each
other: the repo's shared-state sites register their locks through
:func:`lock`/:func:`rlock`/:func:`condition` and mark their guarded
accesses with :func:`access`, and when ``DBSCAN_TSAN=1`` (or a test
calls :func:`enable`) the sanitizer records

- **per-site locksets** (Eraser-style): the intersection of locks held
  across every access to a site. A site touched by two threads whose
  lockset intersection is empty, with at least one write, is a race —
  including a caller that broke the ``_locked``-suffix convention the
  static rule trusts;
- **lock-acquisition order**: an edge A->B whenever B is acquired with
  A held; observing both A->B and B->A is a lock-order inversion (the
  dynamic twin of ``race-lock-order``);
- **cross-thread access maps**: which thread roles touched which site —
  ``tests/test_tsan.py`` asserts the pull worker's observed set is
  contained in the static worker-slice model
  (``lint.races.worker_tsan_sites``), so model drift fails tier-1.

Overhead contract: the DISABLED path is one module-global truthiness
check per hook (same discipline as ``dbscan_tpu.obs``); the lock
wrappers delegate to real ``threading`` primitives and never allocate
when disabled. The wrappers are installed unconditionally (they cost a
Python-level indirection only on paths that already take a lock), so
:func:`enable` works mid-process — locks constructed before enable
still record.

Ownership-transfer state (PullJob results, chunk record dicts) is
deliberately NOT tsan-monitored: its safety argument is the job
completion event's happens-before edge, not a lock, and a lockset
checker would mis-flag it. PARITY.md documents that contract.

Reports: :func:`report` (dict), :func:`assert_clean` (raises on
races/inversions), and — under ``DBSCAN_TSAN_REPORT=path`` — an atexit
JSON dump, which is how the tier-1 rerun of the pipeline/fault suites
asserts an empty race report from outside the process. :func:`
emit_telemetry` publishes the declared ``tsan.*`` counters/events when
obs is enabled.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Optional

from dbscan_tpu import config
from dbscan_tpu.lint import faultcheck as _faultcheck

_rt: Optional["TsanRuntime"] = None


class TsanRuntime:
    """Process-global sanitizer state (see module docstring)."""

    def __init__(self):
        self._mu = threading.Lock()  # raw: invisible to itself
        self._tls = threading.local()  # per-thread held-lock stack
        self.accesses: dict = {}  # site -> record
        self.edges: dict = {}  # (a, b) -> count
        self.races: list = []
        self.inversions: list = []
        self.acquires = 0
        self.naccesses = 0
        # already-published telemetry watermark (emit_telemetry emits
        # deltas, so periodic publication never double-counts)
        self._emitted = {"accesses": 0, "acquires": 0, "races": 0,
                         "inversions": 0}

    # --- per-thread held stack ----------------------------------------

    def _held(self) -> list:
        st = getattr(self._tls, "held", None)
        if st is None:
            st = []
            self._tls.held = st
        return st

    # --- lock hooks ----------------------------------------------------

    def note_acquire(self, site: str) -> None:
        held = self._held()
        tname = threading.current_thread().name
        with self._mu:
            self.acquires += 1
            for h in held:
                if h == site:
                    continue  # reentrant re-acquire of the same site
                edge = (h, site)
                if edge not in self.edges and (site, h) in self.edges:
                    self.inversions.append(
                        {
                            "locks": sorted((h, site)),
                            "thread": tname,
                            "order_here": [h, site],
                        }
                    )
                self.edges[edge] = self.edges.get(edge, 0) + 1
        held.append(site)

    def note_release(self, site: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    # --- shared-state access hooks ------------------------------------

    def note_access(self, site: str, write: bool) -> None:
        held = frozenset(self._held())
        tname = threading.current_thread().name
        with self._mu:
            self.naccesses += 1
            rec = self.accesses.get(site)
            if rec is None:
                rec = {
                    "threads": set(),
                    "lockset": None,  # None until the first access
                    "writes": 0,
                    "reads": 0,
                    "raced": False,
                }
                self.accesses[site] = rec
            rec["threads"].add(tname)
            rec["writes" if write else "reads"] += 1
            if rec["lockset"] is None:
                rec["lockset"] = set(held)
            else:
                rec["lockset"] &= held
            if (
                not rec["raced"]
                and len(rec["threads"]) > 1
                and not rec["lockset"]
                and rec["writes"] > 0
            ):
                rec["raced"] = True
                self.races.append(
                    {
                        "site": site,
                        "threads": sorted(rec["threads"]),
                        "writes": rec["writes"],
                        "reads": rec["reads"],
                    }
                )

    # --- reporting -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "enabled": True,
                "accesses": {
                    site: {
                        "threads": sorted(rec["threads"]),
                        "lockset": sorted(rec["lockset"] or ()),
                        "writes": rec["writes"],
                        "reads": rec["reads"],
                    }
                    for site, rec in sorted(self.accesses.items())
                },
                "order_edges": [
                    {"from": a, "to": b, "count": n}
                    for (a, b), n in sorted(self.edges.items())
                ],
                "races": list(self.races),
                "lock_inversions": list(self.inversions),
                "acquires": self.acquires,
                "naccesses": self.naccesses,
            }


def _empty_report() -> dict:
    # built fresh per call: a caller mutating its report (aggregation)
    # must never corrupt the disabled-path constant
    return {
        "enabled": False,
        "accesses": {},
        "order_edges": [],
        "races": [],
        "lock_inversions": [],
        "acquires": 0,
        "naccesses": 0,
    }


# --- lock wrappers -----------------------------------------------------


class TsanLock:
    """Recording wrapper over a ``threading`` lock. Delegation only —
    one ``_rt`` truthiness check per operation when disabled."""

    __slots__ = ("site", "_lk")

    def __init__(self, site: str, lk):
        self.site = site
        self._lk = lk

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            rt = _rt
            if rt is not None:
                rt.note_acquire(self.site)
        return ok

    def release(self) -> None:
        rt = _rt
        if rt is not None:
            rt.note_release(self.site)
        self._lk.release()

    def locked(self) -> bool:
        probe = getattr(self._lk, "locked", None)
        if probe is not None:
            return probe()
        # RLock has no locked() before Python 3.12; _is_owned is the
        # stdlib-internal equivalent threading.Condition itself uses
        return self._lk._is_owned()

    def __enter__(self) -> "TsanLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()


class TsanCondition:
    """Recording wrapper over ``threading.Condition``. ``wait`` releases
    the lock, so the held-stack mirrors that (release on wait entry,
    re-acquire on wake)."""

    __slots__ = ("site", "_cond")

    def __init__(self, site: str):
        self.site = site
        self._cond = threading.Condition()

    def __enter__(self) -> "TsanCondition":
        self._cond.__enter__()
        rt = _rt
        if rt is not None:
            rt.note_acquire(self.site)
        return self

    def __exit__(self, exc_type, exc, tb):
        rt = _rt
        if rt is not None:
            rt.note_release(self.site)
        return self._cond.__exit__(exc_type, exc, tb)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._cond.acquire(blocking, timeout)
        if ok:
            rt = _rt
            if rt is not None:
                rt.note_acquire(self.site)
        return ok

    def release(self) -> None:
        rt = _rt
        if rt is not None:
            rt.note_release(self.site)
        self._cond.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        rt = _rt
        if rt is not None:
            rt.note_release(self.site)
        try:
            return self._cond.wait(timeout)
        finally:
            rt = _rt
            if rt is not None:
                rt.note_acquire(self.site)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        rt = _rt
        if rt is not None:
            rt.note_release(self.site)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            rt = _rt
            if rt is not None:
                rt.note_acquire(self.site)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# --- public API --------------------------------------------------------


def lock(site: str) -> TsanLock:
    """A (non-reentrant) lock registered under ``site``."""
    return TsanLock(site, threading.Lock())


def rlock(site: str) -> TsanLock:
    """A reentrant lock registered under ``site``."""
    return TsanLock(site, threading.RLock())


def condition(site: str) -> TsanCondition:
    """A condition variable registered under ``site``."""
    return TsanCondition(site)


def access(site: str, write: bool = True) -> None:
    """Mark one access to the shared state behind ``site`` — call it
    INSIDE the locked region so the recorded lockset carries the guard.
    One truthiness check (per checker) when the sanitizers are off.
    Writes also feed the graftfault cross-check's per-supervised-window
    mutation fingerprint (lint/faultcheck.py)."""
    rt = _rt
    if rt is not None:
        rt.note_access(site, write)
    if write and _faultcheck._rt is not None:
        _faultcheck.note_access(site)


def enabled() -> bool:
    return _rt is not None


def enable() -> TsanRuntime:
    """Turn the sanitizer on (idempotent); returns the runtime."""
    global _rt
    if _rt is None:
        _rt = TsanRuntime()
    return _rt


def disable() -> None:
    global _rt
    _rt = None


def reset() -> None:
    """Fresh runtime if enabled (drop recorded state, keep recording)."""
    global _rt
    if _rt is not None:
        _rt = TsanRuntime()


def report() -> dict:
    """The current sanitizer report (a disabled sanitizer reports
    ``enabled: False`` with empty tables)."""
    rt = _rt
    if rt is None:
        return _empty_report()
    return rt.snapshot()


def assert_clean() -> None:
    """Raise AssertionError when the run recorded any race or
    lock-order inversion (the test-suite gate)."""
    rep = report()
    problems = rep["races"] + rep["lock_inversions"]
    if problems:
        raise AssertionError(
            "thread sanitizer found "
            f"{len(rep['races'])} race(s) and "
            f"{len(rep['lock_inversions'])} lock inversion(s): "
            + json.dumps(problems, indent=2)
        )


def worker_sites(thread_prefix: str = "dbscan-pull") -> set:
    """Sites touched by pull-engine worker threads in the live run —
    the observed half of the static-model containment test."""
    rep = report()
    return {
        site
        for site, rec in rep["accesses"].items()
        if any(t.startswith(thread_prefix) for t in rec["threads"])
    }


def emit_telemetry() -> None:
    """Publish the declared ``tsan.*`` counters/events (no-op unless
    both the sanitizer and obs are enabled). Emits DELTAS since the
    last call, so periodic publication from a long-lived harness never
    double-counts and never re-emits a race/inversion event."""
    rt = _rt
    if rt is None:
        return
    from dbscan_tpu import obs

    if not obs.active():
        return
    rep = rt.snapshot()
    with rt._mu:
        done = dict(rt._emitted)
        rt._emitted = {
            "accesses": rep["naccesses"],
            "acquires": rep["acquires"],
            "races": len(rep["races"]),
            "inversions": len(rep["lock_inversions"]),
        }
    obs.count("tsan.accesses", rep["naccesses"] - done["accesses"])
    obs.count("tsan.acquires", rep["acquires"] - done["acquires"])
    obs.count("tsan.races", len(rep["races"]) - done["races"])
    obs.count(
        "tsan.lock_inversions",
        len(rep["lock_inversions"]) - done["inversions"],
    )
    for r in rep["races"][done["races"]:]:
        obs.event("tsan.race", site=r["site"], threads=",".join(r["threads"]))
    for inv in rep["lock_inversions"][done["inversions"]:]:
        obs.event("tsan.lock_inversion", locks=",".join(inv["locks"]))


def write_report(path: str) -> str:
    """Write the JSON report atomically; returns the path. Also
    publishes the pending ``tsan.*`` telemetry deltas first (the one
    product call site — the ``DBSCAN_TSAN_REPORT`` atexit hook — so a
    sanitized run with obs enabled carries its tsan counters/events in
    the trace, not only in the JSON file)."""
    emit_telemetry()
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def _env_init() -> None:
    """Activate from the environment at import: ``DBSCAN_TSAN=1`` turns
    recording on; ``DBSCAN_TSAN_REPORT=path`` additionally dumps the
    JSON report at process exit (how the tier-1 subprocess rerun of the
    pipeline/fault suites is asserted race-free from outside)."""
    if config.env("DBSCAN_TSAN"):
        enable()
        path = config.env("DBSCAN_TSAN_REPORT")
        if path:
            atexit.register(write_report, path)


_env_init()
