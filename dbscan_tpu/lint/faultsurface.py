"""graftfault static rules: the fault surface as a checked contract.

Four ``fault-*`` families plus the persistence-atomicity rule, all over
the effect model (lint/effects.py) and the declared site registry
(``faults.SITES``, the obs/schema.py idiom applied to the fault plane):

- ``fault-retry-unsafe`` — the callable handed to
  ``faults.supervised(site, fn)`` mutates caller-visible state before
  its success point, so a transient-fault retry double-applies it (the
  ``_pull_record`` idempotence discipline from PR 5, generalized).
- ``fault-site-undeclared`` — a ``supervised(...)`` /
  ``next_ordinal(...)`` consumption whose site token is not in
  ``faults.SITES``: adding the registry row (owner, ordinal unit,
  degrade ladder, handler mode) IS the registration step.
- ``fault-site-undrilled`` — a consumed declared site with no
  ``DBSCAN_FAULT_SPEC`` drill clause anywhere in ``tests/`` (resolved
  statically from the test ASTs): an undrilled site is a retry path CI
  never exercises.
- ``fault-degrade-unreachable`` — a supervised call that satisfies none
  of its site's declared handler modes: no ``fallback=`` degradation
  argument, no enclosing ``except`` degrade handler, and no
  ``FatalDeviceFault`` catcher in the declared propagation module — the
  documented degrade ladder cannot be reached from this site.
- ``atomic-write-violation`` — a function opens a file for writing
  without the write-tmp-then-``os.replace`` idiom the persistence
  modules (checkpoint/flight/export/profiles) already follow: a run
  killed mid-write must leave the previous artifact intact.
  Append-mode opens are the other crash-tolerant idiom (bench-history
  JSONL) and are exempt.

Site tokens are resolved statically: string literals,
``faults.SITE_*`` constants through the import maps,
``shard_site(base, …)`` unwrapping (shard 0 normalizes to the bare
token), ``self._site`` through the owner class's ``__init__``
assignment, and parameter defaults (``site: str = faults.SITE_SERVE``).
An unresolvable site expression is skipped — the rules are
conservative, never guessing.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from dbscan_tpu.lint import callgraph as cg_mod
from dbscan_tpu.lint import effects as effects_mod
from dbscan_tpu.lint.callgraph import (
    FuncInfo,
    callable_argument,
    terminal_name,
)
from dbscan_tpu.lint.core import Finding, Package

# one drill clause of the DBSCAN_FAULT_SPEC grammar, as it appears in
# test-source string literals: site[@shard]#ordinal:KIND[*count]
_CLAUSE_RE = re.compile(
    r"(?P<site>[a-z_][a-z0-9_]*)(?:@\d+)?#\d+:[A-Z_]+(?:\*\d+)?"
)

_EXC_NAMES = ("Exception", "BaseException", "FatalDeviceFault")


class SiteCall:
    """One static consumption of a fault site."""

    __slots__ = ("site", "call", "info", "path", "kind")

    def __init__(self, site, call, info, path, kind):
        self.site = site  # resolved token (shard suffix stripped) or None
        self.call = call  # the ast.Call node
        self.info = info  # enclosing FuncInfo (None at module level)
        self.path = path
        self.kind = kind  # "supervised" | "ordinal"


def _strip_shard(token: str) -> str:
    return token.split("@", 1)[0]


def _resolve_site(cg, info: Optional[FuncInfo], mod, expr, depth=0):
    """Best-effort static value of a site expression (see module doc)."""
    if depth > 6 or expr is None:
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return _strip_shard(expr.value)
    if isinstance(expr, ast.Call):
        if terminal_name(expr.func) == "shard_site" and expr.args:
            return _resolve_site(cg, info, mod, expr.args[0], depth + 1)
        return None
    if isinstance(expr, ast.Attribute):
        recv = expr.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and info is not None and (
                info.owner_class is not None
            ):
                # self._site: resolve through the owner class's
                # assignments (canonically __init__)
                cls = info.owner_class
                for m in cls.methods.values():
                    for n in cg_mod.walk_scope(m.node):
                        if not isinstance(n, ast.Assign):
                            continue
                        for tgt in n.targets:
                            if (
                                isinstance(tgt, ast.Attribute)
                                and tgt.attr == expr.attr
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                got = _resolve_site(
                                    cg, m, m.module, n.value, depth + 1
                                )
                                if got is not None:
                                    return got
                return None
            modname = mod.import_alias.get(recv.id)
            if modname is None and recv.id in mod.from_names:
                src, orig = mod.from_names[recv.id]
                modname = f"{src}.{orig}"
            if modname is not None:
                m2 = cg.by_modname.get(modname)
                if m2 is not None:
                    val = m2.constants.get(expr.attr)
                    if isinstance(val, str):
                        return _strip_shard(val)
        return None
    if isinstance(expr, ast.Name):
        val = mod.constants.get(expr.id)
        if isinstance(val, str):
            return _strip_shard(val)
        if expr.id in mod.from_names:
            src, _orig = mod.from_names[expr.id]
            m2 = cg.by_modname.get(src)
            if m2 is not None:
                val = m2.constants.get(_orig)
                if isinstance(val, str):
                    return _strip_shard(val)
        if info is not None:
            # parameter default
            args = getattr(info.node, "args", None)
            if args is not None:
                pos = args.posonlyargs + args.args
                for a, d in zip(pos[len(pos) - len(args.defaults):],
                                args.defaults):
                    if a.arg == expr.id:
                        return _resolve_site(
                            cg, info, mod, d, depth + 1
                        )
                for a, d in zip(args.kwonlyargs, args.kw_defaults):
                    if a.arg == expr.id and d is not None:
                        return _resolve_site(
                            cg, info, mod, d, depth + 1
                        )
            # frame-local assignment
            for n in cg_mod.walk_scope(info.node):
                if isinstance(n, ast.Assign):
                    for tgt in n.targets:
                        if (
                            isinstance(tgt, ast.Name)
                            and tgt.id == expr.id
                        ):
                            got = _resolve_site(
                                cg, info, mod, n.value, depth + 1
                            )
                            if got is not None:
                                return got
    return None


def _enclosing_func(cg, mod, call: ast.Call) -> Optional[FuncInfo]:
    best = None
    best_span = None
    for fi in mod.all_functions:
        node = fi.node
        lo = getattr(node, "lineno", None)
        hi = getattr(node, "end_lineno", None)
        if lo is None or hi is None:
            continue
        if lo <= call.lineno <= hi:
            span = hi - lo
            if best_span is None or span < best_span:
                # innermost frame whose SCOPE walk actually contains
                # the call (not a sibling nested def)
                if any(n is call for n in cg_mod.walk_scope(node)):
                    best, best_span = fi, span
    return best


def site_consumptions(pkg: Package) -> List[SiteCall]:
    """Every static fault-site consumption in the linted set:
    ``faults.supervised(site, …)`` wraps and direct
    ``reg.next_ordinal(site)`` ordinal draws (the campaign lease path
    consumes its stream without a supervised wrap)."""
    cg = pkg.callgraph
    out: List[SiteCall] = []
    for sf in pkg.files:
        if sf.tree is None:
            continue
        mod = cg.modules.get(sf.path)
        if mod is None:
            continue
        if mod.modname == "dbscan_tpu.faults":
            continue  # the supervisor itself, not a consumer
        for n in ast.walk(sf.tree):
            if not isinstance(n, ast.Call):
                continue
            tname = terminal_name(n.func)
            if tname == "supervised" and n.args:
                info = _enclosing_func(cg, mod, n)
                site = _resolve_site(cg, info, mod, n.args[0])
                out.append(SiteCall(site, n, info, sf.path, "supervised"))
            elif tname == "next_ordinal" and n.args:
                info = _enclosing_func(cg, mod, n)
                site = _resolve_site(cg, info, mod, n.args[0])
                if site is not None:
                    out.append(SiteCall(site, n, info, sf.path, "ordinal"))
    return out


# --- drills (tests/ AST scan) ----------------------------------------


def _tests_dir(pkg: Package) -> Optional[str]:
    dirs = {
        os.path.dirname(os.path.abspath(f.path)) for f in pkg.files
    }
    if not dirs:
        return None
    common = os.path.commonpath(sorted(dirs))
    for cand in (common, os.path.dirname(common)):
        t = os.path.join(cand, "tests")
        if os.path.isdir(t):
            return t
    return None


def drill_sites(tests_dir: str) -> Dict[str, Set[str]]:
    """site token -> test basenames containing a drill clause for it,
    from every string literal in ``tests/test_*.py`` (static: the
    linter never imports test code)."""
    out: Dict[str, Set[str]] = {}
    for name in sorted(os.listdir(tests_dir)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        path = os.path.join(tests_dir, name)
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for n in ast.walk(tree):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                for m in _CLAUSE_RE.finditer(n.value):
                    out.setdefault(m.group("site"), set()).add(name)
    return out


# --- handler-mode checks ---------------------------------------------


def _in_degrading_try(mod, call: ast.Call) -> bool:
    """Is the call lexically inside a ``try`` whose handlers catch
    Exception/BaseException/FatalDeviceFault (a caller-owned degrade
    handler, the spill-tree pattern)?"""
    hit = [False]

    def walk(node, stack):
        if node is call:
            hit[0] = any(stack)
            return
        if isinstance(node, ast.Try):
            catches = False
            for h in node.handlers:
                names = []
                t = h.type
                for sub in ast.walk(t) if t is not None else ():
                    tn = terminal_name(sub)
                    if tn:
                        names.append(tn)
                if t is None or any(x in _EXC_NAMES for x in names):
                    catches = True
            for child in node.body:
                walk(child, stack + [catches])
            for h in node.handlers:
                for child in h.body:
                    walk(child, stack)
            for child in node.orelse + node.finalbody:
                walk(child, stack)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, stack)

    walk(mod.tree, [])
    return hit[0]


def _module_catches_fatal(cg, modname: str) -> Optional[bool]:
    """Does the declared propagation module own a degrade handler: an
    ``except`` naming FatalDeviceFault, or a ``faults.note_degrade()``
    call (the caller-counted degradation protocol the spill tree uses)?
    None when the module is outside the linted set (single-file fixture
    runs) — leniently satisfied."""
    m = cg.by_modname.get(modname)
    if m is None:
        return None
    for n in ast.walk(m.tree):
        if isinstance(n, ast.ExceptHandler) and n.type is not None:
            for sub in ast.walk(n.type):
                if terminal_name(sub) == "FatalDeviceFault":
                    return True
        if (
            isinstance(n, ast.Call)
            and terminal_name(n.func) == "note_degrade"
        ):
            return True
    return False


def _handler_satisfied(cg, spec, sc: SiteCall) -> bool:
    mod = cg.modules.get(sc.path)
    for mode in spec.handler:
        if mode == "fallback-arg":
            if any(kw.arg == "fallback" for kw in sc.call.keywords):
                return True
        elif mode == "caller-except":
            if mod is not None and _in_degrading_try(mod, sc.call):
                return True
        elif mode.startswith("propagate:"):
            got = _module_catches_fatal(cg, mode.split(":", 1)[1])
            if got is None or got:
                return True
    return False


# --- the rule entry point --------------------------------------------


def check(pkg: Package) -> List[Finding]:
    from dbscan_tpu import faults as _faults

    cg = pkg.callgraph
    findings: List[Finding] = []
    consumptions = site_consumptions(pkg)
    model = effects_mod.EffectModel(cg)

    tests_dir = _tests_dir(pkg)
    drills = drill_sites(tests_dir) if tests_dir is not None else None
    undrilled_reported: Set[str] = set()

    for sc in consumptions:
        if sc.site is None:
            continue
        spec = _faults.SITES.get(sc.site)
        if spec is None:
            findings.append(Finding(
                rule="fault-site-undeclared",
                path=sc.path,
                line=sc.call.lineno,
                col=sc.call.col_offset + 1,
                message=(
                    f"fault site '{sc.site}' is not declared in "
                    "faults.SITES — declare its owner, ordinal unit, "
                    "degrade ladder, and handler mode there "
                    "(registration is the obs/schema.py discipline: "
                    "the registry row IS the contract)"
                ),
            ))
            continue
        if (
            drills is not None
            and sc.site not in drills
            and sc.site not in undrilled_reported
        ):
            undrilled_reported.add(sc.site)
            findings.append(Finding(
                rule="fault-site-undrilled",
                path=sc.path,
                line=sc.call.lineno,
                col=sc.call.col_offset + 1,
                message=(
                    f"fault site '{sc.site}' has no DBSCAN_FAULT_SPEC "
                    "drill in tests/ — add at least one "
                    f"'{sc.site}#0:TRANSIENT'-style clause so CI "
                    "exercises this retry path"
                ),
            ))
        if sc.kind != "supervised":
            continue
        if not _handler_satisfied(cg, spec, sc):
            findings.append(Finding(
                rule="fault-degrade-unreachable",
                path=sc.path,
                line=sc.call.lineno,
                col=sc.call.col_offset + 1,
                message=(
                    f"site '{sc.site}' declares degrade ladder "
                    f"{' -> '.join(spec.degrade)} (handler "
                    f"{'/'.join(spec.handler)}) but this supervised "
                    "call reaches none of it: pass fallback=, wrap in "
                    "a degrading try/except, or route the "
                    "FatalDeviceFault to the declared catcher"
                ),
            ))
        # retry idempotence of the attempt callable (and the fallback:
        # a degraded group re-lands the same state)
        if len(sc.call.args) >= 2 and sc.info is not None:
            types = cg_mod.local_types(cg, sc.info)
            attempt = callable_argument(
                cg, sc.info, sc.call.args[1], types
            )
            if attempt is not None:
                seen: Set[Tuple[str, str]] = set()
                for eff in effects_mod.unsafe_mutations(model, attempt):
                    key = (eff.target, eff.flavor)
                    if key in seen:
                        continue
                    seen.add(key)
                    via = f" (via {eff.via})" if eff.via else ""
                    findings.append(Finding(
                        rule="fault-retry-unsafe",
                        path=sc.path,
                        line=sc.call.lineno,
                        col=sc.call.col_offset + 1,
                        message=(
                            f"supervised callable for site "
                            f"'{sc.site}' mutates caller-visible "
                            f"state before its success point: "
                            f"{eff.target} ({eff.flavor}{via}, line "
                            f"{eff.line}) — a transient-fault retry "
                            "re-applies it; mutate only after the "
                            "last device op, or restore a snapshot "
                            "as the callable's first statement"
                        ),
                    ))
    findings.extend(_check_atomic_writes(pkg))
    return findings


# --- atomic-write-violation ------------------------------------------


def _open_write_mode(call: ast.Call) -> Optional[str]:
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return None
    mode = "r"
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if not isinstance(mode, str):
        return None
    if "w" in mode or "x" in mode:
        return mode
    return None


def _check_atomic_writes(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    cg = pkg.callgraph
    for sf in pkg.files:
        if sf.tree is None:
            continue
        mod = cg.modules.get(sf.path)
        if mod is None:
            continue
        scopes = [mod.tree] + [fi.node for fi in mod.all_functions]
        for scope in scopes:
            opens: List[ast.Call] = []
            has_replace = False
            for n in cg_mod.walk_scope(scope):
                if not isinstance(n, ast.Call):
                    continue
                if _open_write_mode(n) is not None:
                    opens.append(n)
                f = n.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "replace"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "os"
                ):
                    has_replace = True
            if has_replace or not opens:
                continue
            for call in opens:
                findings.append(Finding(
                    rule="atomic-write-violation",
                    path=sf.path,
                    line=call.lineno,
                    col=call.col_offset + 1,
                    message=(
                        "file opened for writing without the "
                        "write-tmp-then-os.replace idiom — a run "
                        "killed mid-write corrupts the artifact; "
                        "write to '<path>.tmp' and os.replace() it "
                        "(obs/export._atomic_write is the reference "
                        "shape), or append (mode 'a') for logs"
                    ),
                ))
    return findings


# --- PARITY fault-surface table --------------------------------------


def fault_table(pkg: Optional[Package] = None) -> str:
    """The PARITY.md fault-surface table (``python -m dbscan_tpu.lint
    --fault-table``): one row per declared site — its consumers as
    found statically, ordinal unit, degrade ladder, handler mode(s),
    and the test files drilling it."""
    from dbscan_tpu import faults as _faults

    if pkg is None:
        import dbscan_tpu
        from dbscan_tpu.lint.core import load_package, run_rules

        pkg = load_package([os.path.dirname(dbscan_tpu.__file__)])
        run_rules(pkg, (), {})
    consumers: Dict[str, Set[str]] = {}
    cg = pkg.callgraph
    for sc in site_consumptions(pkg):
        if sc.site is None:
            continue
        mod = cg.modules.get(sc.path)
        name = (
            mod.modname if mod is not None else os.path.basename(sc.path)
        )
        consumers.setdefault(sc.site, set()).add(
            name.replace("dbscan_tpu.", "")
        )
    tests_dir = _tests_dir(pkg)
    drills = drill_sites(tests_dir) if tests_dir is not None else {}
    lines = [
        "| Site | Consumers | Ordinal unit | Degrade ladder "
        "| Handler | Drills |",
        "|---|---|---|---|---|---|",
    ]
    for site in sorted(_faults.SITES):
        spec = _faults.SITES[site]
        cons = sorted(consumers.get(site, set()))
        if not cons:
            cons = [spec.owner + " (declared)"]
        drill_names = sorted(drills.get(site, set()))
        lines.append(
            f"| `{site}` | {', '.join(f'`{c}`' for c in cons)} "
            f"| {spec.unit} "
            f"| {' -> '.join(spec.degrade)} "
            f"| {'/'.join(spec.handler)} "
            f"| {', '.join(f'`{d}`' for d in drill_names) or '—'} |"
        )
    return "\n".join(lines)
