"""Elastic fault-priced campaign driver (ROADMAP item 5).

The reference paper's whole design is "survive the cluster": partitions
are eps-halo'd precisely so any executor can die and be rescheduled
without poisoning the global merge (DBSCAN.scala:53-60 leans on Spark
lineage for the replay). Our only campaign harness so far was the m100
retry-resume loop hard-coded in bench.py — one worker, whole-process
restarts, no way to steal work, resize its grain, or price what a
restart costs. This module generalizes it into a campaign driver that
runs ONE logical clustering job as a queue of resumable work chunks
over a worker fleet:

- **work-stealing chunk queue** (:class:`ChunkQueue`): the p1chunk
  restart points (parallel/checkpoint.py) are the lease currency.
  Workers lease batches of chunk indices with heartbeat-expiring
  leases; a preempted or wedged worker's unfinished chunks return to
  the queue and are restolen instead of stalling the campaign. Chunk
  artifacts are deterministic (the plan-derived composition signature
  is the adoption gate), so even the stale-leaseholder-races-the-thief
  case is benign: both write byte-identical files through an atomic
  rename.
- **fault-rate-aware re-partitioning**: each worker watches its own
  leases' ``stats["faults"]`` deltas (PR 1) and outcomes, halving its
  lease size (never below ``DBSCAN_CAMPAIGN_MIN_CHUNK``) while faults
  run hot and doubling it back (capped at ``DBSCAN_CAMPAIGN_MAX_CHUNK``)
  after sustained health. Lease size only changes WHICH chunks a leg
  computes — chunk compositions are plan-fixed and every dispatch rides
  the existing ladder/ratchet shapes — so re-partitioning can never
  mint a recompile.
- **degradation tiers**: a lease that dies with a real retries-
  exhausted device fault (``faults.FatalDeviceFault`` from a non-
  campaign site) degrades its WORKER to the CPU tier — subsequent
  leases run the per-group CPU kernel for the whole leg
  (``CampaignLeg(tier="cpu")``, the whole-chunk generalization of the
  faults.py per-group fallback) — rather than aborting the campaign.
  Labels are unchanged (same algebra; PARITY.md "Campaign contract").
- **priced replay budget**: every lease's wall is accounted.
  A failed/killed/expired lease's wall is charged pro-rata to the
  chunks that did NOT land (``wasted = wall * unfinished/leased``), and
  ``replay_frac = replayed_wall / work_wall`` is stamped on the bench
  row (``campaign_replay_frac``), promoted by obs/bench_history, and
  gated regress-UP by obs/regress — restart overhead is a first-class
  regression-tested metric, the spot-instance economics of production
  clusters made measurable.
- **preemption drills**: the ``campaign`` site in ``DBSCAN_FAULT_SPEC``
  (faults.py) injects deterministic worker failures at lease grant:
  ``TRANSIENT`` kills the leg after it banks one chunk (through the
  driver's real abort path — note_abort + flightrec dump),
  ``PERSISTENT`` wedges the worker (its lease must heartbeat-expire and
  be restolen), ``RESOURCE_EXHAUSTED`` degrades the worker to the CPU
  tier. The steal/resume/degrade paths are exercised in tier-1
  (tests/test_campaign.py) with flightrec (PR 9) as the per-worker
  postmortem and the graftcheck/tsan rules (PR 6) certifying the shared
  queue state.

Two campaign shapes share the machinery:

- **chunk-leased** (:class:`Campaign` + :class:`TrainChunkJob`): N
  in-process worker threads lease chunk subsets and run partial legs
  (``driver.train_arrays(campaign=CampaignLeg(...))``); a finalize run
  over the fully-banked dir loads every chunk and merges. In-process
  legs serialize on the module device lease (one accelerator per
  process) — the queue semantics are fleet-general, and ROADMAP item 1's
  multi-chip mesh is the consumer that will lease chips concurrently.
- **frontier** (:func:`run_frontier`): subprocess legs in the m100 mold
  — each lease is one full ``train(checkpoint_dir=...)`` attempt that
  banks whatever it reaches; bench.py::m100_row now rides this,
  keeping its measured-honesty rules (prior-chunk mpts suppression,
  stall breakout on the progress counter, campaign-key invalidation)
  while gaining lease accounting and the priced replay budget.

CLI: ``python -m dbscan_tpu.campaign`` runs a deterministic drilled
campaign (see README "Campaigns") and emits a bench-history-ingestible
capture; ``--leg`` is the subprocess leg entry the drills SIGTERM.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.lint import tsan as _tsan

logger = logging.getLogger(__name__)

#: one accelerator per process: in-process chunk legs (and the plan /
#: finalize runs) serialize on this reentrant lease, so concurrent
#: worker threads contend for the device instead of interleaving
#: dispatches inside one run. Subprocess legs and (ROADMAP item 1)
#: per-chip meshes are the true-parallel tiers.
_DEVICE_LEASE = _tsan.rlock("campaign.device")

_MAX_WORKER_ERRORS = 3  # unclassified failures before a worker retires


class LeaseCancelled(Exception):
    """The campaign is shutting down (budget exhausted / stop set)
    while this lease was still queued behind the device — the leg
    never ran; its chunks go straight back to the queue."""


def _consume_campaign_fault():
    """Consume one ``campaign`` fault-site ordinal for a lease grant
    (only when the spec names the site — the ``pull#N`` opt-in
    discipline) and return ``(kind, ordinal)``; ``(None, -1)`` with no
    active campaign clause. The ONE consume rule both campaign shapes
    (worker fleet and frontier legs) share."""
    if not faults.campaign_site_active():
        return None, -1
    reg = faults.get_registry()
    n, g = reg.next_ordinal(faults.SITE_CAMPAIGN)
    try:
        reg.check(faults.SITE_CAMPAIGN, n, g, 0)
    except faults.FaultInjected as e:
        return e.kind, n
    return None, n


# --- lease / queue -----------------------------------------------------


@dataclasses.dataclass
class Lease:
    """One granted lease: a batch of chunk ids owned by a worker until
    it completes, fails, or stops heartbeating past the expiry window."""

    lease_id: int
    worker: str
    tier: str
    chunks: tuple  # chunk ids granted (sorted)
    granted_at: float  # time.monotonic at grant
    heartbeat_at: float
    done: set = dataclasses.field(default_factory=set)
    active: bool = True
    outcome: str = ""  # ok | kill | fault | error | expired | cancelled


class ChunkQueue:
    """Work-stealing chunk queue with heartbeat-expiring leases.

    Thread-safety: one condition variable guards ALL queue state
    (pending/done sets, lease table, replay accounting) — the same
    single-monitor discipline as the pull engine
    (parallel/pipeline.py), checked statically by graftcheck's
    race rules and at runtime under ``DBSCAN_TSAN=1``. Telemetry is
    emitted OUTSIDE the lock.

    Replay pricing: ``work_wall_s`` accumulates every lease's wall;
    ``replayed_wall_s`` accumulates the pro-rata share of a
    failed/expired lease's wall attributable to the chunks it did not
    finish (they must be recomputed by the thief). A wedged worker that
    reports after its lease expired is ignored entirely — its wall was
    priced at expiry."""

    def __init__(self, chunk_ids: Sequence[int], lease_s: float):
        self._cv = _tsan.condition("campaign.queue")
        self._pending: List[int] = sorted(int(c) for c in chunk_ids)
        self._done: set = set()
        self._total = len(self._pending)
        self._leases: dict = {}
        self._next_id = 0
        self.lease_s = float(lease_s)
        self._acct = {
            "leases": 0,
            "steals": 0,
            "expired": 0,
            "work_wall_s": 0.0,
            "replayed_wall_s": 0.0,
        }

    # --- worker side ---------------------------------------------------

    def lease(self, worker: str, want: int, tier: str) -> Optional[Lease]:
        """Grant up to ``want`` pending chunks (lowest ids first) to
        ``worker``; None when nothing is pending (completed chunks never
        re-lease — only failed/expired ones return)."""
        now = time.monotonic()
        with self._cv:
            _tsan.access("campaign.queue")
            if not self._pending:
                return None
            take = self._pending[: max(1, int(want))]
            del self._pending[: len(take)]
            lease = Lease(
                lease_id=self._next_id,
                worker=worker,
                tier=tier,
                chunks=tuple(take),
                granted_at=now,
                heartbeat_at=now,
            )
            self._next_id += 1
            self._leases[lease.lease_id] = lease
            self._acct["leases"] += 1
            depth = self._depth_locked()
        obs.count("campaign.leases")
        self._emit_depth(depth)
        return lease

    def heartbeat(self, lease: Lease) -> None:
        """Refresh a lease's expiry window: the holder demonstrated
        forward progress (a leased group dispatched, or the leg just
        acquired the device). A lease only reads as wedged after a
        whole ``lease_s`` window with NO progress — a long first chunk
        is not a wedge."""
        with self._cv:
            _tsan.access("campaign.queue")
            lease.heartbeat_at = time.monotonic()

    def note_chunk(self, lease: Lease, ci: int) -> None:
        """Heartbeat + incremental completion: chunk ``ci`` of ``lease``
        is banked on disk. An expired lease's notes are ignored (the
        chunk was requeued at expiry; the thief's recompute overwrites
        the same bytes)."""
        with self._cv:
            _tsan.access("campaign.queue")
            lease.heartbeat_at = time.monotonic()
            if not lease.active:
                return
            ci = int(ci)
            lease.done.add(ci)
            if ci not in self._done:
                self._done.add(ci)
                self._cv.notify_all()
            depth = self._depth_locked()
        obs.count("campaign.chunks_done")
        self._emit_depth(depth)

    def release(self, lease: Lease, wall_s: float, outcome: str) -> int:
        """A worker finished (or died on) its lease: price the wall,
        requeue unfinished chunks, and return how many were requeued.
        No-op (returns 0) when the lease already expired — its pricing
        happened at steal time."""
        requeued = 0
        with self._cv:
            _tsan.access("campaign.queue")
            if not lease.active:
                return 0
            lease.active = False
            lease.outcome = outcome
            requeued = self._requeue_locked(lease)
            wall = max(0.0, float(wall_s))
            if outcome != "cancelled":
                # a cancelled lease never ran its leg (shutdown while
                # queued): its wait wall is neither work nor replay
                self._acct["work_wall_s"] += wall
            if outcome not in ("ok", "cancelled"):
                self._acct["replayed_wall_s"] += self._wasted(
                    lease, wall, requeued
                )
                self._acct["steals"] += requeued
            self._cv.notify_all()
            depth = self._depth_locked()
        # telemetry mirrors the priced accounting exactly: cancelled
        # leases requeue their chunks but are neither steals nor replay
        # (the bench row and the trace must agree)
        if requeued and outcome != "cancelled":
            obs.count("campaign.steals", requeued)
            obs.event(
                "campaign.steal",
                lease=lease.lease_id,
                worker=lease.worker,
                outcome=outcome,
                chunks=requeued,
            )
        self._emit_depth(depth)
        return requeued

    def expire_stale(self) -> List[Lease]:
        """Requeue the chunks of every active lease whose heartbeat is
        older than ``lease_s`` — the steal path for wedged/preempted
        workers. The expired lease's elapsed wall is priced pro-rata
        here; any later report from the stale holder is ignored."""
        now = time.monotonic()
        stolen = []
        with self._cv:
            _tsan.access("campaign.queue")
            for lease in self._leases.values():
                if not lease.active:
                    continue
                if now - lease.heartbeat_at <= self.lease_s:
                    continue
                lease.active = False
                lease.outcome = "expired"
                requeued = self._requeue_locked(lease)
                elapsed = max(0.0, now - lease.granted_at)
                self._acct["work_wall_s"] += elapsed
                self._acct["replayed_wall_s"] += self._wasted(
                    lease, elapsed, requeued
                )
                self._acct["expired"] += 1
                self._acct["steals"] += requeued
                stolen.append(lease)
            if stolen:
                self._cv.notify_all()
            depth = self._depth_locked()
        for lease in stolen:
            obs.count("campaign.expired")
            obs.count("campaign.steals", len(lease.chunks) - len(lease.done))
            obs.event(
                "campaign.expire",
                lease=lease.lease_id,
                worker=lease.worker,
                chunks=len(lease.chunks) - len(lease.done),
                lease_s=self.lease_s,
            )
        if stolen:
            self._emit_depth(depth)
        return stolen

    def _requeue_locked(self, lease: Lease) -> int:
        """Return the lease's unfinished chunks to the pending queue
        (caller holds the lock)."""
        back = [c for c in lease.chunks if c not in lease.done
                and c not in self._done and c not in self._pending]
        self._pending = sorted(self._pending + back)
        return len(back)

    @staticmethod
    def _wasted(lease: Lease, wall: float, requeued: int) -> float:
        """Pro-rata replayed wall: the share of this lease's wall
        attributable to chunks that must be recomputed. Exact under the
        uniform-chunk approximation; a lease that banked nothing wastes
        its whole wall."""
        if not lease.chunks:
            return wall
        return wall * (requeued / len(lease.chunks))

    # --- campaign side -------------------------------------------------

    def mark_done(self, chunk_ids: Sequence[int]) -> None:
        """Chunks already banked on disk (a resumed campaign): never
        leased, counted done."""
        with self._cv:
            _tsan.access("campaign.queue")
            for ci in chunk_ids:
                ci = int(ci)
                self._done.add(ci)
                if ci in self._pending:
                    self._pending.remove(ci)
            self._cv.notify_all()
            depth = self._depth_locked()
        self._emit_depth(depth)

    def done(self) -> bool:
        with self._cv:
            _tsan.access("campaign.queue", write=False)
            return len(self._done) >= self._total

    def wait(self, timeout: float) -> bool:
        """Block up to ``timeout`` for any queue-state change; returns
        :meth:`done`."""
        with self._cv:
            _tsan.access("campaign.queue", write=False)
            if len(self._done) < self._total:
                self._cv.wait(timeout)
            return len(self._done) >= self._total

    def snapshot(self) -> dict:
        """Queue accounting for the campaign result (counts + replay
        pricing)."""
        with self._cv:
            _tsan.access("campaign.queue", write=False)
            out = dict(self._acct)
            out["chunks_total"] = self._total
            out["chunks_done"] = len(self._done)
            out["pending"] = len(self._pending)
            out["work_wall_s"] = round(out["work_wall_s"], 6)
            out["replayed_wall_s"] = round(out["replayed_wall_s"], 6)
            return out

    def _depth_locked(self) -> int:
        """Chunks not yet banked (caller holds the monitor) — computed
        inside the caller's existing critical section so telemetry
        emission costs no second lock round-trip per queue op."""
        return self._total - len(self._done)

    @staticmethod
    def _emit_depth(depth: int) -> None:
        obs.gauge("campaign.queue_depth", depth)


def replay_frac(work_wall_s: float, replayed_wall_s: float) -> float:
    """``campaign_replay_frac`` = replayed wall / total work wall (0.0
    for an idle or fault-free campaign) — THE priced restart-overhead
    figure, gated regress-up (obs/regress.py)."""
    if work_wall_s <= 0:
        return 0.0
    return round(min(1.0, replayed_wall_s / work_wall_s), 4)


# --- workers -----------------------------------------------------------


class CampaignWorker:
    """One worker of the fleet: a thread that leases chunk batches,
    runs them through the job, adapts its lease size to its own fault
    rate, and degrades to the CPU tier when the device path exhausts
    its retries. All cross-thread state lives in the
    :class:`ChunkQueue` monitor; a worker's own fields are owned by its
    thread (the campaign reads them only after ``join``)."""

    def __init__(
        self,
        name: str,
        job,
        queue: ChunkQueue,
        *,
        min_chunk: int,
        max_chunk: int,
        stop: threading.Event,
        release: threading.Event,
    ):
        self.name = name
        self.job = job
        self.queue = queue
        self.min_chunk = max(1, int(min_chunk))
        self.max_chunk = max(self.min_chunk, int(max_chunk))
        self.stop = stop
        self.release = release
        # start mid-ladder: hot fault rates halve toward min_chunk,
        # sustained health doubles toward max_chunk
        self.want = min(self.max_chunk, max(self.min_chunk, 2))
        self.tier = "device"
        self.clean_streak = 0
        self.errors = 0
        self.kills = 0
        self.wedged = False
        self.degraded = False
        self.last_error = ""
        self._thread = threading.Thread(
            target=self._run, name=f"dbscan-campaign-{name}", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    # --- internals -----------------------------------------------------

    def _degrade(self, why: str) -> None:
        if self.tier == "cpu":
            return
        self.tier = "cpu"
        self.degraded = True
        obs.count("campaign.degrades")
        obs.event("campaign.degrade", worker=self.name, error=why[:120])
        logger.warning(
            "campaign worker %s: degrading to the CPU tier (%s)",
            self.name,
            why,
        )

    def _adapt(self, hot: bool) -> None:
        """Fault-rate-aware re-partitioning of this worker's lease
        size. Pure queue-grain arithmetic: chunk compositions are
        plan-fixed and shapes ride the existing ladders, so no setting
        of ``want`` can mint a recompile."""
        old = self.want
        if hot:
            self.clean_streak = 0
            self.want = max(self.min_chunk, self.want // 2)
        else:
            self.clean_streak += 1
            if self.clean_streak >= 2:
                self.clean_streak = 0
                self.want = min(self.max_chunk, self.want * 2)
        if self.want != old:
            obs.count("campaign.repartitions")
            obs.event(
                "campaign.repartition",
                worker=self.name,
                want=self.want,
                was=old,
                hot=hot,
            )

    def _wedge(self, lease: Lease) -> None:
        """Injected PERSISTENT campaign fault: this worker wedges —
        holds its lease, stops heartbeating, and parks until the
        campaign releases it. The lease must expire and be restolen by
        the rest of the fleet (the drill the acceptance test pins)."""
        self.wedged = True
        obs.count("campaign.wedges")
        obs.event(
            "campaign.wedge",
            worker=self.name,
            lease=lease.lease_id,
            chunks=len(lease.chunks),
        )
        logger.warning(
            "campaign worker %s: injected wedge holding lease %d "
            "(%d chunk(s)); lease expires in %.1fs",
            self.name,
            lease.lease_id,
            len(lease.chunks),
            self.queue.lease_s,
        )
        self.release.wait()

    def _run(self) -> None:
        poll = max(0.05, min(self.queue.lease_s / 4.0, 0.5))
        while not self.stop.is_set():
            self.queue.expire_stale()
            kind, ordinal = None, -1
            lease = self.queue.lease(self.name, self.want, self.tier)
            if lease is None:
                if self.queue.wait(poll):
                    break
                continue
            kind, ordinal = _consume_campaign_fault()
            if kind == faults.PERSISTENT:
                self._wedge(lease)
                return
            if kind == faults.RESOURCE_EXHAUSTED:
                # the drill stand-in for "this worker's device lost its
                # memory headroom": degrade the tier, then run the lease
                self._degrade("injected RESOURCE_EXHAUSTED")
            kill_after = 1 if kind == faults.TRANSIENT else 0
            outcome = "ok"
            stats = None
            t0 = time.monotonic()
            tp0 = time.perf_counter()
            try:
                stats = self.job.run_lease(
                    sorted(lease.chunks),
                    tier=self.tier,
                    kill_after=kill_after,
                    kill_ordinal=ordinal,
                    on_chunk=lambda ci, lease=lease: self.queue.note_chunk(
                        lease, ci
                    ),
                    heartbeat=lambda lease=lease: self.queue.heartbeat(
                        lease
                    ),
                    should_stop=self.stop.is_set,
                )
            except LeaseCancelled:
                # shutdown while queued behind the device: the leg
                # never ran — hand the chunks back and exit the loop
                outcome = "cancelled"
            except faults.FatalDeviceFault as e:
                self.last_error = str(e)
                if e.site == faults.SITE_CAMPAIGN:
                    # the injected worker-kill drill: the leg died
                    # through the driver's real abort path (banked
                    # chunks + note_abort + flightrec dump)
                    outcome = "kill"
                    self.kills += 1
                    obs.count("campaign.kills")
                    obs.event(
                        "campaign.kill",
                        worker=self.name,
                        lease=lease.lease_id,
                        ordinal=ordinal,
                    )
                else:
                    # a real retries-exhausted device fault: this
                    # worker's device path is unhealthy — degrade the
                    # whole worker to the CPU tier and requeue
                    outcome = "fault"
                    self._degrade(str(e))
            except Exception as e:  # noqa: BLE001 — worker must survive
                outcome = "error"
                self.errors += 1
                self.last_error = f"{type(e).__name__}: {e}"
                logger.exception(
                    "campaign worker %s: lease %d failed",
                    self.name,
                    lease.lease_id,
                )
            wall = time.monotonic() - t0
            self.queue.release(lease, wall, outcome)
            obs.add_span(
                "campaign.lease",
                tp0,
                time.perf_counter(),
                worker=self.name,
                lease=lease.lease_id,
                chunks=len(lease.chunks),
                tier=self.tier,
                outcome=outcome,
            )
            if outcome == "cancelled":
                continue  # shutdown, not a fault: no lease-size signal
            hot = outcome != "ok" or bool(
                stats
                and (
                    stats.get("faults", {}).get("retries", 0)
                    or stats.get("faults", {}).get("fallbacks", 0)
                )
            )
            self._adapt(hot)
            if self.errors >= _MAX_WORKER_ERRORS:
                logger.error(
                    "campaign worker %s: retiring after %d errors "
                    "(last: %s)",
                    self.name,
                    self.errors,
                    self.last_error,
                )
                return


# --- campaign ----------------------------------------------------------


@dataclasses.dataclass
class CampaignResult:
    """One campaign's outcome + priced accounting. ``replay_frac`` is
    the bench-row ``campaign_replay_frac`` figure."""

    complete: bool
    output: object  # TrainOutput of the finalize run, or None
    chunks_total: int
    chunks_done: int
    leases: int
    steals: int
    expired: int
    kills: int
    wedges: int
    degrades: int
    work_wall_s: float
    replayed_wall_s: float
    replay_frac: float
    wall_s: float
    workers: int
    last_error: str = ""

    def row(self, prefix: str = "campaign") -> dict:
        """Bench-row keys for this campaign (the shape bench.py stamps
        and obs/bench_history promotes)."""
        out = {
            f"{prefix}_complete": bool(self.complete),
            f"{prefix}_chunks_total": int(self.chunks_total),
            f"{prefix}_chunks_done": int(self.chunks_done),
            f"{prefix}_leases": int(self.leases),
            f"{prefix}_steals": int(self.steals),
            f"{prefix}_expired": int(self.expired),
            f"{prefix}_kills": int(self.kills),
            f"{prefix}_wedges": int(self.wedges),
            f"{prefix}_degrades": int(self.degrades),
            f"{prefix}_replay_frac": float(self.replay_frac),
            f"{prefix}_wall_s": round(float(self.wall_s), 3),
        }
        if self.last_error:
            out[f"{prefix}_last_error"] = self.last_error[:200]
        return out


class Campaign:
    """Run one chunk-leased campaign over a worker fleet (module
    docstring). ``job`` duck-types three methods:

    - ``plan() -> dict`` with ``chunks_total`` (and optionally
      ``banked`` — chunk ids already on disk — and ``output`` when the
      job discovered it is ALREADY complete, e.g. a premerge resume);
    - ``run_lease(chunks, *, tier, kill_after, kill_ordinal, on_chunk,
      heartbeat, should_stop) -> stats dict`` — compute + bank the
      leased chunks, calling ``on_chunk(ci)`` after each (lease
      completion), ``heartbeat()`` on any forward progress, and
      raising :class:`LeaseCancelled` if ``should_stop()`` turns true
      before the leg starts;
    - ``finalize() -> output`` — the assembly run over the fully-banked
      state.
    """

    def __init__(
        self,
        job,
        *,
        workers: Optional[int] = None,
        lease_s: Optional[float] = None,
        min_chunk: Optional[int] = None,
        max_chunk: Optional[int] = None,
        budget_s: Optional[float] = None,
        poll_s: float = 0.25,
    ):
        self.job = job
        self.n_workers = int(
            workers
            if workers is not None
            else config.env("DBSCAN_CAMPAIGN_WORKERS")
        )
        self.lease_s = float(
            lease_s
            if lease_s is not None
            else config.env("DBSCAN_CAMPAIGN_LEASE_S")
        )
        self.min_chunk = int(
            min_chunk
            if min_chunk is not None
            else config.env("DBSCAN_CAMPAIGN_MIN_CHUNK")
        )
        self.max_chunk = int(
            max_chunk
            if max_chunk is not None
            else config.env("DBSCAN_CAMPAIGN_MAX_CHUNK")
        )
        self.budget_s = budget_s
        self.poll_s = float(poll_s)

    def run(self) -> CampaignResult:
        t0 = time.monotonic()
        tp0 = time.perf_counter()
        plan = self.job.plan()
        if plan.get("output") is not None:
            # the job was already complete (premerge resume): a
            # zero-lease campaign with nothing replayed
            return CampaignResult(
                complete=True,
                output=plan["output"],
                chunks_total=int(plan.get("chunks_total") or 0),
                chunks_done=int(plan.get("chunks_total") or 0),
                leases=0, steals=0, expired=0, kills=0, wedges=0,
                degrades=0, work_wall_s=0.0, replayed_wall_s=0.0,
                replay_frac=0.0,
                wall_s=round(time.monotonic() - t0, 6),
                workers=0,
            )
        total = int(plan.get("chunks_total") or 0)
        queue = ChunkQueue(range(total), self.lease_s)
        banked = [c for c in plan.get("banked", ()) if 0 <= c < total]
        if banked:
            queue.mark_done(banked)
        stop = threading.Event()
        release = threading.Event()
        fleet = [
            CampaignWorker(
                f"w{i}",
                self.job,
                queue,
                min_chunk=self.min_chunk,
                max_chunk=self.max_chunk,
                stop=stop,
                release=release,
            )
            for i in range(max(1, self.n_workers))
        ]
        obs.gauge("campaign.workers_active", len(fleet))
        for w in fleet:
            w.start()
        try:
            while not queue.done():
                queue.wait(self.poll_s)
                # the main thread steals too: with every worker wedged
                # or busy, SOMEONE must still expire stale leases
                queue.expire_stale()
                if (
                    self.budget_s is not None
                    and time.monotonic() - t0 > self.budget_s
                ):
                    logger.warning(
                        "campaign: budget %.1fs exhausted with %s",
                        self.budget_s,
                        queue.snapshot(),
                    )
                    break
                # no worker left that could ever lease again — retired,
                # dead, or parked in an injected wedge (alive but
                # permanently out of the loop): stop instead of
                # spinning forever on an unfillable queue
                if all(not w.alive or w.wedged for w in fleet):
                    break
        finally:
            stop.set()
            release.set()
        for w in fleet:
            # a worker blocked inside a leg finishes that leg first —
            # bounded by the leg itself, the same contract as one m100
            # subprocess leg
            w.join()
        obs.gauge("campaign.workers_active", 0)
        snap = queue.snapshot()
        output = None
        complete = queue.done()
        last_error = next(
            (w.last_error for w in fleet if w.last_error), ""
        )
        if complete:
            fin0 = time.perf_counter()
            output = self.job.finalize()
            obs.add_span("campaign.finalize", fin0, time.perf_counter())
        wall = time.monotonic() - t0
        obs.count("campaign.work_wall_s", snap["work_wall_s"])
        obs.count("campaign.replayed_wall_s", snap["replayed_wall_s"])
        obs.add_span(
            "campaign.run",
            tp0,
            time.perf_counter(),
            chunks=total,
            workers=len(fleet),
            complete=complete,
        )
        obs.flush()  # the campaign tail must reach DBSCAN_TRACE's file
        return CampaignResult(
            complete=complete,
            output=output,
            chunks_total=snap["chunks_total"],
            chunks_done=snap["chunks_done"],
            leases=snap["leases"],
            steals=snap["steals"],
            expired=snap["expired"],
            kills=sum(w.kills for w in fleet),
            wedges=sum(1 for w in fleet if w.wedged),
            degrades=sum(1 for w in fleet if w.degraded),
            work_wall_s=snap["work_wall_s"],
            replayed_wall_s=snap["replayed_wall_s"],
            replay_frac=replay_frac(
                snap["work_wall_s"], snap["replayed_wall_s"]
            ),
            wall_s=round(wall, 6),
            workers=len(fleet),
            last_error=last_error,
        )


# --- the in-process clustering job -------------------------------------


class TrainChunkJob:
    """Chunk-leased campaign job over one dataset: partial legs via
    ``driver.train_arrays(campaign=CampaignLeg(...))``, assembly via an
    unrestricted run over the fully-banked checkpoint dir. Labels are
    byte-identical to a single fault-free ``train`` (pinned by
    tests/test_campaign.py): chunk artifacts are deterministic and the
    finalize run adopts them under the ordinal-salted composition
    signatures, exactly as the existing resume path does."""

    def __init__(self, points, cfg, ckpt_dir: str, mesh=None):
        self.points = points
        self.cfg = cfg.validate()
        self.ckpt_dir = ckpt_dir
        self.mesh = mesh

    def _fingerprint(self) -> str:
        from dbscan_tpu.parallel import checkpoint as ckpt_mod

        # mirror train_arrays' input normalization (euclidean banded
        # path: f64 cast) so the fingerprint matches the legs'
        pts = np.asarray(self.points, dtype=np.float64)
        return ckpt_mod.run_fingerprint(pts, self.cfg)

    def plan(self) -> dict:
        from dbscan_tpu.parallel import checkpoint as ckpt_mod
        from dbscan_tpu.parallel import driver

        leg = driver.CampaignLeg(chunks=frozenset())
        with _DEVICE_LEASE:
            out = driver.train_arrays(
                self.points,
                self.cfg,
                mesh=self.mesh,
                checkpoint_dir=self.ckpt_dir,
                campaign=leg,
            )
        if out.stats.get("resumed_from_checkpoint"):
            return {"output": out, "chunks_total": 0, "banked": []}
        return {
            "output": None,
            "chunks_total": out.stats.get("campaign_chunks_total") or 0,
            # chunks banked by a prior (interrupted) campaign: the
            # queue marks them done so only the holes get leased
            "banked": ckpt_mod.p1_chunk_indices(
                self.ckpt_dir,
                self._fingerprint(),
                budget=driver._COMPACT_CHUNK_SLOTS,
            ),
        }

    def run_lease(
        self,
        chunks,
        *,
        tier: str,
        kill_after: int = 0,
        kill_ordinal: int = -1,
        on_chunk: Optional[Callable[[int], None]] = None,
        heartbeat: Optional[Callable[[], None]] = None,
        should_stop: Optional[Callable[[], bool]] = None,
    ) -> dict:
        from dbscan_tpu.parallel import driver

        leg = driver.CampaignLeg(
            chunks=frozenset(int(c) for c in chunks),
            tier=tier,
            kill_after=int(kill_after),
            kill_ordinal=int(kill_ordinal),
            on_chunk=on_chunk,
            # per-group heartbeat: a first chunk longer than the expiry
            # window must not read as a wedge
            on_progress=heartbeat,
        )
        # heartbeat WHILE queued behind the device lease too: a worker
        # blocked here is healthy (serialized behind a peer's leg, not
        # wedged), and letting its lease expire would both steal its
        # chunks into duplicate recompute and inflate the regress-gated
        # replay_frac on a fault-free campaign. The beat stops the
        # moment the leg runs — a hung dispatch still expires via the
        # absence of per-group progress. The wait also observes the
        # campaign's shutdown: once the budget breaks the main loop, a
        # still-queued lease must NOT run its whole leg serially after
        # the campaign already gave up.
        while not _DEVICE_LEASE.acquire(timeout=0.5):
            if should_stop is not None and should_stop():
                raise LeaseCancelled("campaign stopped while queued")
            if heartbeat is not None:
                heartbeat()
        try:
            if heartbeat is not None:
                heartbeat()
            out = driver.train_arrays(
                self.points,
                self.cfg,
                mesh=self.mesh,
                checkpoint_dir=self.ckpt_dir,
                campaign=leg,
            )
        finally:
            _DEVICE_LEASE.release()
        return out.stats

    def finalize(self):
        from dbscan_tpu.parallel import driver

        with _DEVICE_LEASE:
            return driver.train_arrays(
                self.points,
                self.cfg,
                mesh=self.mesh,
                checkpoint_dir=self.ckpt_dir,
            )


# --- campaign-key invalidation (shared with bench.py) ------------------


def ensure_campaign_key(ckpt_dir: str, key: dict) -> bool:
    """A config change (n, maxpp, chunk/group slots) makes every banked
    chunk unloadable but NOT invisible: stale files would inflate
    chunks_done and mask real progress from the stall detector. The
    campaign key captures every knob the fingerprint depends on; a
    mismatch wipes the dir clean. Returns True when prior state was
    invalidated. (Hoisted from bench.py::m100_row so every campaign
    harness shares one invalidation rule.)"""
    os.makedirs(ckpt_dir, exist_ok=True)
    key_path = os.path.join(ckpt_dir, "campaign.json")
    prior = None
    if os.path.exists(key_path):
        try:
            with open(key_path) as f:
                prior = json.load(f)
        except (OSError, ValueError):
            # a PRESENT but unreadable key (torn write, foreign file)
            # must invalidate, not read as a fresh dir: skipping the
            # wipe here is exactly the stale-chunk-masking hazard this
            # function exists to prevent
            prior = "unreadable"
    invalidated = False
    if prior != key:
        if prior is not None:
            from dbscan_tpu.parallel import checkpoint as ckpt_mod

            ckpt_mod.invalidate_p1_chunk(ckpt_dir, 0)
            for stale in ("progress.json", "premerge.npz", "manifest.json"):
                try:
                    os.unlink(os.path.join(ckpt_dir, stale))
                except OSError:
                    pass
            invalidated = True
        tmp = key_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(key, f)
        os.replace(tmp, key_path)  # never leave a torn key behind
    return invalidated


# --- leg-progress signal (the stall detector's input) ------------------


def progress_counter(ckpt_dir: str) -> int:
    """The monotone chunk-write counter from the progress sidecar, or
    -1 when absent (pre-campaign dirs / no chunk ever banked)."""
    from dbscan_tpu.parallel import checkpoint as ckpt_mod

    try:
        return int(
            ckpt_mod.read_progress(ckpt_dir).get(
                ckpt_mod.PROGRESS_WRITE_COUNTER, -1
            )
        )
    except (TypeError, ValueError):
        return -1


def mtime_fresh_chunks(ckpt_dir: str, since: float) -> int:
    """Fallback leg-progress signal: p1chunk files (re)written at or
    after ``since`` (an epoch timestamp). mtime granularity and clock
    skew can misclassify a productive leg as stalled, which is why the
    sidecar counter is authoritative when present."""
    fresh = 0
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return 0
    for name in names:
        if name.startswith("p1chunk") and name.endswith(".npz"):
            try:
                if os.path.getmtime(os.path.join(ckpt_dir, name)) >= since:
                    fresh += 1
            except OSError:
                pass
    return fresh


def leg_progressed(
    ckpt_dir: str, counter_before: int, since: float
) -> bool:
    """Did a leg bank anything? The sidecar's monotone write counter is
    authoritative (written by the child under the progress file lock);
    mtimes are the fallback for dirs that predate the counter."""
    after = progress_counter(ckpt_dir)
    if after >= 0:
        return after > max(0, counter_before)
    return mtime_fresh_chunks(ckpt_dir, since) > 0


# --- frontier campaigns (subprocess legs, the m100 mold) ---------------


@dataclasses.dataclass
class FrontierResult:
    """Outcome of a frontier campaign: sequential full-train subprocess
    legs over one checkpoint dir, each banking whatever it reaches."""

    complete: bool
    legs: int
    wall_s: float
    work_wall_s: float
    replayed_wall_s: float
    replay_frac: float
    chunks_done: int
    chunks_total: Optional[int]
    stall_break: bool
    expired: int
    kills: int
    degrades: int = 0
    last_error: str = ""


def run_frontier(
    ckpt_dir: str,
    argv: Sequence[str],
    *,
    env: Optional[dict] = None,
    max_leases: int = 3,
    budget_s: float = 1500.0,
    leg_timeout_s: float = 3600.0,
    rest_s: float = 45.0,
    success_path: Optional[str] = None,
    lease_s: Optional[float] = None,
    poll_s: float = 0.5,
    count_done: Optional[Callable[[str], int]] = None,
) -> FrontierResult:
    """Run a frontier campaign: each lease launches ``argv`` as one
    subprocess leg (child_m100 / ``--leg`` mold) that resumes from the
    banked chunks and runs until completion or death. Keeps the m100
    harness's measured-honesty rules — a leg never outlives the
    remaining budget by more than the ~10-min floor that lets it reach
    its first restart points, and two consecutive legs with no progress
    signal (the sidecar counter, mtime fallback) break out instead of
    burning budget — and adds lease accounting, the priced replay
    budget, and the ``campaign``-site drills (TRANSIENT kills the child
    after its next banked chunk; PERSISTENT wedges the lease for
    ``lease_s`` so the next leg steals it).

    ``count_done`` overrides the banked-chunk census (default: the m100
    p1-chunk count) so campaigns over other restart-point grains — the
    embed engine's bucket-band files — price replay against THEIR
    durable artifacts; the sidecar progress counter stays the shared
    progress signal either way."""
    from dbscan_tpu.parallel import checkpoint as ckpt_mod

    if count_done is None:
        count_done = ckpt_mod.count_p1_chunks
    lease_s = float(
        lease_s if lease_s is not None
        else config.env("DBSCAN_CAMPAIGN_LEASE_S")
    )
    t0 = time.monotonic()
    tp0 = time.perf_counter()
    legs = 0
    stall = 0
    stall_break = False
    complete = False
    expired = 0
    kills = 0
    degraded = False
    degrades = 0
    work_wall = 0.0
    replayed_wall = 0.0
    last_err = ""
    campaign_active = faults.campaign_site_active()
    while legs < max_leases:
        remaining = budget_s - (time.monotonic() - t0)
        if legs and remaining <= 0:
            break
        legs += 1
        obs.count("campaign.leases")
        kind = _consume_campaign_fault()[0] if campaign_active else None
        if kind == faults.PERSISTENT:
            # wedged lease: nothing runs, nothing heartbeats; the wall
            # is pure waste and the next leg is the steal
            obs.count("campaign.wedges")
            obs.count("campaign.expired")
            obs.event("campaign.wedge", leg=legs, lease_s=lease_s)
            wedge_wall = min(lease_s, max(0.0, remaining))
            time.sleep(wedge_wall)
            expired += 1
            work_wall += wedge_wall
            replayed_wall += wedge_wall
            continue
        if kind == faults.RESOURCE_EXHAUSTED and not degraded:
            # tier drill, frontier shape: this and every later leg runs
            # on the CPU backend (the subprocess analog of the worker
            # fleet's whole-lease CPU degradation) — same algebra,
            # labels unchanged
            degraded = True
            degrades += 1
            env = {**(env or os.environ), "JAX_PLATFORMS": "cpu"}
            obs.count("campaign.degrades")
            obs.event("campaign.degrade", leg=legs, error="injected")
        counter0 = progress_counter(ckpt_dir)
        done0 = count_done(ckpt_dir)
        leg_start = time.time()
        t_leg = time.monotonic()
        # honor the campaign budget even against a WEDGED (not crashed)
        # worker: the floor lets a resumed leg reach its first restart
        # points (~10 min incl. datagen + re-pack at m100 scale)
        deadline = t_leg + min(leg_timeout_s, max(remaining, 600.0))
        rc = None
        with tempfile.TemporaryFile() as errf:
            proc = subprocess.Popen(
                list(argv),
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=errf,
            )
            killed = False
            try:
                while True:
                    rc = proc.poll()
                    if rc is not None:
                        break
                    now = time.monotonic()
                    if now >= deadline:
                        proc.kill()
                        proc.wait()
                        rc = None
                        last_err = "leg timeout"
                        break
                    if (
                        kind == faults.TRANSIENT
                        and not killed
                        and leg_progressed(ckpt_dir, counter0, leg_start)
                    ):
                        # deterministic preemption drill: the worker
                        # dies right after banking its next chunk
                        proc.kill()
                        killed = True
                        kills += 1
                        obs.count("campaign.kills")
                        obs.event("campaign.kill", leg=legs)
                    time.sleep(poll_s)
            finally:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
            if rc is not None and rc != 0:
                errf.seek(0)
                tail = errf.read()[-300:].decode(errors="replace")
                last_err = f"rc {rc}: {tail}".strip()
        wall = time.monotonic() - t_leg
        work_wall += wall
        ok = (
            rc == 0
            and not killed
            and (success_path is None or os.path.exists(success_path))
        )
        done1 = count_done(ckpt_dir)
        if ok:
            complete = True
            break
        if rc == 0 and not killed:
            # a clean-exit leg that produced no result file is its own
            # failure shape (wrong output path, result unlinked by a
            # concurrent campaign) — leave the breadcrumb the old m100
            # loop always recorded for any non-success leg
            last_err = f"leg exited 0 without {success_path}"
        # pro-rata replay pricing, consistent with ChunkQueue._wasted:
        # charge the share of the wall the leg ACTUALLY spent on work
        # that did not persist. A failed leg's wall bought `banked`
        # durable restart points plus one lost in-flight chunk's worth
        # of compute, so under the uniform-chunk approximation the
        # wasted share is 1/(banked+1) — a leg that banked nothing
        # wasted everything, and pricing never depends on how much of
        # the campaign happened to remain when the leg started (the
        # old remaining-chunks denominator overstated replay for legs
        # that died late, failing the regress gate on kill TIMING
        # rather than real restart overhead).
        banked = max(0, done1 - done0)
        frac_wasted = 1.0 / (banked + 1.0)
        replayed_wall += wall * frac_wasted
        obs.event(
            "campaign.leg",
            leg=legs,
            rc=-1 if rc is None else int(rc),
            banked=banked,
            wall_s=round(wall, 3),
        )
        # two consecutive legs with zero new restart points means the
        # worker is dying before any progress — stop burning budget
        if not leg_progressed(ckpt_dir, counter0, leg_start):
            stall += 1
            if stall >= 2:
                stall_break = True
                break
        else:
            stall = 0
        if legs < max_leases:
            time.sleep(rest_s)
    chunks_done = count_done(ckpt_dir)
    total = ckpt_mod.read_progress(ckpt_dir).get("chunks_total")
    obs.count("campaign.work_wall_s", work_wall)
    obs.count("campaign.replayed_wall_s", replayed_wall)
    obs.add_span(
        "campaign.run",
        tp0,
        time.perf_counter(),
        legs=legs,
        complete=complete,
        frontier=True,
    )
    obs.flush()  # the campaign tail must reach DBSCAN_TRACE's file
    return FrontierResult(
        complete=complete,
        legs=legs,
        wall_s=round(time.monotonic() - t0, 6),
        work_wall_s=round(work_wall, 6),
        replayed_wall_s=round(replayed_wall, 6),
        replay_frac=replay_frac(work_wall, replayed_wall),
        chunks_done=chunks_done,
        chunks_total=total,
        stall_break=stall_break,
        expired=expired,
        kills=kills,
        degrades=degrades,
        last_error=last_err[:200],
    )


# --- CLI ---------------------------------------------------------------


def demo_points(n: int, seed: int = 0) -> np.ndarray:
    """Deterministic mixed-density blobs: partitions land on several
    bucket-ladder rungs so the packer emits multiple groups (chunking
    is group-granular)."""
    rng = np.random.default_rng(seed)
    centers = [(0, 0), (8, 8), (-7, 9), (9, -8), (-9, -9), (16, 2)]
    weights = np.array([1, 3, 6, 15, 4, 11], dtype=np.float64)
    sizes = np.maximum(
        1, (weights / weights.sum() * n).astype(int)
    )
    pts = np.concatenate(
        [rng.normal(c, 0.4, (s, 2)) for c, s in zip(centers, sizes)]
    )
    rng.shuffle(pts)
    return pts


def train_resharded(pts, mesh, **train_kw):
    """One sharded train that survives chip drop (ROADMAP items 1+5
    composed): a retries-exhausted device fault re-shards the run onto
    a smaller mesh — half the devices, eventually single-device —
    instead of dying. Labels are mesh-decomposition-independent (the
    halo-merge fixed point and the dispatch sharding are pure layout;
    pinned by tests/test_meshshard.py), so every degraded rerun returns
    byte-identical output.

    Drills ride the ``campaign`` fault site with the one-ordinal-per-
    attempt discipline every campaign shape shares
    (:func:`_consume_campaign_fault`): a ``campaign#N`` clause kills
    attempt N before dispatch, exercising the re-shard path
    deterministically. ``DBSCAN_MESH_RESHARD=0`` lets faults propagate
    (the historical dead-run behavior).
    """
    from dbscan_tpu import train as _train
    from dbscan_tpu.parallel import mesh as mesh_mod

    cur = mesh
    attempt = 0
    while True:
        kind, _n = _consume_campaign_fault()
        try:
            if kind is not None:
                raise faults.FatalDeviceFault(
                    faults.SITE_CAMPAIGN, _n, 1,
                    RuntimeError(f"injected sharded-attempt fault: {kind}"),
                )
            return _train(pts, mesh=cur, **train_kw)
        except faults.FatalDeviceFault as e:
            k = mesh_mod.mesh_size(cur)
            if not config.env("DBSCAN_MESH_RESHARD") or k <= 1:
                raise
            # the fault carries no device attribution, so we cannot
            # route around the failed chip directly; ALTERNATE which
            # half survives each rung so a single bad chip is excluded
            # within two re-shards instead of riding a fixed low-index
            # prefix all the way down the ladder
            flat = list(cur.devices.flat)
            half = max(1, k // 2)
            devs = flat[half:] if attempt % 2 else flat[:half]
            attempt += 1
            new = mesh_mod.make_mesh(devs) if len(devs) > 1 else None
            obs.count("mesh.reshards")
            obs.event(
                "mesh.reshard",
                old_devices=k,
                new_devices=len(devs),
                error=str(e)[:200],
            )
            logger.warning(
                "sharded run lost its mesh (%s); re-sharding %d -> %d "
                "devices and rerunning (labels are decomposition-"
                "independent)",
                e,
                k,
                len(devs),
            )
            cur = new


def _cli_config(args):
    from dbscan_tpu.config import DBSCANConfig, Engine

    return DBSCANConfig(
        eps=args.eps,
        min_points=args.min_points,
        max_points_per_partition=args.maxpp,
        engine=Engine.ARCHERY,
        neighbor_backend="banded",
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.campaign",
        description="Elastic fault-priced campaign driver: run one "
        "clustering job as a work-stealing chunk-lease campaign over a "
        "worker fleet, with deterministic preemption drills "
        "(DBSCAN_FAULT_SPEC campaign#N clauses) and a priced replay "
        "budget (campaign_replay_frac).",
    )
    p.add_argument("--n", type=int, default=8000,
                   help="points in the deterministic demo dataset")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--eps", type=float, default=0.5)
    p.add_argument("--min-points", type=int, default=5, dest="min_points")
    p.add_argument("--maxpp", type=int, default=256,
                   help="max points per partition")
    p.add_argument("--ckpt", default=None,
                   help="checkpoint dir (default: a fresh temp dir)")
    p.add_argument("--workers", type=int, default=None,
                   help="fleet size (default DBSCAN_CAMPAIGN_WORKERS)")
    p.add_argument("--lease-s", type=float, default=None,
                   help="lease heartbeat expiry "
                   "(default DBSCAN_CAMPAIGN_LEASE_S)")
    p.add_argument("--budget-s", type=float, default=None,
                   help="campaign wall budget")
    p.add_argument("--chunk-slots", type=int, default=None,
                   help="compact chunk slot budget override (drill "
                   "knob: the env knob clamps at 2^16, too coarse for "
                   "laptop-scale multi-chunk drills)")
    p.add_argument("--fault-spec", default=None,
                   help="DBSCAN_FAULT_SPEC for this campaign, e.g. "
                   "'campaign#0:TRANSIENT;campaign#2:PERSISTENT'")
    p.add_argument("--verify", action="store_true",
                   help="also run a clean single-process train and "
                   "assert byte-identical labels")
    p.add_argument("--json", default=None,
                   help="write the capture record to this path "
                   "(bench-history-ingestible)")
    p.add_argument("--leg", action="store_true",
                   help="run ONE subprocess leg over --ckpt instead of "
                   "a whole campaign (the frontier/drill child entry)")
    p.add_argument("--chunks", default=None,
                   help="with --leg: comma-separated chunk ids to "
                   "lease (omitted = full frontier leg)")
    p.add_argument("--tier", default="device", choices=("device", "cpu"),
                   help="with --leg: dispatch tier")
    args = p.parse_args(argv)

    if args.fault_spec is not None:
        os.environ["DBSCAN_FAULT_SPEC"] = args.fault_spec
        faults.reset_registry()
    from dbscan_tpu.parallel import driver

    if args.chunk_slots is not None:
        driver._COMPACT_CHUNK_SLOTS = max(256, int(args.chunk_slots))
    pts = demo_points(args.n, args.seed)
    cfg = _cli_config(args)
    ckpt_dir = args.ckpt or tempfile.mkdtemp(prefix="dbscan_campaign_")

    if args.leg:
        if args.chunks is not None:
            chunks = frozenset(
                int(c) for c in args.chunks.split(",") if c.strip()
            )
            leg = driver.CampaignLeg(chunks=chunks, tier=args.tier)
            out = driver.train_arrays(
                pts, cfg, checkpoint_dir=ckpt_dir, campaign=leg
            )
            print(json.dumps(out.stats.get("campaign_chunks_done", [])))
        else:
            out = driver.train_arrays(pts, cfg, checkpoint_dir=ckpt_dir)
            print(
                json.dumps(
                    {
                        "n_clusters": int(out.n_clusters),
                        "resumed": bool(
                            out.stats.get("resumed_from_checkpoint", False)
                        ),
                    }
                )
            )
        return 0

    ensure_campaign_key(
        ckpt_dir,
        {
            "n": args.n,
            "seed": args.seed,
            "eps": args.eps,
            "min_points": args.min_points,
            "maxpp": args.maxpp,
            "chunk_slots": int(driver._COMPACT_CHUNK_SLOTS),
            "group_slots": int(config.env("DBSCAN_GROUP_SLOTS")),
        },
    )
    job = TrainChunkJob(pts, cfg, ckpt_dir)
    result = Campaign(
        job,
        workers=args.workers,
        lease_s=args.lease_s,
        budget_s=args.budget_s,
    ).run()
    import jax

    row = result.row("campaign")
    row["backend"] = jax.default_backend()
    row["campaign_n"] = args.n
    row["campaign_workers"] = result.workers
    if args.verify and result.output is not None:
        clean = driver.train_arrays(pts, cfg)
        row["labels_equal"] = bool(
            np.array_equal(clean.clusters, result.output.clusters)
            and np.array_equal(clean.flags, result.output.flags)
        )
    print(json.dumps(row, indent=2))
    if args.json:
        tmp = args.json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(row, f, indent=2)
        os.replace(tmp, args.json)  # never leave a torn row behind
    if not result.complete:
        return 1
    if args.verify and row.get("labels_equal") is False:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
