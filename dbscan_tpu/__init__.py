"""tpu-dbscan: a TPU-native distributed DBSCAN framework on JAX/XLA/Pallas/pjit.

A ground-up rebuild of the capabilities of ningchungui/dbscan-on-spark
(distributed 2-D DBSCAN via spatial domain decomposition with eps-halo
replication; reference layer map in /root/repo/SURVEY.md), re-designed
TPU-first:

- the per-partition O(n^2) BFS engine (reference LocalDBSCANNaive.scala:37-118)
  becomes a tiled pairwise-distance + min-label-propagation kernel that runs on
  the MXU/VPU under `jit` / Pallas;
- the Spark shuffle/broadcast fan-out (reference DBSCAN.scala:126-173) becomes
  `shard_map` over a `jax.sharding.Mesh`;
- the driver-side cluster-alias merge (reference DBSCAN.scala:179-228,
  DBSCANGraph.scala) becomes a host-side union-find over doubly-labeled halo
  points.

Public API mirrors the reference surface (DBSCAN.train -> model with
labeled_points / partitions / predict) while staying idiomatic JAX.
"""

from dbscan_tpu.config import DBSCANConfig, Engine, Precision
from dbscan_tpu.ops.labels import CORE, BORDER, NOISE, NOT_FLAGGED, UNKNOWN
from dbscan_tpu.models.dbscan import DBSCANModel, train
from dbscan_tpu.streaming import StreamingDBSCAN


def sparse_cosine_dbscan(*args, **kwargs):
    """Lazy re-export of :func:`dbscan_tpu.ops.sparse.sparse_cosine_dbscan`
    (keeps scipy an optional import for the dense-only paths)."""
    from dbscan_tpu.ops.sparse import sparse_cosine_dbscan as impl

    return impl(*args, **kwargs)


__version__ = "0.1.0"

__all__ = [
    "DBSCANConfig",
    "Engine",
    "Precision",
    "DBSCANModel",
    "train",
    "StreamingDBSCAN",
    "sparse_cosine_dbscan",
    "CORE",
    "BORDER",
    "NOISE",
    "NOT_FLAGGED",
    "UNKNOWN",
]
