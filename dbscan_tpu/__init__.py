"""tpu-dbscan: a TPU-native distributed DBSCAN framework on JAX/XLA/Pallas/pjit.

A ground-up rebuild of the capabilities of ningchungui/dbscan-on-spark
(distributed 2-D DBSCAN via spatial domain decomposition with eps-halo
replication; reference layer map in /root/repo/SURVEY.md), re-designed
TPU-first:

- the per-partition O(n^2) BFS engine (reference LocalDBSCANNaive.scala:37-118)
  becomes a tiled pairwise-distance + min-label-propagation kernel that runs on
  the MXU/VPU under `jit` / Pallas;
- the Spark shuffle/broadcast fan-out (reference DBSCAN.scala:126-173) becomes
  `shard_map` over a `jax.sharding.Mesh`;
- the driver-side cluster-alias merge (reference DBSCAN.scala:179-228,
  DBSCANGraph.scala) becomes a host-side union-find over doubly-labeled halo
  points.

Public API mirrors the reference surface (DBSCAN.train -> model with
labeled_points / partitions / predict) while staying idiomatic JAX.
"""

import os as _os

from dbscan_tpu.config import env as _env

# Persistent XLA compilation cache: the banded/dense executors compile one
# program per (bucket width, slab) shape — ~2 min of XLA time at 10M-point
# scale — and identical shapes recur across processes (ladder widths are
# quantized). Defers to any cache the user already configured (their env
# var or a prior jax.config call); opt out with DBSCAN_TPU_NO_COMPILE_CACHE=1.
if not _env("DBSCAN_TPU_NO_COMPILE_CACHE"):
    import jax as _jax

    if (
        not _os.environ.get("JAX_COMPILATION_CACHE_DIR")
        and _jax.config.jax_compilation_cache_dir is None
    ):
        _jax.config.update(
            "jax_compilation_cache_dir",
            _os.path.expanduser(_env("DBSCAN_TPU_COMPILE_CACHE_DIR")),
        )
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dbscan_tpu.config import DBSCANConfig, Engine, Precision
from dbscan_tpu.ops.labels import CORE, BORDER, NOISE, NOT_FLAGGED, UNKNOWN
from dbscan_tpu.models.dbscan import DBSCANModel, train
from dbscan_tpu.streaming import StreamingDBSCAN


def sparse_cosine_dbscan(*args, **kwargs):
    """Lazy re-export of :func:`dbscan_tpu.ops.sparse.sparse_cosine_dbscan`
    (keeps scipy an optional import for the dense-only paths)."""
    from dbscan_tpu.ops.sparse import sparse_cosine_dbscan as impl

    return impl(*args, **kwargs)


def embed_dbscan(*args, **kwargs):
    """Lazy re-export of :func:`dbscan_tpu.embed.embed_dbscan` — the
    high-dimensional cosine engine (LSH binning + spill-tree fallback +
    blocked MXU neighbor kernel; dbscan_tpu/embed)."""
    from dbscan_tpu.embed import embed_dbscan as impl

    return impl(*args, **kwargs)


def hdbscan(*args, **kwargs):
    """Lazy re-export of :func:`dbscan_tpu.density.hdbscan` — the
    variable-density engine (device core distances + Borůvka
    mutual-reachability MST + condensed-tree EOM labels;
    dbscan_tpu/density)."""
    from dbscan_tpu.density import hdbscan as impl

    return impl(*args, **kwargs)


def optics(*args, **kwargs):
    """Lazy re-export of :func:`dbscan_tpu.density.optics` — the OPTICS
    reachability ordering off the same sorted mutual-reachability MST
    (dbscan_tpu/density)."""
    from dbscan_tpu.density import optics as impl

    return impl(*args, **kwargs)


__version__ = "0.1.0"

__all__ = [
    "DBSCANConfig",
    "Engine",
    "Precision",
    "DBSCANModel",
    "train",
    "StreamingDBSCAN",
    "sparse_cosine_dbscan",
    "embed_dbscan",
    "hdbscan",
    "optics",
    "CORE",
    "BORDER",
    "NOISE",
    "NOT_FLAGGED",
    "UNKNOWN",
]
