"""Host numpy cosine-DBSCAN oracle: exact, small-N, no JAX.

Two consumers, one implementation:

- the ``embed`` fault site's PERSISTENT degradation path
  (``dbscan_tpu/embed/engine.py``): a bucket whose neighbor dispatch
  exhausts its retries runs here instead of aborting the run, and a
  persistently-failing hash dispatch degrades the WHOLE run here — the
  numpy analog of the dense driver's per-group CPU ``local_dbscan``
  fallback;
- test parity assertions (``tests/test_embed.py``): the engine's exact
  path must reproduce these labels on fuzzed ``[N, D]`` inputs.

Semantics are the package's standard label algebra
(``ops/local_dbscan.py``), computed in float64:

- cosine distance ``1 - dot`` on L2-normalized rows; adjacency
  ``dist <= eps``, self-inclusive; core at ``counts >= min_points``;
- a cluster's seed label is the minimum core row index of its
  core-core component;
- border algebra per engine: ARCHERY adopts any non-core point with a
  core neighbor, NAIVE additionally requires the min adjacent seed to
  precede the point's own row index;
- :func:`cosine_dbscan_oracle` numbers clusters canonically by minimum
  MEMBER row (the ``finalize_merge(canonical=True)`` rule), so its
  label vector is directly comparable to the engine's merged output.

Everything here is dense O(N^2) host math — the exactness reference,
never a production path. :data:`ORACLE_MAX_POINTS` caps the
degradation path so a faulting 10M-point run fails loudly instead of
allocating an 800 TB similarity matrix.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from dbscan_tpu.ops.labels import (
    BORDER,
    CORE,
    NOISE,
    NOT_FLAGGED,
    SEED_NONE,
)

#: largest N the fault-degradation path accepts (the [N, N] f64
#: similarity is 80 GB here; past it the original device fault
#: re-raises — an oracle that OOMs the host is not a degradation)
ORACLE_MAX_POINTS = 100_000


def normalize_rows(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(L2-normalized float64 copy, row norms); zero-norm rows stay
    zero (similarity 0 to everything, the sparse front-end's
    convention)."""
    x = np.asarray(x, dtype=np.float64)
    norms = np.linalg.norm(x, axis=1)
    inv = np.where(norms > 0, 1.0 / np.maximum(norms, 1e-300), 0.0)
    return x * inv[:, None], norms


def oracle_local(
    unit: np.ndarray, eps: float, min_points: int, engine: str = "archery"
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One partition's exact labels over PRE-NORMALIZED rows.

    Returns ``(seed_labels [n] int32, flags [n] int8, counts [n]
    int32)`` in the positional conventions of
    ``ops.local_dbscan.cluster_from_adjacency`` — the drop-in shape the
    engine's per-bucket fault fallback needs (labels are positions
    WITHIN this row block).
    """
    if engine not in ("naive", "archery"):
        raise ValueError(f"unknown engine {engine!r}")
    unit = np.asarray(unit, dtype=np.float64)
    n = len(unit)
    none = np.int32(SEED_NONE)
    if n == 0:
        return (
            np.empty(0, np.int32),
            np.empty(0, np.int8),
            np.empty(0, np.int32),
        )
    dist = 1.0 - unit @ unit.T
    adj = dist <= float(eps)
    np.fill_diagonal(adj, True)  # self-inclusive regardless of eps
    counts = adj.sum(axis=1).astype(np.int32)
    core = counts >= int(min_points)

    # core-core components by BFS; comp = min core row per component
    comp = np.full(n, none, dtype=np.int32)
    adj_cc = adj & core[None, :] & core[:, None]
    seen = np.zeros(n, dtype=bool)
    for i in np.flatnonzero(core):
        if seen[i]:
            continue
        members = [i]
        seen[i] = True
        frontier = [i]
        while frontier:
            nxt = np.flatnonzero(adj_cc[frontier].any(axis=0) & ~seen)
            seen[nxt] = True
            members.extend(nxt.tolist())
            frontier = nxt.tolist()
        comp[members] = min(members)

    # min seed among eps-adjacent cores (cores see their own component)
    nbr = np.where(adj & core[None, :], comp[None, :], none)
    core_nbr_seed = nbr.min(axis=1).astype(np.int32)
    has_core_nbr = core_nbr_seed != none
    idx = np.arange(n, dtype=np.int32)
    if engine == "naive":
        border = ~core & has_core_nbr & (core_nbr_seed < idx)
    else:
        border = ~core & has_core_nbr

    seed_labels = np.where(
        core, comp, np.where(border, core_nbr_seed, none)
    ).astype(np.int32)
    flags = np.where(
        core,
        np.int8(CORE),
        np.where(border, np.int8(BORDER), np.int8(NOISE)),
    ).astype(np.int8)
    return seed_labels, flags, counts


def canonical_ids(seed_labels: np.ndarray) -> np.ndarray:
    """Seed labels -> canonical 1-based cluster ids, numbered by each
    cluster's minimum MEMBER row (border members included) — exactly
    ``finalize_merge(canonical=True)``'s rule, so oracle and engine
    label vectors compare with plain array equality. Noise maps to 0."""
    seed_labels = np.asarray(seed_labels)
    out = np.zeros(len(seed_labels), dtype=np.int32)
    mask = seed_labels != SEED_NONE
    if not mask.any():
        return out
    uniq, inv = np.unique(seed_labels[mask], return_inverse=True)
    first = np.full(len(uniq), len(seed_labels), dtype=np.int64)
    np.minimum.at(first, inv, np.flatnonzero(mask))
    order = np.argsort(first, kind="stable")
    rank = np.empty(len(uniq), dtype=np.int32)
    rank[order] = np.arange(1, len(uniq) + 1, dtype=np.int32)
    out[mask] = rank[inv]
    return out


def cosine_dbscan_oracle(
    x: np.ndarray, eps: float, min_points: int, engine: str = "archery"
) -> Tuple[np.ndarray, np.ndarray]:
    """Full-run exact cosine DBSCAN on the host.

    Returns ``(clusters [N] int32 with 0 = noise, flags [N] int8)`` in
    the engine's output conventions with canonical (min-member-row)
    cluster numbering. Rows are normalized here; zero rows keep
    similarity 0 to everything and cluster only when ``eps >= 1``.
    """
    unit, _norms = normalize_rows(x)
    if len(unit) > ORACLE_MAX_POINTS:
        raise ValueError(
            f"cosine oracle is exact small-N host math: {len(unit)} "
            f"points exceeds ORACLE_MAX_POINTS={ORACLE_MAX_POINTS} "
            "(the [N, N] f64 similarity would not fit host memory)"
        )
    seed, flags, _counts = oracle_local(unit, eps, min_points, engine)
    return canonical_ids(seed), flags


__all__ = [
    "ORACLE_MAX_POINTS",
    "normalize_rows",
    "oracle_local",
    "canonical_ids",
    "cosine_dbscan_oracle",
    "BORDER",
    "CORE",
    "NOISE",
    "NOT_FLAGGED",
]
