"""The embed engine: high-dimensional cosine DBSCAN for [N, D]
normalized embeddings (D up to 768 and beyond).

Pipeline (every stage wired the way the other engines are wired):

1. ``embed.hash`` device dispatch (ONE matmul) projects the payload
   onto the SRP tables (``embed/lsh.py``);
2. host boundary-spill binning over the primary table's projections —
   exact coverage by construction, with the pivot spill tree
   (``parallel/spill.py`` + PR 8's device-resident build) as the exact
   fallback partitioner for nodes no hyperplane can split;
3. one ``embed.neighbors`` dispatch per bucket (``embed/neighbors.py``:
   blocked MXU similarity slabs -> windowed neighbor tables ->
   ``ops/propagation.window_cc`` -> the shared border algebra), each
   under :func:`dbscan_tpu.faults.supervised` at the ``embed`` site —
   transients heal with backoff, a PERSISTENT fault degrades THAT
   bucket to the numpy host oracle (``embed/oracle.py``), and a
   persistently-failing hash dispatch degrades the WHOLE run to the
   oracle (small-N capped);
4. per-bucket label pulls ride the PullEngine
   (``parallel/pipeline.py``) so D2H transfers overlap the remaining
   bucket dispatches — the driver's label-pull discipline;
5. the shared instance-table merge (``parallel/driver.finalize_merge``,
   canonical min-member-row numbering): flags are exact on any input
   (the binning's neighborhood-completeness invariant), memberships
   exact up to the reference's border-bridged merges
   (DBSCAN.scala:161-173 — the grid driver's documented semantic), and
   on bridge-free workloads the label vector is a function of the DATA
   alone — LSH seed, bucket layout, and spill fallbacks cannot move a
   label (the renumbering contract the tests pin).

Subsampled-edge mode (``DBSCAN_EMBED_SAMPLE_FRAC`` or the
``sample_frac`` argument): each candidate edge survives a
deterministic symmetric coin with the declared probability and the
core threshold scales to match (``neighbors.eff_min_points``) — the
explicit accuracy knob; ``bench.py --embed`` reports the resulting ARI
against the exact path and the regression gate holds it to the
declared floor (PARITY.md "Embed accuracy contract").
"""

from __future__ import annotations

import functools
import hashlib
import logging
import os
import time
from typing import Tuple

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.embed import lsh, neighbors, oracle
from dbscan_tpu.embed import quantize as quantize_mod
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.ops import propagation as prop_propagation
from dbscan_tpu.ops.labels import NOISE, NOT_FLAGGED, SEED_NONE
from dbscan_tpu.parallel.binning import _ladder_width

logger = logging.getLogger(__name__)

#: collective halo-merge ratchet floors (binning._ratchet idiom):
#: module-global so repeated sharded embed runs reuse exact jit
#: signatures instead of re-padding per run
_MERGE_FLOORS: dict = {}

#: bucket-band checkpoint file (one durable restart point per band)
_BAND_FILE = "emband{:05d}.npz"
_BAND_FMT = 1


def shard_active(mesh) -> bool:
    """True when embed dispatch shards over ``mesh``: a real
    (multi-device) mesh with ``DBSCAN_EMBED_SHARD`` on."""
    from dbscan_tpu.parallel import mesh as mesh_mod

    return (
        mesh is not None
        and mesh_mod.mesh_size(mesh) > 1
        and bool(config.env("DBSCAN_EMBED_SHARD"))
    )


def _bucket_owner(counts_p: np.ndarray, k: int) -> np.ndarray:
    """[n_parts] owning-device index: contiguous bucket bands balanced
    by INSTANCE count (the work proxy), the embed analog of
    ``mesh.parts_spec``'s contiguous block ownership. Bucket p goes to
    the band its cumulative-instance midpoint falls in, so owners are
    monotone nondecreasing — each chip owns one contiguous band."""
    n_parts = len(counts_p)
    if n_parts == 0 or k <= 1:
        return np.zeros(n_parts, dtype=np.int32)
    cum = np.cumsum(counts_p, dtype=np.float64)
    total = float(cum[-1])
    if total <= 0:
        return np.zeros(n_parts, dtype=np.int32)
    mid = cum - counts_p / 2.0
    owner = np.floor(mid / total * k).astype(np.int32)
    return np.clip(owner, 0, k - 1)


def _band_ranges(n_parts: int):
    """Bucket-band chunking of the campaign/checkpoint grain:
    ``DBSCAN_EMBED_BAND`` buckets per band (0 = auto, ~8 bands).
    Returns ``(band_size, n_bands)``."""
    band_size = int(config.env("DBSCAN_EMBED_BAND"))
    if band_size <= 0:
        band_size = max(1, -(-n_parts // 8))
    return band_size, max(1, -(-n_parts // band_size))


def count_banked_bands(ckpt_dir: str) -> int:
    """Banked bucket-band files in ``ckpt_dir`` — the frontier
    campaign's ``count_done`` hook (fingerprints are verified at load
    time, not here; the p1-chunk counting discipline)."""
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return 0
    return sum(
        1 for nm in names
        if nm.startswith("emband") and nm.endswith(".npz")
    )


def _band_fingerprint(
    unit32, eps, min_points, engine, maxpp, seed, frac, quant,
    n_parts, band_size,
) -> str:
    """Digest of everything that determines a band's bytes: the
    (sampled) payload plus every knob that moves the binning or the
    per-bucket results. checkpoint.run_fingerprint's sampling rationale
    applies verbatim — same-machine resume, not content addressing."""
    h = hashlib.sha256()
    h.update(
        f"emb{_BAND_FMT}|{unit32.shape}|{unit32.dtype}|{float(eps)}|"
        f"{int(min_points)}|{engine}|{int(maxpp)}|{int(seed)}|"
        f"{float(frac)}|{quant}|{int(n_parts)}|{int(band_size)}|"
        .encode()
    )
    step = max(1, len(unit32) // 4096)
    for a in (unit32[:4096], unit32[-4096:], unit32[::step]):
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()[:16]


def _resolve_frac(sample_frac) -> float:
    """The sampled-edge fraction: explicit argument wins, else the
    ``DBSCAN_EMBED_SAMPLE_FRAC`` knob; 0 (the default) means the exact
    path."""
    explicit = sample_frac is not None
    if sample_frac is None:
        sample_frac = float(config.env("DBSCAN_EMBED_SAMPLE_FRAC"))
    frac = float(sample_frac)
    if frac == 0.0:
        return 1.0
    if not 0.0 < frac <= 1.0:
        # a negative typo must not silently run (and report) the exact
        # path as if it were a benchmarked approximation
        raise ValueError(
            f"sample_frac must be in (0, 1], got {frac}"
            + ("" if explicit else " (DBSCAN_EMBED_SAMPLE_FRAC)")
        )
    return frac


def embed_dbscan(
    x: np.ndarray,
    eps: float,
    min_points: int,
    engine: str = "archery",
    max_points_per_partition: int = 4096,
    seed: int = 0,
    sample_frac: float = None,
    oracle_fallback: bool = True,
    stats_out: dict = None,
    mesh=None,
    quantizer: str = None,
    checkpoint_dir: str = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Cosine DBSCAN over dense ``[N, D]`` embeddings.

    Rows are L2-normalized internally (zero rows keep similarity 0 to
    everything and are noise for ``eps < 1``). Returns ``(clusters [N]
    int32 with 0 = noise, flags [N] int8)`` in the package's standard
    conventions with canonical (min-member-row) cluster numbering.

    ``engine``: border semantics, ``"naive"`` | ``"archery"`` (a
    :class:`dbscan_tpu.config.Engine` value is accepted).
    ``max_points_per_partition`` bounds the per-bucket similarity
    working set; ``seed`` fixes the SRP planes and the spill tree's
    pivot draws; ``sample_frac`` opts into the subsampled-edge mode
    (None reads ``DBSCAN_EMBED_SAMPLE_FRAC``); ``oracle_fallback``
    controls the persistent-fault degradation to the host oracle;
    ``stats_out`` (optional dict) receives run diagnostics in the
    driver's stats idiom (``n_partitions``, ``duplication_factor``,
    ``timings``, embed counters).

    ``mesh`` (a ``jax.sharding.Mesh``) shards the run over the device
    mesh when ``DBSCAN_EMBED_SHARD`` is on: the hash dispatch runs
    row-sharded, each chip owns a contiguous instance-balanced band of
    buckets (chip-local neighbor dispatches), and the finalize routes
    the border-union step through the collective halo-merge
    (``parallel/halo.py``) — labels byte-identical to the unsharded run
    (PARITY.md "Sharded embed contract"). ``quantizer`` picks the
    binning front-end (``'srp'`` | ``'ivf'``; None reads
    ``DBSCAN_EMBED_QUANTIZER``). ``checkpoint_dir`` banks per-
    bucket-band results as durable restart points (the campaign grain:
    a killed run resumes from the banked bands and finalizes
    byte-identically; ``campaign.run_frontier`` legs ride this).
    """
    engine = getattr(engine, "value", engine)
    if engine not in ("naive", "archery"):
        raise ValueError(f"unknown engine {engine!r}")
    if not float(eps) > 0:
        raise ValueError(f"eps must be > 0, got {eps}")
    if int(min_points) < 1:
        raise ValueError(f"min_points must be >= 1, got {min_points}")
    x = np.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"expected [N, D] embeddings, got shape {x.shape}")
    maxpp = int(max_points_per_partition)
    if maxpp < 1:
        raise ValueError(
            f"max_points_per_partition must be >= 1, got {maxpp}"
        )
    frac = _resolve_frac(sample_frac)
    if quantizer is None:
        quant = quantize_mod.default_quantizer()
    else:
        quant = str(quantizer).lower()
        if quant not in ("srp", "ivf"):
            raise ValueError(
                f"quantizer must be 'srp' or 'ivf', got {quantizer!r}"
            )
    obs.ensure_env()

    n = len(x)
    if n == 0:
        if stats_out is not None:
            stats_out.update(n_partitions=0, duplication_factor=0.0)
        return np.empty(0, np.int32), np.empty(0, np.int8)

    # normalize straight into f32 (the driver's cosine-route
    # discipline): an f64 intermediate of the whole payload would be
    # 2x the input bytes of pure transient at 10M x 768 scale. Norms
    # accumulate in f64 (cheap [N] vector) for stable zero detection.
    x32 = np.asarray(x, dtype=np.float32)
    norms = np.sqrt(np.einsum("ij,ij->i", x32, x32, dtype=np.float64))
    inv = np.where(norms > 0, 1.0 / np.maximum(norms, 1e-30), 0.0)
    unit = x32 * inv.astype(np.float32)[:, None]
    nz_rows = np.flatnonzero(norms > 0)
    if float(eps) < 1.0 and len(nz_rows) < n:
        # zero rows are sim-0 to everything: deterministic noise under
        # eps < 1, and inside the partitioner they would be equidistant
        # to every pivot/hyperplane (the sparse front-end's strip)
        clusters = np.zeros(n, dtype=np.int32)
        flags = np.full(n, NOISE, dtype=np.int8)
        if len(nz_rows):
            sub_c, sub_f = _embed_unit(
                unit[nz_rows], eps, min_points,
                engine, maxpp, seed, frac, oracle_fallback, stats_out,
                mesh, quant, checkpoint_dir,
            )
            clusters[nz_rows] = sub_c
            flags[nz_rows] = sub_f
            if stats_out is not None and "duplication_factor" in stats_out:
                stats_out["duplication_factor"] = float(
                    stats_out["duplication_factor"] * len(nz_rows) / n
                )
        elif stats_out is not None:
            stats_out.update(n_partitions=0, duplication_factor=0.0)
        if stats_out is not None:
            stats_out["n_zero_norm_noise"] = int(n - len(nz_rows))
        return clusters, flags
    return _embed_unit(
        unit, eps, min_points, engine, maxpp, seed,
        frac, oracle_fallback, stats_out, mesh, quant, checkpoint_dir,
    )


def _whole_run_oracle(unit32, eps, min_points, engine, stats_out, t0):
    """The persistent-hash-fault degradation: the exact numpy oracle
    over the whole (small-N-capped) run."""
    obs.count("embed.oracle_fallbacks")
    logger.warning(
        "embed: hash dispatch persistently failing; degrading the "
        "whole run to the host oracle (%d points)", len(unit32)
    )
    seed_l, flags, _counts = oracle.oracle_local(
        np.asarray(unit32, dtype=np.float64), eps, min_points, engine
    )
    clusters = oracle.canonical_ids(seed_l)
    if stats_out is not None:
        stats_out.update(
            n_partitions=1,
            duplication_factor=1.0,
            embed_degraded="oracle",
            sample_frac=1.0,
            timings={"total_s": round(time.perf_counter() - t0, 6)},
        )
    return clusters, flags


def _embed_unit(
    unit32, eps, min_points, engine, maxpp, seed, frac,
    oracle_fallback, stats_out, mesh=None, quant="srp",
    checkpoint_dir=None,
):
    """The engine body over PRE-NORMALIZED f32 rows (no zero rows)."""
    import jax

    from dbscan_tpu.parallel import mesh as mesh_mod
    from dbscan_tpu.parallel import pipeline as pipe_mod
    from dbscan_tpu.parallel import spill as spill_mod
    from dbscan_tpu.parallel.driver import _check_dense_width, finalize_merge

    t_start = time.perf_counter()
    n, dim = unit32.shape
    obs.count("embed.points", int(n))
    obs.gauge("embed.sample_frac", float(frac))
    shard = shard_active(mesh)
    n_shards = mesh_mod.mesh_size(mesh) if shard else 1
    devices = list(mesh.devices.flat) if shard else None
    if shard:
        obs.gauge("embed.shards", float(n_shards))
    # spill halo in chord units; the quantization term covers the
    # neighbor kernel's f32 similarity rounding (error ~ D * 2^-24 per
    # dot), so every kernel-accepted pair is inside the spill band
    halo = spill_mod.chord_halo(eps, dim * 2.0**-23, dim=dim)
    bin_info: dict = {}

    def spill_fallback(idx):
        return spill_mod.spill_partition(
            unit32[idx], maxpp, halo, seed=seed
        )

    with obs.span("embed.run", n=int(n), d=int(dim)):
        if n <= maxpp:
            part_ids = np.zeros(n, dtype=np.int64)
            point_idx = np.arange(n, dtype=np.int64)
            n_parts = 1
            home_of = np.zeros(n, dtype=np.int32)
            bin_info = {
                "buckets": 1, "fallbacks": 0, "fallback_points": 0,
                "occupancy": [n],
            }
            t_hash = t_bin = time.perf_counter()
        elif quant == "ivf":
            # IVF coarse-quantizer front-end: the spill tree's
            # fp/Lloyd kernels place k-means cells, the exact r_c+halo
            # bands are the copy-set (embed/quantize.py); the hash
            # stage does not run at all
            t_hash = time.perf_counter()
            try:
                with obs.span("embed.bin", n=int(n)):
                    part_ids, point_idx, n_parts, home_of = (
                        quantize_mod.ivf_bin_points(
                            unit32, halo, maxpp, seed, spill_fallback,
                            info=bin_info,
                        )
                    )
            except faults.FatalDeviceFault:
                # same gate as a persistently-failing hash dispatch:
                # the quantizer IS the front-end dispatch on this route
                if not oracle_fallback or n > oracle.ORACLE_MAX_POINTS:
                    raise
                return _whole_run_oracle(
                    unit32, eps, min_points, engine, stats_out, t_start
                )
            t_bin = time.perf_counter()
        else:
            bits = lsh.default_bits()
            tables = lsh.default_tables()
            d_pad = _ladder_width(dim, 8)
            n_pad = _ladder_width(n, 128)
            planes = lsh.make_planes(d_pad, bits, tables, seed)
            x_pad = np.zeros((n_pad, d_pad), dtype=np.float32)
            x_pad[:n, :dim] = unit32
            try:
                _codes, proj0 = lsh.hash_points(
                    x_pad, planes, bits, tables,
                    sharding=(
                        jax.sharding.NamedSharding(
                            mesh, mesh_mod.parts_spec(mesh)
                        )
                        if shard
                        else None
                    ),
                )
            except faults.FatalDeviceFault:
                if not oracle_fallback or n > oracle.ORACLE_MAX_POINTS:
                    raise
                return _whole_run_oracle(
                    unit32, eps, min_points, engine, stats_out, t_start
                )
            t_hash = time.perf_counter()

            with obs.span("embed.bin", n=int(n)):
                part_ids, point_idx, n_parts, home_of = lsh.bin_points(
                    proj0[:n], halo, maxpp, spill_fallback, info=bin_info
                )
            t_bin = time.perf_counter()

        obs.count("embed.buckets", int(bin_info["buckets"]))
        if bin_info["fallbacks"]:
            obs.count("embed.spill_fallbacks", int(bin_info["fallbacks"]))
            obs.count(
                "embed.spill_fallback_points",
                int(bin_info["fallback_points"]),
            )
        lsh.occupancy_counters(bin_info["occupancy"])
        m_tot = len(part_ids)
        obs.count("embed.instances", int(m_tot))

        counts_p = np.bincount(part_ids, minlength=n_parts).astype(np.int64)
        offsets = np.r_[0, np.cumsum(counts_p)]
        widths = np.array(
            [_ladder_width(int(c), 128) for c in counts_p], dtype=np.int64
        )
        if len(widths):
            _check_dense_width(int(widths.max()), int(counts_p.max()))
        max_b = int(widths.max()) if len(widths) else 0

        inst_seed = np.full(m_tot, SEED_NONE, dtype=np.int32)
        inst_flag = np.full(m_tot, NOT_FLAGGED, dtype=np.int8)
        eff_min = neighbors.eff_min_points(min_points, frac)
        keep_num = neighbors.keep_threshold(frac)
        pull_pipe = pipe_mod.get_engine()
        # contiguous instance-balanced bucket bands, one per chip — the
        # embed analog of mesh.parts_spec's contiguous block ownership
        owner = (
            _bucket_owner(counts_p, n_shards)
            if shard
            else np.zeros(n_parts, dtype=np.int32)
        )
        results: dict = {}
        edges = 0
        cc_iters_max = 0
        prop_sweeps = 0
        escalations = 0
        oracle_buckets = [0]  # mutable: bumped inside the fallback

        def _oracle_bucket(rows_idx, b):
            """Per-bucket persistent-fault degradation: the numpy
            oracle over this bucket's rows, padded to the dispatch
            width (exact — a degraded bucket ignores the sampling
            coin, documented in PARITY.md)."""
            sub = np.asarray(
                unit32[rows_idx], dtype=np.float64
            )
            seed_l, flags_l, counts_l = oracle.oracle_local(
                sub, eps, min_points, engine
            )
            c = len(rows_idx)
            seed_p = np.full(b, SEED_NONE, np.int32)
            flag_p = np.full(b, NOT_FLAGGED, np.int8)
            cnt_p = np.zeros(b, np.int32)
            seed_p[:c] = seed_l
            flag_p[:c] = flags_l
            cnt_p[:c] = counts_l
            obs.count("embed.oracle_fallbacks")
            oracle_buckets[0] += 1
            return seed_p, flag_p, cnt_p, np.bool_(False), np.int32(0)

        def _dispatch(p: int, w: int):
            """One supervised ``embed.neighbors`` dispatch for bucket
            ``p`` at W rung ``w``; sharded runs place the inputs on the
            bucket's owning chip first (jit follows placement, so the
            dispatch runs chip-local)."""
            import jax.numpy as jnp

            lo, hi = int(offsets[p]), int(offsets[p + 1])
            rows_idx = point_idx[lo:hi]
            c = hi - lo
            b = int(widths[p])
            xb = np.zeros((b, dim), dtype=np.float32)
            xb[:c] = unit32[rows_idx]
            maskb = np.zeros(b, dtype=bool)
            maskb[:c] = True
            ids = np.full(b, -1, dtype=np.int32)
            ids[:c] = rows_idx
            fn = neighbors._neighbors_fn(b, int(w), engine)
            obs.count("embed.neighbor_dispatches")
            fallback = (
                functools.partial(_oracle_bucket, rows_idx, b)
                if oracle_fallback
                else None
            )

            def _call(_budget):
                xb_d = jnp.asarray(xb)
                maskb_d = jnp.asarray(maskb)
                ids_d = jnp.asarray(ids)
                if shard:
                    dev = devices[int(owner[p])]
                    xb_d = jax.device_put(xb_d, dev)
                    maskb_d = jax.device_put(maskb_d, dev)
                    ids_d = jax.device_put(ids_d, dev)
                return obs_compile.tracked_call(
                    "embed.neighbors",
                    fn,
                    xb_d,
                    maskb_d,
                    ids_d,
                    float(eps),
                    int(eff_min),
                    int(keep_num),
                    int(seed),
                )

            span_args = {"p": int(p), "b": b, "w": int(w)}
            if shard:
                span_args["shard"] = int(owner[p])
            with obs.span("embed.bucket", **span_args):
                out = faults.supervised(
                    faults.SITE_EMBED,
                    _call,
                    fallback=fallback,
                    label=f"bucket{p}",
                )
            obs.count("transfer.h2d_bytes", int(xb.nbytes + maskb.nbytes))
            return out

        def _land(p: int, out):
            """Pull one bucket's labels to host (PullEngine worker when
            live) and bank them for assembly/escalation."""
            if isinstance(out[0], np.ndarray):
                seed_h, flag_h, cnt_h, ovf, iters = out  # oracle path
            else:
                seed_h, flag_h, cnt_h, ovf, iters = jax.device_get(out)
                obs.count(
                    "transfer.d2h_bytes",
                    int(
                        np.asarray(seed_h).nbytes
                        + np.asarray(flag_h).nbytes
                        + np.asarray(cnt_h).nbytes
                    ),
                )
            results[p] = (
                np.asarray(seed_h),
                np.asarray(flag_h),
                np.asarray(cnt_h),
                bool(ovf),
                int(iters),
            )

        band_size, n_bands = _band_ranges(n_parts)
        bands_loaded = 0
        fingerprint = None
        ckpt_mod = None
        if checkpoint_dir is not None:
            from dbscan_tpu.parallel import checkpoint as ckpt_mod

            os.makedirs(checkpoint_dir, exist_ok=True)
            fingerprint = _band_fingerprint(
                unit32, eps, min_points, engine, maxpp, seed, frac,
                quant, n_parts, band_size,
            )
            ckpt_mod.write_progress(
                checkpoint_dir, chunks_total=int(n_bands)
            )

        def _load_band(band: int, lo_b: int, hi_b: int) -> bool:
            """Restore one banked band; False (re-run the band) on any
            mismatch — a stale fingerprint must never splice another
            run's instances into this one."""
            nonlocal edges, cc_iters_max, prop_sweeps, bands_loaded
            path = os.path.join(
                checkpoint_dir, _BAND_FILE.format(band)
            )
            lo0, hi0 = int(offsets[lo_b]), int(offsets[hi_b])
            try:
                with np.load(path, allow_pickle=False) as z:
                    if str(z["fp"]) != fingerprint:
                        return False
                    seed_b = np.asarray(z["seed"], dtype=np.int32)
                    flag_b = np.asarray(z["flag"], dtype=np.int8)
                    if len(seed_b) != hi0 - lo0:
                        return False
                    inst_seed[lo0:hi0] = seed_b
                    inst_flag[lo0:hi0] = flag_b
                    edges += int(z["edges"])
                    cc_iters_max = max(cc_iters_max, int(z["iters"]))
                    prop_sweeps += int(z["sweeps"])
            except (OSError, KeyError, ValueError):
                return False
            obs.count("embed.bands_loaded")
            bands_loaded += 1
            return True

        def _bank_band(band, lo_b, hi_b, edges_b, iters_b, sweeps_b):
            """Bank one settled band atomically (tmp + rename), then
            bump the sidecar progress counter — the frontier campaign's
            ``leg_progressed`` signal."""
            path = os.path.join(
                checkpoint_dir, _BAND_FILE.format(band)
            )
            lo0, hi0 = int(offsets[lo_b]), int(offsets[hi_b])
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                np.savez(
                    fh,
                    fp=np.asarray(fingerprint),
                    seed=inst_seed[lo0:hi0],
                    flag=inst_flag[lo0:hi0],
                    edges=np.int64(edges_b),
                    iters=np.int64(iters_b),
                    sweeps=np.int64(sweeps_b),
                )
            os.replace(tmp, path)
            obs.count("embed.bands_banked")
            ckpt_mod.bump_progress(
                checkpoint_dir, ckpt_mod.PROGRESS_WRITE_COUNTER
            )

        dur_dispatch = 0.0
        dur_pull = 0.0
        for band in range(n_bands):
            lo_b = band * band_size
            hi_b = min(n_parts, lo_b + band_size)
            if checkpoint_dir is not None and _load_band(
                band, lo_b, hi_b
            ):
                continue
            t_b0 = time.perf_counter()
            edges0, sweeps0 = edges, prop_sweeps
            band_iters = 0
            jobs = []
            disp_w: dict = {}
            try:
                for p in range(lo_b, hi_b):
                    w = neighbors.w_floor(int(widths[p]), eff_min)
                    disp_w[p] = w
                    out = _dispatch(p, w)
                    if pull_pipe is not None:
                        jobs.append(
                            (
                                pull_pipe.submit(
                                    functools.partial(_land, p, out),
                                    bytes_hint=int(widths[p]) * 9,
                                    label=f"embed{p}",
                                ),
                                functools.partial(_land, p, out),
                            )
                        )
                    else:
                        _land(p, out)
            except BaseException:
                # mirror spill_device's orphan-drain: pulls already
                # submitted must not outlive a failing dispatch loop on
                # the shared worker (their results land in state this
                # frame is about to drop)
                for job, _work in jobs:
                    try:
                        pull_pipe.wait(job)
                    except Exception:  # noqa: BLE001 — already failing
                        pass
                raise
            for job, work in jobs:
                pull_pipe.settle(job, work)
            dur_dispatch += time.perf_counter() - t_b0
            t_b1 = time.perf_counter()

            # W-rung escalation: any bucket whose table truncated
            # re-runs synchronously at the rung its observed max degree
            # needs; the ratchet pins the settled rung so the NEXT
            # same-width bucket starts there (zero recompiles at
            # steady state)
            for p in range(lo_b, hi_b):
                seed_h, flag_h, cnt_h, ovf, iters = results.pop(p)
                b = int(widths[p])
                w = int(disp_w[p])
                while ovf:
                    c = int(counts_p[p])
                    need = int(cnt_h[:c].max()) - 1 if c else 1
                    w = neighbors.next_w(b, need)  # > old w: overflow
                    # means some observed degree exceeded the old rung
                    escalations += 1
                    obs.count("embed.neighbor_escalations")
                    _land(p, _dispatch(p, w))
                    seed_h, flag_h, cnt_h, ovf, iters = results.pop(p)
                neighbors.note_w(b, w)
                lo, hi = int(offsets[p]), int(offsets[p + 1])
                c = hi - lo
                inst_seed[lo:hi] = seed_h[:c]
                inst_flag[lo:hi] = flag_h[:c]
                edges += int(np.asarray(cnt_h[:c], dtype=np.int64).sum())
                band_iters = max(band_iters, int(iters))
                cc_iters_max = max(cc_iters_max, int(iters))
                prop_sweeps += int(iters)
            dur_pull += time.perf_counter() - t_b1
            if checkpoint_dir is not None:
                _bank_band(
                    band, lo_b, hi_b,
                    edges - edges0, band_iters, prop_sweeps - sweeps0,
                )
        obs.count("embed.edges", int(edges))
        if prop_sweeps:
            # the shared propagation telemetry (ops/propagation.py):
            # every bucket's window_cc sweep count funnels into
            # prop.sweeps so leg-1's collapse is measured on the embed
            # path too, not just the banded cellcc finalize
            prop_propagation.note_sweeps(prop_sweeps)
        t_bands = time.perf_counter()

        cand, inst_inner = spill_mod.band_membership(
            part_ids, point_idx, home_of, n
        )
        # sharded finalize routes the border-union step through the
        # collective halo-merge (parallel/halo.py): the boundary-spill
        # duplicates ARE the eps-halo points, so cross-chip components
        # reconcile with no new merge algebra; canonical numbering
        # keeps the labels byte-identical to the unsharded run
        with obs.span("embed.merge", instances=int(m_tot)):
            clusters, flags, n_clusters = finalize_merge(
                part_ids, point_idx, inst_seed, inst_flag, cand,
                inst_inner, n, n_parts, max_b, canonical=True,
                mesh=mesh if shard else None,
                shape_floors=_MERGE_FLOORS if shard else None,
            )
        t_end = time.perf_counter()

    if stats_out is not None:
        stats_out.update(
            n_partitions=int(n_parts),
            duplication_factor=float(m_tot) / max(1, n),
            n_clusters=int(n_clusters),
            sample_frac=float(frac),
            embed_buckets=int(bin_info["buckets"]),
            embed_spill_fallbacks=int(bin_info["fallbacks"]),
            embed_spill_fallback_points=int(bin_info["fallback_points"]),
            embed_edges=int(edges),
            embed_cc_iters=int(cc_iters_max),
            embed_escalations=int(escalations),
            embed_oracle_buckets=int(oracle_buckets[0]),
            embed_quantizer=quant,
            embed_ivf_cells=int(bin_info.get("cells", 0)),
            embed_shards=int(n_shards),
            campaign_chunks_total=int(n_bands),
            campaign_bands_loaded=int(bands_loaded),
            resumed_from_checkpoint=bool(bands_loaded),
            timings={
                "hash_s": round(t_hash - t_start, 6),
                "bin_s": round(t_bin - t_hash, 6),
                "dispatch_s": round(dur_dispatch, 6),
                "pull_s": round(dur_pull, 6),
                "merge_s": round(t_end - t_bands, 6),
                "total_s": round(t_end - t_start, 6),
            },
        )
    return clusters, flags
