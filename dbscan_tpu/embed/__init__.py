"""``dbscan_tpu/embed``: high-dimensional cosine DBSCAN engine.

The workload modern traffic actually brings (ROADMAP item 3): [N, D]
unit-normalized embeddings, D up to 768 and beyond. Signed-random-
projection LSH binning replaces the 2-D grid front-end, the pivot
spill tree is the exact fallback partitioner, a blocked MXU cosine
neighbor kernel feeds the shared ``ops/propagation.window_cc``, and an
opt-in subsampled-edge mode trades accuracy for speed under a declared,
regression-gated ARI floor. See ``embed/engine.py`` for the pipeline
and PARITY.md "Embed accuracy contract" for the knobs.
"""

from dbscan_tpu.embed.engine import embed_dbscan
from dbscan_tpu.embed.oracle import cosine_dbscan_oracle

__all__ = ["embed_dbscan", "cosine_dbscan_oracle"]
