"""Signed-random-projection LSH binning: the high-dim replacement for
the 2-D grid front-end (``parallel/binning.py``).

Device side, ONE matmul: :func:`hash_dispatch` projects the whole
``[N, D]`` payload onto ``T * H`` random unit normals (family
``embed.hash``) and returns per-table sign codes plus the PRIMARY
table's signed projections. Codes serve the multi-table candidate
diagnostics (:func:`pair_covered`, the recall bound the tests check);
the primary projections drive the EXACT partitioner below.

Host side, :func:`bin_points` turns the primary projections into a
partition with the spill tree's coverage contract — every point pair
the kernel can accept shares at least one partition:

- recurse one hyperplane at a time; points within ``band`` of the cut
  (``|proj| <= halo + slack``) are COPIED into both children. The
  invariant this buys is NEIGHBORHOOD COMPLETENESS at the home chain —
  strictly stronger than pair-sharing, and the one the merge actually
  needs: core flags come from bucket-LOCAL counts, so the home
  instance of every point must see its ENTIRE eps-ball (the same
  invariant the spill tree's ``r_c + halo`` bands provide — a point
  assigned to cell c pulls every neighbor into c's band). Proof, one
  Cauchy-Schwarz line: for unit normal ``w`` and a pair with
  ``chord(p, q) <= halo``, ``|p.w - q.w| <= halo``; if q sits on the
  other side of the cut from p's HOME side, then ``|q.w| <= halo``, so
  q is in band and is copied into p's home child. Inductively every
  neighbor of p follows p's home chain to its home leaf. (A half-width
  ``halo/2`` band guarantees only that the PAIR shares some leaf —
  p's home instance can still lose out-of-band neighbors on the far
  side, undercounting its core test; caught by review + the
  uniform-sphere fuzz in tests/test_embed.py.);
- a cut whose band swallows too much of the node (dense mass ON the
  hyperplane — the regime ``parallel/spill.py``'s docstring warns
  projections hit in high-D: data spread along a random direction
  contracts by ~sqrt(D) while the band stays at chord scale, so
  hyperplane cuts pay only when ``halo/2 < ~1/sqrt(D)``, i.e. TIGHT
  thresholds — the near-duplicate regime embeddings are actually
  deduped at) is skipped for the next plane, and a node with NO
  payable plane left falls back to the pivot spill tree
  (``spill.spill_partition`` — dimension-agnostic, device-resident via
  PR 8), which owns exactly that regime. The recursion contract
  composes: pairs crossing the fallback node's boundary were already
  covered by ancestor bands, pairs inside it are the spill tree's
  standard guarantee;
- ``home`` follows the SIGN chain (band membership never moves a
  point's home), so every point has exactly one home leaf — the
  invariant ``spill.band_membership`` and the driver's merge
  classification require.

The reference analog is the margin/outer-rectangle duplication
(DBSCAN.scala:132-137) with hyperplane cells standing in for grid
rectangles.
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.parallel.spill import MAX_CHILD_FRAC

#: a cut duplicating more than this fraction of a node into both
#: children makes no progress worth its copies — skip to the next plane
#: (0.5 bounds per-level duplication at 1.5x; the spill tree's
#: MAX_DUP_FACTOR regime owns anything denser via the fallback)
BAND_FRAC_MAX = 0.5
#: absolute slack added to the band over the chord halo: f32 projection
#: rounding (dot error ~ D * 2^-24 on unit rows, < 5e-5 at D = 768) can
#: only SHRINK a measured |proj|, and an under-read band could miss a
#: boundary pair — inflating is one-sided, copies only grow
PROJ_SLACK = 1e-4


def default_bits() -> int:
    return max(1, int(config.env("DBSCAN_EMBED_BITS")))


def default_tables() -> int:
    return max(1, int(config.env("DBSCAN_EMBED_TABLES")))


def make_planes(
    dim: int, bits: int, tables: int, seed: int = 0
) -> np.ndarray:
    """[T * H, D] f32 unit normals, seed-deterministic."""
    rng = np.random.default_rng([seed, dim, bits, tables])
    p = rng.standard_normal((tables * bits, dim)).astype(np.float32)
    p /= np.maximum(np.linalg.norm(p, axis=1, keepdims=True), 1e-20)
    return p


@functools.lru_cache(maxsize=32)
def _hash_fn(bits: int, tables: int):
    """Jitted SRP hash: one [N, D] x [D, T*H] MXU matmul, sign-packed
    per-table codes + the primary table's raw projections. Compiled per
    (bits, tables); N and D ride the callers' ladder pads."""
    import jax
    import jax.numpy as jnp

    def fn(x, planes):
        proj = x @ planes.T  # [n, T*H] f32
        bits_ = (proj >= 0.0).reshape(x.shape[0], tables, bits)
        weights = jnp.left_shift(
            jnp.int32(1), jnp.arange(bits, dtype=jnp.int32)
        )
        codes = jnp.sum(
            bits_ * weights[None, None, :], axis=2, dtype=jnp.int32
        )
        return codes, proj[:, :bits]

    return jax.jit(fn)


def hash_points(
    x_pad: np.ndarray, planes: np.ndarray, bits: int, tables: int,
    sharding=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """``embed.hash`` device dispatch under fault supervision: returns
    host ``(codes [n_pad, T] int32, proj0 [n_pad, H] f32)``.

    ``x_pad`` is the ladder-padded [n_pad, d_pad] f32 payload (zero
    rows/columns hash harmlessly — padded rows' codes are never read,
    padded columns meet zero plane weights). ``sharding`` (a
    ``jax.sharding.NamedSharding`` over the row axis) runs the matmul
    row-sharded over the mesh with the small plane matrix replicated —
    per-row results are the single-device bytes exactly, since each
    output row reads only its own input row. A persistent device fault
    raises :class:`dbscan_tpu.faults.FatalDeviceFault`; the engine owns
    the whole-run oracle degradation decision."""
    import jax
    import jax.numpy as jnp

    fn = _hash_fn(int(bits), int(tables))
    obs.count("embed.hash_dispatches")

    def _call(_b):
        xd = jnp.asarray(x_pad)
        if sharding is not None:
            xd = jax.device_put(xd, sharding)
        return obs_compile.tracked_call(
            "embed.hash", fn, xd, jnp.asarray(planes)
        )

    with obs.span(
        "embed.hash",
        n=int(x_pad.shape[0]),
        d=int(x_pad.shape[1]),
        tables=int(tables),
        bits=int(bits),
    ) as sp:
        out = faults.supervised(
            faults.SITE_EMBED,
            _call,
            label="hash",
        )
        sp.sync(out)
    codes, proj0 = jax.device_get(out)
    obs.count("transfer.h2d_bytes", int(x_pad.nbytes + planes.nbytes))
    obs.count("transfer.d2h_bytes", int(codes.nbytes + proj0.nbytes))
    return np.asarray(codes), np.asarray(proj0)


def collision_lower_bound(eps: float, bits: int, tables: int) -> float:
    """Goemans-Williamson lower bound on the probability that an
    eps-close pair (cosine distance <= eps on unit rows) co-buckets in
    at least one of ``tables`` SRP tables of ``bits`` bits each:
    per-bit collision >= 1 - theta_max / pi with
    ``theta_max = arccos(1 - eps)``. The recall test checks the
    multi-table candidate sets against this floor."""
    theta = float(np.arccos(np.clip(1.0 - float(eps), -1.0, 1.0)))
    p_bit = 1.0 - theta / np.pi
    return float(1.0 - (1.0 - p_bit ** int(bits)) ** int(tables))


def pair_covered(
    codes: np.ndarray, ii: np.ndarray, jj: np.ndarray
) -> np.ndarray:
    """[len(ii)] bool: pair (ii[k], jj[k]) shares a bucket in at least
    one table — the multi-table candidate relation the recall
    diagnostics measure (the EXACT partitioner does not rely on it)."""
    codes = np.asarray(codes)
    return (codes[ii] == codes[jj]).any(axis=1)


def bin_points(
    proj0: np.ndarray,
    halo: float,
    maxpp: int,
    spill_fallback: Callable,
    info: dict = None,
) -> Tuple[np.ndarray, np.ndarray, int, np.ndarray]:
    """Exact boundary-spill binning over the primary-table projections.

    Args:
      proj0: [N, H] f32 signed projections of the (unit) payload onto
        the primary table's hyperplanes, host-side.
      halo: chord halo (``spill.chord_halo``); the duplication band is
        ``halo + PROJ_SLACK`` (module docstring: every neighbor of a
        point must follow its home chain — neighborhood completeness,
        not merely pair-sharing).
      maxpp: bucket size target; a node at or under it becomes a leaf.
      spill_fallback: ``idx -> (part_ids, point_idx, n_parts,
        home_of)`` over the node's rows (node-local indices) — the
        pivot spill tree, invoked for nodes no remaining hyperplane can
        split within the band/progress budget.
      info: optional dict receiving ``buckets`` / ``fallbacks`` /
        ``fallback_points`` / ``occupancy`` (leaf sizes, spill
        sub-leaves included).

    Returns ``(part_ids [M], point_idx [M], n_parts, home_of [N])`` —
    instances sorted by (partition, point row), the layout
    ``band_membership`` and ``finalize_merge`` consume.
    """
    proj0 = np.asarray(proj0)
    n, depth_max = proj0.shape
    band = float(halo) + PROJ_SLACK
    part_blocks = []  # (pid array, point row array) per emitted leaf
    home_of = np.full(n, -1, dtype=np.int32)
    occupancy: list = []
    next_pid = 0
    buckets = 0
    fallbacks = 0
    fallback_points = 0

    stack = [(np.arange(n, dtype=np.int64), np.ones(n, dtype=bool), 0)]
    while stack:
        idx, home, depth = stack.pop()
        if len(idx) == 0:
            continue
        if len(idx) <= maxpp:
            pid = next_pid
            next_pid += 1
            buckets += 1
            occupancy.append(len(idx))
            part_blocks.append(
                (np.full(len(idx), pid, dtype=np.int64), idx)
            )
            home_of[idx[home]] = pid
            continue
        chosen = -1
        k = depth
        while k < depth_max:
            p = proj0[idx, k]
            in_band = np.abs(p) <= band
            left_n = int((p <= band).sum())
            right_n = int((p >= -band).sum())
            cap = MAX_CHILD_FRAC * len(idx)
            if (
                in_band.mean() <= BAND_FRAC_MAX
                and left_n <= cap
                and right_n <= cap
            ):
                chosen = k
                break
            k += 1
        if chosen < 0:
            # no payable hyperplane left: the node is dense on every
            # remaining cut — exactly the pivot tree's regime
            fallbacks += 1
            fallback_points += len(idx)
            pa, pi, n_sub, home_sub = spill_fallback(idx)
            part_blocks.append(
                (np.asarray(pa, np.int64) + next_pid, idx[pi])
            )
            sizes = np.bincount(pa, minlength=n_sub)
            occupancy.extend(int(c) for c in sizes)
            home_of[idx[home]] = (
                np.asarray(home_sub, np.int64) + next_pid
            )[home].astype(np.int32)
            next_pid += int(n_sub)
            continue
        p = proj0[idx, chosen]
        sign_pos = p >= 0
        neg = p <= band
        pos = p >= -band
        stack.append((idx[pos], home[pos] & sign_pos[pos], chosen + 1))
        stack.append(
            (idx[neg], home[neg] & ~sign_pos[neg], chosen + 1)
        )

    if part_blocks:
        part_ids = np.concatenate([b[0] for b in part_blocks])
        point_idx = np.concatenate([b[1] for b in part_blocks])
        # leaves emit in pid order but the fallback sub-blocks arrive
        # partition-major only locally; one stable lexsort pins the
        # global (partition, point) layout the packers/merge require
        order = np.lexsort((point_idx, part_ids))
        part_ids = part_ids[order]
        point_idx = point_idx[order]
    else:
        part_ids = np.empty(0, np.int64)
        point_idx = np.empty(0, np.int64)
    if info is not None:
        info["buckets"] = buckets
        info["fallbacks"] = fallbacks
        info["fallback_points"] = fallback_points
        info["occupancy"] = occupancy
    assert (home_of >= 0).all(), "every point needs exactly one home leaf"
    return part_ids, point_idx, next_pid, home_of


def occupancy_counters(occupancy) -> None:
    """Fold leaf sizes into the fixed-edge occupancy histogram counters
    the ``obs.analyze`` embed section renders."""
    sizes = np.asarray(occupancy, dtype=np.int64)
    if sizes.size == 0:
        return
    le64 = int((sizes <= 64).sum())
    le1k = int(((sizes > 64) & (sizes <= 1024)).sum())
    le16k = int(((sizes > 1024) & (sizes <= 16384)).sum())
    gt16k = int((sizes > 16384).sum())
    if le64:
        obs.count("embed.occ_le_64", le64)
    if le1k:
        obs.count("embed.occ_le_1024", le1k)
    if le16k:
        obs.count("embed.occ_le_16384", le16k)
    if gt16k:
        obs.count("embed.occ_gt_16384", gt16k)
