"""Blocked cosine-similarity neighbor kernel for one embed bucket.

One fused device dispatch (family ``embed.neighbors``) per bucket:

1. ``lax.map`` over row blocks of ``_BLK`` rows; each block computes
   its ``[_BLK, B]`` similarity slab as ONE MXU matmul against the
   resident bucket rows — the same blocked-matmul shape discipline as
   the spill tree's ``[M, S*m]`` passes — thresholds the cosine
   distance, and compacts each row's eps-neighbors into a ``[B, W]``
   neighbor table (sorted column indices; ``B`` = "no neighbor");
2. core flags from the self-inclusive counts, then connected
   components of the core-core windowed relation through the SHARED
   ``ops/propagation.window_cc`` — the same min-label fixed point the
   banded cellcc finalize rides;
3. border/noise algebra via the shared ``ops.local_dbscan._finalize``
   tail, so both border semantics (naive/archery) match the other
   engines by construction.

``W`` (neighbor slots per row) rides the ladder/ratchet compiled-shape
discipline of ``ops/banded.py``: widths come from
``binning._ladder_width``, the kernel reports an ``overflow`` flag when
any valid row's non-self neighbor count exceeds ``W`` (truncation would
break CC/border exactness), the caller re-runs at the rung that fits,
and a process-wide per-width ratchet (:func:`w_floor` /
:func:`note_w`) pins the settled rung so steady-state job streams
re-dispatch with zero recompiles.

Subsampled-edge mode (SNG-DBSCAN, arXiv:2006.06743): a deterministic
symmetric per-pair hash keeps each candidate edge with probability
``frac`` (self-edges always kept), and the core threshold scales to
``ceil(frac * (min_points - 1)) + 1`` sampled neighbors — the explicit
accuracy knob; the engine reports ARI vs the exact path and the bench
gate keeps the declared floor honest (PARITY.md "Embed accuracy
contract").
"""

from __future__ import annotations

import functools

import numpy as np

from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.parallel.binning import _ladder_width

#: rows per similarity block (the lax.map slab height); bucket widths
#: are _ladder_width(c, 128) multiples, so blocks always divide B
_BLK = 128

#: resolution of the sampled-edge keep threshold (frac quantizes to
#: 1/2^24; the exact path passes the full range so every edge keeps)
SAMPLE_RES = 1 << 24

# per-width settled W rungs: the ratchet that makes a steady-state job
# stream re-dispatch with ZERO recompiles (the escalation rerun only
# ever fires the first time a width class meets a denser bucket).
# Written from the engine's pull-land path, which may run on the
# PullEngine worker while the main thread dispatches — lock it.
_w_floors: dict = {}
_w_lock = _tsan.lock("embed.w_floors")


def w_floor(b: int, min_points: int) -> int:
    """Starting W rung for bucket width ``b``: the settled floor when
    one exists, else a ladder rung sized to the density the core
    threshold implies."""
    with _w_lock:
        _tsan.access("embed.w_floors")
        prev = _w_floors.get(int(b), 0)
    guess = max(32, 4 * int(min_points))
    return min(int(b), max(prev, _ladder_width(guess, 8)))


def note_w(b: int, w: int) -> None:
    """Ratchet the settled W rung for width ``b`` up to ``w``."""
    with _w_lock:
        _tsan.access("embed.w_floors")
        _w_floors[int(b)] = max(_w_floors.get(int(b), 0), int(w))


def reset_w_floors() -> None:
    """Drop the ratchet state (tests)."""
    with _w_lock:
        _tsan.access("embed.w_floors")
        _w_floors.clear()


def next_w(b: int, max_count: int) -> int:
    """The rung that fits an observed max non-self neighbor count."""
    return min(int(b), _ladder_width(max(1, int(max_count)), 8))


def _pair_keep(jnp, rids, cids, seed):
    """[R, C] uint32 in [0, 2^24): a deterministic symmetric hash of
    the UNORDERED original-row pair — the sampled-edge coin. Keyed on
    original rows (not bucket slots), so the sampled graph is identical
    across decompositions of the same data."""
    a = jnp.minimum(rids[:, None], cids[None, :]).astype(jnp.uint32)
    b = jnp.maximum(rids[:, None], cids[None, :]).astype(jnp.uint32)
    h = (
        a * jnp.uint32(2654435761)
        + b * jnp.uint32(0x9E3779B9)
        + jnp.uint32(seed)
    )
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x2C1B3C6D)
    h = h ^ (h >> 12)
    h = h * jnp.uint32(0x297A2D39)
    h = h ^ (h >> 15)
    return h & jnp.uint32(SAMPLE_RES - 1)


def _neighbors_fn(b: int, w: int, engine: str, mode: str = None):
    """Jitted per-bucket kernel (see module doc). Compiled per
    (bucket width, W rung, engine, propagation mode) — the mode
    (DBSCAN_PROP_UNIONFIND, ops/propagation.py) resolves BEFORE the
    cache so an in-process knob flip mints a fresh trace; D rides the
    traced array shape. Returns (seed_labels [b], flags [b], counts
    [b], overflow bool, cc iters int32)."""
    from dbscan_tpu.ops.propagation import prop_mode

    return _neighbors_fn_cached(b, w, engine, prop_mode(mode))


@functools.lru_cache(maxsize=128)
def _neighbors_fn_cached(b: int, w: int, engine: str, mode: str):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from dbscan_tpu.ops.labels import SEED_NONE
    from dbscan_tpu.ops.local_dbscan import _finalize
    from dbscan_tpu.ops.propagation import window_cc

    nb = b // _BLK
    assert nb * _BLK == b, "bucket widths are _BLK multiples"
    none = jnp.int32(SEED_NONE)

    def fn(x, mask, ids, eps, eff_min, keep_num, seed):
        col = jnp.arange(b, dtype=jnp.int32)

        def block(i0):
            r0 = i0 * _BLK
            rows = lax.dynamic_slice(
                x, (r0, jnp.int32(0)), (_BLK, x.shape[1])
            )
            rmask = lax.dynamic_slice(mask, (r0,), (_BLK,))
            rids = lax.dynamic_slice(ids, (r0,), (_BLK,))
            sims = rows @ x.T  # the MXU slab
            valid = rmask[:, None] & mask[None, :]
            selfm = (rids[:, None] == ids[None, :]) & valid
            adj = ((1.0 - sims) <= eps) & valid
            # self-adjacency explicit (f32 self-similarity can round
            # below 1.0) — counts stay self-inclusive under sampling
            adj = adj | selfm
            keep = _pair_keep(jnp, rids, ids, seed) < jnp.uint32(
                keep_num
            )
            adj = adj & (keep | selfm)
            counts = jnp.sum(adj, axis=1, dtype=jnp.int32)
            key = jnp.where(adj & ~selfm, col[None, :], jnp.int32(b))
            tab = jnp.sort(key, axis=1)[:, :w]
            return tab, counts

        tabs, counts = lax.map(block, jnp.arange(nb, dtype=jnp.int32))
        tab = tabs.reshape(b, w)
        counts = counts.reshape(b)
        # truncation guard: a row listing more non-self neighbors than
        # W slots would drop edges — CC and border assignment both
        # need the full relation, so the caller escalates the rung
        overflow = jnp.any(mask & (counts - 1 > jnp.int32(w)))

        core = mask & (counts >= eff_min)
        in_tab = tab < jnp.int32(b)
        tabc = jnp.clip(tab, 0, b - 1)
        col_core = core[tabc] & in_tab
        # symmetric by construction: the underlying eps-relation is
        # symmetric (one compiled matmul per block -> bitwise-equal
        # sims both ways), the pair hash is unordered, and no-overflow
        # means every neighbor is listed — window_cc's contract
        comp_all, iters = window_cc(
            col_core & core[:, None], tabc, mode=mode
        )
        comp = jnp.where(core, comp_all, none)
        nbr_seed = jnp.min(
            jnp.where(col_core, comp[tabc], none), axis=1
        )
        # cores see their own component (self sits outside the table)
        core_nbr_seed = jnp.minimum(
            nbr_seed, jnp.where(core, comp, none)
        )
        res = _finalize(mask, core, comp, core_nbr_seed, counts, engine)
        return res.seed_labels, res.flags, res.counts, overflow, iters

    return jax.jit(fn)


def eff_min_points(min_points: int, frac: float) -> int:
    """Core threshold on SAMPLED self-inclusive counts: self always
    kept, each of the other ``min_points - 1`` required neighbors
    survives with probability ``frac`` — the declared SNG-style scaling
    (PARITY.md "Embed accuracy contract")."""
    if frac >= 1.0:
        return int(min_points)
    return int(np.ceil(frac * (int(min_points) - 1))) + 1


def keep_threshold(frac: float) -> int:
    """``frac`` quantized to the kernel's 2^-24 keep-coin resolution;
    the exact path (frac >= 1) keeps every edge."""
    if frac >= 1.0:
        return SAMPLE_RES
    return max(0, int(round(float(frac) * SAMPLE_RES)))
