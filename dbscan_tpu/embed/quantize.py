"""IVF-style coarse quantizer front-end for the embed engine
(``DBSCAN_EMBED_QUANTIZER=ivf``).

The spill tree's farthest-point/Lloyd kernels ARE the quantizer: one
``embed.quantize`` dispatch reuses ``spill_device._farthest_lloyd_fn``
(fp seeding + Lloyd steps, already device-resident and
dimension-agnostic) to place ``m`` k-means cells on the unit sphere and
computes the full ``[n, m]`` chord matrix in the same compiled body.
Host side, membership is the spill tree's EXACT band formula
(``spill._membership``: intersection of the radius band ``r_c + halo``
and the classic ``d_min + 2*halo``), so the coverage argument is the
spill tree's own, verbatim: a point assigned to cell c pulls every
chord-halo neighbor into c's member set — neighborhood completeness at
the home cell, the invariant ``finalize_merge`` needs for exact core
flags. Cells the bands still leave over ``maxpp`` recurse through the
same pivot-spill fallback the SRP path uses; pairs crossing the
fallback cell's boundary were already covered by the cell bands, pairs
inside it are the spill tree's standard guarantee — the identical
composition ``embed/lsh.py`` documents for its hyperplane recursion.

k-means cells replace SRP planes as the BINNING only: bucket
dispatches, escalation, merge, and the canonical numbering are the
shared engine path, so on bridge-free workloads the label vector is
byte-identical to the SRP route (and to any mesh shape) — the contract
tests/test_embed_sharded.py pins, with the ARI >= 0.95 gate declared
alongside the sampled mode's in PARITY.md.
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.parallel.binning import _ladder_width


def default_quantizer() -> str:
    """The binning front-end: ``DBSCAN_EMBED_QUANTIZER`` ('srp' |
    'ivf'); unknown values raise — a typo must not silently run the
    default partitioner under a benchmark labeled 'ivf'."""
    q = str(config.env("DBSCAN_EMBED_QUANTIZER") or "srp").lower()
    if q not in ("srp", "ivf"):
        raise ValueError(
            f"DBSCAN_EMBED_QUANTIZER must be 'srp' or 'ivf', got {q!r}"
        )
    return q


def default_cells(n: int, maxpp: int) -> int:
    """IVF cell count: the knob when set, else ~2x the payload/maxpp
    ratio (each cell targets ~half a bucket so the band duplication
    rarely pushes a cell over ``maxpp``), clamped to the spill ladder's
    range."""
    cells = int(config.env("DBSCAN_EMBED_IVF_CELLS"))
    if cells <= 0:
        cells = 2 * max(1, -(-int(n) // max(1, int(maxpp))))
    return max(2, min(192, cells))


@functools.lru_cache(maxsize=32)
def _quantize_fn(m: int, dim: int):
    """Jitted ``embed.quantize`` body: the spill tree's fp+Lloyd kernel
    (``spill_device._farthest_lloyd_fn`` — called inside this jit, so
    the two compile as ONE dispatch) followed by the [n, m] chord
    matrix against the surviving pivots; empty cells chord +inf so the
    host membership can never assign to them."""
    import jax
    import jax.numpy as jnp

    from dbscan_tpu.parallel import spill_device

    inner = spill_device._farthest_lloyd_fn(m, dim)

    def fn(x, seed0):
        piv, mass = inner(x, seed0)
        d = 2.0 - 2.0 * (x.astype(jnp.float32) @ piv.T)
        d = jnp.sqrt(jnp.maximum(d, 0.0))
        d = jnp.where((mass > 0)[None, :], d, jnp.inf)
        return piv, mass, d

    return jax.jit(fn)


def quantize_points(
    unit32: np.ndarray, cells: int, seed: int
) -> np.ndarray:
    """One supervised ``embed.quantize`` dispatch over the (pad-
    replicated) payload: returns the host ``[n, m]`` chord matrix.

    Rows are padded to the shared 128-ladder by REPLICATING row 0 —
    zero-pad rows would sit at chord sqrt(2) from every unit row and
    the farthest-point seeding would elect them as pivots; duplicates
    of a real row have chord 0 to it and can never be re-chosen.
    A persistent device fault raises
    :class:`dbscan_tpu.faults.FatalDeviceFault`; the engine owns the
    whole-run oracle degradation decision (the hash dispatch's gate).
    """
    import jax
    import jax.numpy as jnp

    n, dim = unit32.shape
    m = _ladder8_cells(cells)
    n_pad = _ladder_width(n, 128)
    d_pad = _ladder_width(dim, 8)
    x_pad = np.zeros((n_pad, d_pad), dtype=np.float32)
    x_pad[:n, :dim] = unit32
    x_pad[n:, :dim] = unit32[0]
    rng = np.random.default_rng([seed, n, dim, m])
    seed0 = int(rng.integers(n))
    fn = _quantize_fn(m, d_pad)
    obs.count("embed.quantize_dispatches")
    obs.gauge("embed.ivf_cells", float(m))
    with obs.span(
        "embed.quantize", n=int(n), d=int(dim), cells=int(m)
    ) as sp:
        out = faults.supervised(
            faults.SITE_EMBED,
            lambda _b: obs_compile.tracked_call(
                "embed.quantize", fn, jnp.asarray(x_pad), seed0
            ),
            label="quantize",
        )
        sp.sync(out)
    _piv, _mass, d = jax.device_get(out)
    obs.count("transfer.h2d_bytes", int(x_pad.nbytes))
    obs.count("transfer.d2h_bytes", int(np.asarray(d).nbytes))
    return np.asarray(d, dtype=np.float64)[:n]


def _ladder8_cells(m: int) -> int:
    from dbscan_tpu.parallel.spill_device import _ladder8

    return _ladder8(int(m))


def ivf_bin_points(
    unit32: np.ndarray,
    halo: float,
    maxpp: int,
    seed: int,
    spill_fallback: Callable,
    info: dict = None,
) -> Tuple[np.ndarray, np.ndarray, int, np.ndarray]:
    """IVF binning with the exact spill-band copy-set: returns
    ``(part_ids [M], point_idx [M], n_parts, home_of [N])`` in the
    (partition, point)-sorted layout ``band_membership`` and
    ``finalize_merge`` consume — the same contract as
    ``lsh.bin_points``, with k-means cells in place of hyperplane
    leaves. ``info`` receives the binning diagnostics dict the engine
    folds into counters (plus ``cells``, the surviving cell count)."""
    from dbscan_tpu.parallel import spill as spill_mod

    n = len(unit32)
    d = quantize_points(unit32, default_cells(n, maxpp), seed)
    assign, _d_min, _r, member = spill_mod._membership(d, float(halo))

    part_blocks = []
    home_of = np.full(n, -1, dtype=np.int32)
    occupancy: list = []
    next_pid = 0
    buckets = 0
    fallbacks = 0
    fallback_points = 0
    live_cells = 0
    for c in range(d.shape[1]):
        idx = np.flatnonzero(member[:, c])
        if len(idx) == 0:
            continue
        live_cells += 1
        home = assign[idx] == c
        if len(idx) <= maxpp:
            pid = next_pid
            next_pid += 1
            buckets += 1
            occupancy.append(len(idx))
            part_blocks.append(
                (np.full(len(idx), pid, dtype=np.int64), idx)
            )
            home_of[idx[home]] = pid
            continue
        # a cell the bands still leave oversized recurses through the
        # pivot spill tree over ITS member rows — crossing pairs were
        # covered by the cell bands, inner pairs by the tree (the
        # composition lsh.bin_points documents)
        fallbacks += 1
        fallback_points += len(idx)
        pa, pi, n_sub, home_sub = spill_fallback(idx)
        part_blocks.append(
            (np.asarray(pa, np.int64) + next_pid, idx[pi])
        )
        sizes = np.bincount(pa, minlength=n_sub)
        occupancy.extend(int(s) for s in sizes)
        home_of[idx[home]] = (
            np.asarray(home_sub, np.int64) + next_pid
        )[home].astype(np.int32)
        next_pid += int(n_sub)

    if part_blocks:
        part_ids = np.concatenate([b[0] for b in part_blocks])
        point_idx = np.concatenate([b[1] for b in part_blocks])
        order = np.lexsort((point_idx, part_ids))
        part_ids = part_ids[order]
        point_idx = point_idx[order]
    else:
        part_ids = np.empty(0, np.int64)
        point_idx = np.empty(0, np.int64)
    if info is not None:
        info["buckets"] = buckets
        info["fallbacks"] = fallbacks
        info["fallback_points"] = fallback_points
        info["occupancy"] = occupancy
        info["cells"] = live_cells
    assert (home_of >= 0).all(), "every point needs exactly one home cell"
    return part_ids, point_idx, next_pid, home_of
