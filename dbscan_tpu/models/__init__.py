"""Public model surface: the DBSCAN estimator and (later) streaming."""
