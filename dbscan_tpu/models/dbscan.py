"""Public DBSCAN API: train() -> DBSCANModel.

Mirrors the reference surface (DBSCAN.scala:28-50 object + model accessors
:287-302) with the gaps filled:

- ``train(data, eps, min_points, max_points_per_partition)`` — same
  hyperparameters, positional-compatible;
- ``model.labeled_points`` — per-point (coords, cluster, flag), the
  RDD-of-DBSCANLabeledPoint equivalent (:291-293) as host arrays;
- ``model.partitions`` — final main rectangles with ids (:66, :272-274);
- ``model.predict(vectors)`` — the reference ADVERTISES this and throws
  NotImplementedError (:300-302); we implement it as
  nearest-core-point-within-eps (documented delta).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from dbscan_tpu.config import DBSCANConfig, Engine, Precision
from dbscan_tpu.ops import geometry as geo
from dbscan_tpu.ops.labels import CORE, FLAG_NAMES
from dbscan_tpu.parallel.driver import TrainOutput, train_arrays


@dataclasses.dataclass
class DBSCANModel:
    """A fitted distributed-DBSCAN model (host-resident)."""

    config: DBSCANConfig
    points: np.ndarray  # [N, >=2] original input rows
    clusters: np.ndarray  # [N] int32 global cluster ids, 0 == noise
    flags: np.ndarray  # [N] int8
    partitions: List[Tuple[int, np.ndarray]]  # (id, main rect [4])
    n_clusters: int
    stats: dict

    @property
    def labeled_points(self) -> np.ndarray:
        """[N, D+2] array: original columns + cluster id + flag code —
        the labeledPoints accessor (reference DBSCAN.scala:291-293)."""
        return np.concatenate(
            [
                np.asarray(self.points, dtype=np.float64),
                self.clusters[:, None].astype(np.float64),
                self.flags[:, None].astype(np.float64),
            ],
            axis=1,
        )

    def flag_names(self) -> List[str]:
        return [FLAG_NAMES[int(f)] for f in self.flags]

    def predict(self, vectors: np.ndarray, chunk: int = 8192) -> np.ndarray:
        """Cluster id for each query point: the cluster of the nearest core
        point within eps, else 0 (noise).

        The reference advertises predict but throws NotImplementedError
        (DBSCAN.scala:300-302); nearest-core-within-eps is the textbook
        out-of-sample rule and reduces to the training labels on core
        points.
        """
        q = np.asarray(vectors, dtype=np.float64)
        if q.ndim == 1:
            q = q[None, :]
        core_mask = self.flags == CORE
        core_pts = np.asarray(self.points, dtype=np.float64)[core_mask][:, :2]
        core_ids = self.clusters[core_mask]
        out = np.zeros(len(q), dtype=np.int32)
        if core_pts.size == 0:
            return out
        eps_sq = self.config.eps_sq
        for s in range(0, len(q), chunk):
            d2 = geo.pairwise_sq_dists(q[s : s + chunk], core_pts)
            nearest = np.argmin(d2, axis=1)
            within = d2[np.arange(len(nearest)), nearest] <= eps_sq
            out[s : s + chunk] = np.where(within, core_ids[nearest], 0)
        return out


def train(
    data: np.ndarray,
    eps: float,
    min_points: int,
    max_points_per_partition: int = 250,
    *,
    engine: Engine = Engine.NAIVE,
    metric: str = "euclidean",
    precision: Precision = Precision.F32,
    bucket_multiple: int = 128,
    use_pallas: bool = False,
    neighbor_backend: str = "auto",
    auto_maxpp: bool = False,
    fault_max_retries: int = 3,
    fault_cpu_fallback: bool = True,
    mesh=None,
    config: Optional[DBSCANConfig] = None,
    checkpoint_dir: Optional[str] = None,
) -> DBSCANModel:
    """Train a distributed DBSCAN model (reference DBSCAN.train,
    DBSCAN.scala:40-48).

    data: [N, >=2] host array; only the first two columns participate in
    Euclidean clustering (reference DBSCAN.scala:33-34); extra columns ride
    along into labeled_points.
    eps: the neighborhood radius, or the string ``"auto"`` to select it
    from the data — the knee of the per-partition sorted k-distance
    curve (k = min_points) over a deterministic subsample, median
    across ``DBSCAN_DENSITY_AUTO_PARTS`` coordinate strips
    (dbscan_tpu/density/core.py:auto_eps, euclidean only); the chosen
    value and per-strip statistics land in ``model.stats["eps_auto"]``.
    mesh: optional jax.sharding.Mesh to fan partitions out over devices;
    None = single device.
    checkpoint_dir: when set, the expensive pre-merge state is persisted
    there and a re-run with the same data/config resumes at the merge
    phase (parallel/checkpoint.py — the Spark-lineage replacement).
    fault_max_retries/fault_cpu_fallback: supervised-dispatch policy
    (dbscan_tpu/faults.py) — bounded retries per device dispatch, and
    whether a retries-exhausted group degrades to the CPU engine
    instead of aborting the run.
    """
    auto_stats: dict = {}
    if isinstance(eps, str):
        if eps != "auto":
            raise ValueError(f"eps must be a number or 'auto', got {eps!r}")
        if config is not None:
            raise ValueError("eps='auto' cannot override an explicit config")
        if metric != "euclidean":
            raise ValueError("eps='auto' supports only metric='euclidean'")
        from dbscan_tpu.density.core import auto_eps

        eps = auto_eps(
            np.asarray(data, dtype=np.float64)[:, :2],
            min_points,
            stats_out=auto_stats,
        )
    cfg = config or DBSCANConfig(
        eps=eps,
        min_points=min_points,
        max_points_per_partition=max_points_per_partition,
        engine=engine,
        metric=metric,
        precision=precision,
        bucket_multiple=bucket_multiple,
        use_pallas=use_pallas,
        neighbor_backend=neighbor_backend,
        auto_maxpp=auto_maxpp,
        fault_max_retries=fault_max_retries,
        fault_cpu_fallback=fault_cpu_fallback,
    )
    out: TrainOutput = train_arrays(
        data, cfg, mesh=mesh, checkpoint_dir=checkpoint_dir
    )
    if auto_stats:
        out.stats.update(auto_stats)
    return DBSCANModel(
        config=cfg,
        points=np.asarray(data),
        clusters=out.clusters,
        flags=out.flags,
        partitions=out.partitions,
        n_clusters=out.n_clusters,
        stats=out.stats,
    )
