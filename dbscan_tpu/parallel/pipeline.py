"""Pipelined device->host pull engine: overlap D2H transfers with host
finalize and device compute.

Why this module exists: on the flagship 10M-point anchor run the
dominant phase was no longer device compute — ``cellcc_pull_core_s``
reached 16.4 s of 34.5 s wall (BENCH_TPU_r05c.json) because the driver
pulled compact chunks ONE AT A TIME, blocking on each D2H transfer
while the host-side unpack/layout algebra that follows it sat idle.
Parallel-DBSCAN systems win by keeping every pipeline stage busy (Wang
et al., arXiv:1912.06255; Prokopenko et al., arXiv:2103.05162); this is
the transfer-stage counterpart of the driver's existing pack/compute
overlap.

With the PR-10 device-resident cellcc finalize
(``DBSCAN_CELLCC_DEVICE``, parallel/cellgraph.py ``finalize_device``)
the banded jobs shrink again: the per-chunk pull+unpack work this
engine used to hide moves onto the device entirely, and the one job
the finalize still submits is a THIN LABEL PULL — the fused CC
dispatch's compact ``[V]`` seeds/flags, ~5 bytes per instance instead
of per-slot slabs plus host algebra. The engine's role there is the
stall telemetry + fault-composition path, and full-depth pipelining
remains live for the host-oracle modes (checkpointed, multi-process,
``DBSCAN_CELLCC_DEVICE=0``) and the group/sparse/streaming families.

Shape: a bounded-depth producer/consumer pipeline with ONE background
worker.

- Producers (:meth:`PullEngine.submit`) enqueue *jobs*: a host
  ``work()`` callable (the pull + the host finalize that consumes it)
  plus an optional ``on_start()`` hook (``copy_to_host_async()`` for
  device buffers, so the transfer is in flight before the worker
  reaches the job). Submission never blocks.
- The worker STARTS up to ``DBSCAN_PULL_INFLIGHT`` jobs ahead —
  byte-budgeted by ``DBSCAN_PULL_INFLIGHT_BYTES`` so HBM-resident
  chunks are not all materialized host-side at once — and EXECUTES
  jobs strictly in submission order (the host finalize is sequential
  algebra; ordering is what makes pipelined and serial runs
  label-for-label identical).
- Consumers (:meth:`PullEngine.wait`) block until their job finishes
  and re-raise the job's exception AT THE CONSUMING SITE — exactly
  where an async device fault surfaces on the serial path, so the
  driver's ``_abort_guard`` banks earlier chunks' artifacts unchanged.

Fault composition: the engine runs whatever callable it is given, so a
caller that wraps its work in :func:`dbscan_tpu.faults.supervised`
(the driver does, when a ``pull``-site fault clause is active) gets
retry/halving ON the worker — a failed pull re-enters the pipeline job,
not the raw call. Jobs the abort path cancels before they start leave
their record untouched, so the serial abort-flush re-pull is always
safe.

Observability (declared in :mod:`dbscan_tpu.obs.schema`): a
``pull.inflight`` gauge (started-but-unfinished jobs — bounded by the
configured depth), a ``pull.queue_depth`` gauge (submitted-but-
unexecuted backlog — a wedged engine freezes it nonzero), ``pull.wait_s``
(consumer seconds actually blocked) and ``pull.overlap_s`` (worker
seconds hidden behind other work) counters, ``pull.busy_s``/
``pull.bytes`` totals, one ``pull.chunk`` span per job, and a
``pull.stall`` event (+ ``pull.stalls`` counter) when a consumer blocks
past ``DBSCAN_PULL_STALL_S`` on one job — all of which also land in the
always-on flight ring (obs/flight.py) when tracing is off, so a wedged
engine leaves a postmortem. The same figures accumulate in engine-internal
:meth:`PullEngine.totals` (independent of obs being enabled) so the
driver can stamp ``stats["pull"]`` and bench can derive
``pull_overlap_ratio`` without a live trace.

Off-switch: ``DBSCAN_PULL_PIPELINE=0`` makes :func:`get_engine` return
None and every call site keeps its original serial code path
byte-for-byte.

Dedicated instances: :func:`get_engine` hands out ONE process engine,
and its strict submission order is load-bearing for the driver's
sequential finalize — but that same strict order means an unrelated
consumer sharing it inherits the driver's queue as latency. Consumers
with their own ordering domain construct their own
:class:`PullEngine`: the serving layer's query path
(dbscan_tpu/serve/service.py) does exactly this, so point-lookup pulls
never queue behind an ingest train's chunk pulls (measured ~10x
sustained QPS on this container). Same off-switch discipline applies —
under ``DBSCAN_PULL_PIPELINE=0`` such consumers run their serial path.

Collective-aware mode (multi-process runs): pulls there are cross-host
collectives (``mesh.pull_to_host`` allgathers non-addressable shards),
so their ISSUE ORDER must be identical on every process or the job
deadlocks — the reason earlier revisions forced the engine off under
``mesh.multiprocess()`` entirely. The engine now runs there with
``collective=True``, which turns the submission order into a
per-shard submission barrier:

- jobs execute INLINE at the submission point, on the submitting
  thread. A background worker issuing a cross-host allgather while the
  main thread dispatches a psum-bearing device program would let the
  two processes enqueue the same pair of collectives in OPPOSITE
  orders — the classic all-chips deadlock graftcheck's rules exist to
  prevent. One issuing thread per process, with the issue point pinned
  to the (plan-deterministic) submission point, makes every process's
  collective sequence identical by construction; the cost is the
  transfer/compute overlap, which a future split of the addressable
  local copy (async-able) from the DCN allgather can win back;
- ``on_start`` prefetch hooks are suppressed (an async copy of a
  non-addressable global array is not meaningful, and a second thread
  touching transfers would break the single-issuer ordering);
- ``quiesce`` cancels nothing (there is never a started-but-unexecuted
  job to cancel), so an abort on one process cannot desynchronize the
  others; ``barrier()`` (= drain) is trivially satisfied.

``stats["pull"]`` (and bench's ``pull_overlap_ratio``) therefore now
exist per shard in multi-process runs — the per-process engine totals
are the per-shard figures the MULTICHIP capture stamps.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Optional

from dbscan_tpu import config, obs
from dbscan_tpu.lint import tsan as _tsan

logger = logging.getLogger(__name__)

#: totals keys (engine-internal accounting, mirrored as pull.* counters)
_TOTAL_KEYS = ("jobs", "wait_s", "busy_s", "overlap_s", "bytes")


class PullJob:
    """One submitted pull: transfer + host finalize, executed on the
    engine worker. ``wait`` on the owning engine blocks for it."""

    __slots__ = (
        "work", "on_start", "bytes_hint", "label", "rid",
        "result", "error", "busy_s", "cancelled", "consumed", "_done",
    )

    def __init__(
        self,
        work: Callable[[], object],
        on_start: Optional[Callable[[], None]],
        bytes_hint: int,
        label: str,
    ):
        self.work = work
        self.on_start = on_start
        self.bytes_hint = max(0, int(bytes_hint))
        self.label = label
        # request context does not follow the job to the worker thread
        # on its own (the worker predates the request): capture the id
        # at submit, restore it around _execute
        self.rid = obs.current_request()
        self.result = None
        self.error: Optional[BaseException] = None
        self.busy_s = 0.0
        self.cancelled = False
        self.consumed = False
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()


class PullEngine:
    """Single-worker bounded-depth pull pipeline (module docstring)."""

    def __init__(
        self,
        inflight: int = 2,
        inflight_bytes: int = 1 << 30,
        collective: bool = False,
    ):
        self.inflight = max(1, int(inflight))
        self.inflight_bytes = max(1, int(inflight_bytes))
        #: collective-aware mode (module docstring): submission order is
        #: a cross-process barrier — on_start suppressed, quiesce drains
        self.collective = bool(collective)
        self._cv = _tsan.condition("pipeline.engine")
        self._pending: deque = deque()  # submitted, on_start not yet run
        self._ready: deque = deque()  # started, not yet executed
        self._executing: Optional[PullJob] = None
        self._started = 0  # started (ready + executing) job count
        self._started_bytes = 0
        self._shutdown = False
        self._worker: Optional[threading.Thread] = None
        self._totals = {k: 0 if k in ("jobs", "bytes") else 0.0
                        for k in _TOTAL_KEYS}
        self._totals["inflight_peak"] = 0

    # --- producer side -------------------------------------------------

    def submit(
        self,
        work: Callable[[], object],
        *,
        on_start: Optional[Callable[[], None]] = None,
        bytes_hint: int = 0,
        label: str = "",
    ) -> PullJob:
        """Enqueue one job; never blocks. Jobs execute strictly in
        submission order on the worker."""
        job = PullJob(work, on_start, bytes_hint, label)
        if self.collective:
            # collective mode: the submission point IS the issue point
            # (module docstring) — execute on THIS thread, no worker
            with self._cv:
                _tsan.access("pipeline.engine")
                if self._shutdown:
                    raise RuntimeError("pull engine is shut down")
            self._execute(job)
            return job
        with self._cv:
            _tsan.access("pipeline.engine")
            if self._shutdown:
                raise RuntimeError("pull engine is shut down")
            self._pending.append(job)
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._loop, name="dbscan-pull", daemon=True
                )
                self._worker.start()
            # start-ahead from the SUBMITTING thread too: the worker
            # cannot issue async copies while it is blocked inside a
            # pull, and the whole point of the depth window is that the
            # next chunk's D2H is in flight BEHIND the executing one
            to_start = self._start_ready_locked()
            self._cv.notify_all()
        self._run_start_hooks(to_start)
        if not to_start:
            # depth grew without a start (budget full): the queue-depth
            # gauge must still see the backlog a wedged worker builds
            self._set_inflight_gauge()
        return job

    # --- consumer side -------------------------------------------------

    def wait(self, job: PullJob):
        """Block until ``job`` finishes; returns its result or re-raises
        its exception at THIS (consuming) call site. A cancelled job
        returns None with its record untouched — the caller's serial
        fallback still applies. Idempotent accounting: only the first
        wait on a job contributes to wait/overlap totals.

        Stall watchdog: a consumer blocked past ``DBSCAN_PULL_STALL_S``
        (default 30 s) on ONE job emits a ``pull.stall`` event with the
        job label and the engine's queue depth — into the live obs
        registries or the always-on flight ring — so a wedged engine
        (dead worker, hung D2H) leaves a mark in the postmortem even
        though this thread never unblocks to report it."""
        t0 = time.perf_counter()
        stall_s = float(config.env("DBSCAN_PULL_STALL_S"))
        if stall_s > 0 and not job._done.wait(stall_s):
            with self._cv:
                _tsan.access("pipeline.engine", write=False)
                depth = self._queue_depth_locked()
            obs.count("pull.stalls")
            obs.event(
                "pull.stall",
                label=job.label,
                waited_s=round(time.perf_counter() - t0, 3),
                queue_depth=depth,
                stall_after_s=stall_s,
            )
            logger.warning(
                "pull pipeline stall: consumer blocked > %.1fs on job "
                "%r (queue depth %d) — worker wedged or transfer hung",
                stall_s,
                job.label,
                depth,
            )
        job._done.wait()
        waited = time.perf_counter() - t0
        first = False
        with self._cv:
            _tsan.access("pipeline.engine")
            if not job.consumed:
                job.consumed = True
                first = True
                overlap = max(0.0, job.busy_s - waited)
                self._totals["wait_s"] += waited
                self._totals["overlap_s"] += overlap
        if first:
            obs.count("pull.wait_s", waited)
            obs.count("pull.overlap_s", overlap)
        if job.error is not None:
            raise job.error
        return job.result

    def settle(self, job: PullJob, serial_fallback=None):
        """Consume one job at its ordering point — the ONE place the
        wait/quiesce/cancelled contract lives, shared by every
        consumer. Waits for the job; on a worker fault, brakes the
        worker first (quiesce — it must not race ahead on a doomed
        run's remaining jobs) and re-raises HERE, the consuming site.
        A job cancelled by a concurrent abort left its inputs
        untouched, so ``serial_fallback()`` (when given) runs the work
        inline. Returns the job's result, or the fallback's."""
        try:
            out = self.wait(job)
        except Exception:
            self.quiesce()
            raise
        if job.cancelled and serial_fallback is not None:
            return serial_fallback()
        return out

    def drain(self) -> None:
        """Block until every submitted job has finished (results are NOT
        consumed; exceptions stay on their jobs for wait())."""
        with self._cv:
            _tsan.access("pipeline.engine", write=False)
            jobs = list(self._pending) + list(self._ready)
            if self._executing is not None:
                jobs.append(self._executing)
        for j in jobs:
            j._done.wait()

    def barrier(self) -> None:
        """Submission barrier (collective mode's public name for
        :meth:`drain`): block until every job submitted so far has
        executed, so a main-thread collective pull issued AFTER the
        barrier can never interleave with worker-issued ones. Valid —
        and a plain drain — in any mode."""
        self.drain()

    def quiesce(self) -> int:
        """Abort-path brake. Serial mode: cancel every job that has not
        begun executing (their records stay untouched — serial re-pull
        safe) and block until the in-flight one finishes; returns the
        number of cancelled jobs. Collective mode: cancelling would
        desynchronize the cross-process pull sequence (another process
        may be executing the very job this one cancels), so every
        submitted job RUNS instead — quiesce degrades to the barrier
        and returns 0."""
        if self.collective:
            self.drain()
            return 0
        with self._cv:
            _tsan.access("pipeline.engine")
            dropped = list(self._pending) + list(self._ready)
            self._pending.clear()
            # started-but-unexecuted jobs already ran on_start (the async
            # copy is in flight) but their work never runs: releasing the
            # byte window here keeps the invariants for later jobs
            for j in self._ready:
                self._started -= 1
                self._started_bytes -= j.bytes_hint
            self._ready.clear()
            for j in dropped:
                j.cancelled = True
                j._done.set()
            while self._executing is not None:
                self._cv.wait()
        self._set_inflight_gauge()
        return len(dropped)

    def close(self) -> None:
        """Stop the worker (cancels everything not yet executing)."""
        self.quiesce()
        with self._cv:
            _tsan.access("pipeline.engine")
            self._shutdown = True
            self._cv.notify_all()

    # --- accounting ----------------------------------------------------

    def totals(self) -> dict:
        """Cumulative engine accounting (independent of obs): jobs,
        wait_s, busy_s, overlap_s, bytes, inflight_peak."""
        with self._cv:
            _tsan.access("pipeline.engine", write=False)
            return dict(self._totals)

    def _queue_depth_locked(self) -> int:
        """Jobs submitted and not yet executed (pending + started-ahead
        + the one executing) — the backlog figure a wedged engine
        freezes at a nonzero value."""
        return (
            len(self._pending)
            + len(self._ready)
            + (1 if self._executing is not None else 0)
        )

    def _set_inflight_gauge(self) -> None:
        with self._cv:
            _tsan.access("pipeline.engine")
            n = self._started
            depth = self._queue_depth_locked()
            if n > self._totals["inflight_peak"]:
                self._totals["inflight_peak"] = n
        obs.gauge("pull.inflight", n)
        obs.gauge("pull.queue_depth", depth)

    # --- worker --------------------------------------------------------

    def _start_ready_locked(self) -> list:
        """Move pending jobs into the started window while the depth and
        byte budgets allow (the first job of an empty window always
        fits, so an oversized single chunk cannot deadlock). Returns the
        jobs whose on_start must run (outside the lock)."""
        to_start = []
        while self._pending:
            nxt = self._pending[0]
            if self._started >= self.inflight:
                break
            if (
                self._started > 0
                and self._started_bytes + nxt.bytes_hint
                > self.inflight_bytes
            ):
                break
            self._pending.popleft()
            self._started += 1
            self._started_bytes += nxt.bytes_hint
            self._ready.append(nxt)
            to_start.append(nxt)
        return to_start

    def _run_start_hooks(self, to_start: list) -> None:
        """Run on_start (the async D2H copy kick) for freshly-started
        jobs, outside the lock. Each job is moved to the started window
        exactly once (under the lock), so its hook runs exactly once —
        from whichever thread moved it."""
        for j in to_start:
            # collective mode: no prefetch — the pull itself is the
            # ordered cross-process collective, and only the worker may
            # touch transfers (single-issuer ordering)
            if j.on_start is not None and not self.collective:
                try:
                    j.on_start()
                except Exception as e:  # noqa: BLE001 — surfaces at wait
                    logger.debug("pull on_start failed: %s", e)
        if to_start:
            self._set_inflight_gauge()

    def _loop(self) -> None:
        while True:
            with self._cv:
                _tsan.access("pipeline.engine")
                while True:
                    if self._shutdown:
                        return
                    to_start = self._start_ready_locked()
                    if to_start or self._ready:
                        break
                    self._cv.wait()
            self._run_start_hooks(to_start)
            with self._cv:
                _tsan.access("pipeline.engine")
                if not self._ready:
                    continue
                job = self._ready.popleft()
                self._executing = job
            self._execute(job, from_worker=True)

    def _execute(self, job: PullJob, from_worker: bool = False) -> None:
        """Run one job to completion and finish its accounting — the
        shared tail of the worker loop and of collective-mode inline
        submission (where the job never entered the started window, so
        no depth/byte release applies)."""
        t0 = time.perf_counter()
        # the submitter's request id is restored for the WHOLE job —
        # the work itself and the retroactive pull.chunk span both
        # stamp it, so a request's trace follows it onto the worker
        with obs.request_scope(job.rid):
            try:
                job.result = job.work()
            except BaseException as e:  # noqa: BLE001 — re-raised at wait
                job.error = e
            job.busy_s = time.perf_counter() - t0
            with self._cv:
                _tsan.access("pipeline.engine")
                if from_worker:
                    self._executing = None
                    self._started -= 1
                    self._started_bytes -= job.bytes_hint
                else:
                    # inline (collective-mode) execution: the SUBMITTER
                    # blocked for the whole job, so the honest accounting
                    # is wait = busy and overlap = 0 — consumed here so a
                    # later wait() (which returns instantly) cannot
                    # re-score it as fully overlapped
                    job.consumed = True
                    self._totals["wait_s"] += job.busy_s
                self._totals["jobs"] += 1
                self._totals["busy_s"] += job.busy_s
                self._totals["bytes"] += job.bytes_hint
                self._cv.notify_all()
            # telemetry BEFORE the done event (a consumer that returned
            # from wait() must find the job's counters/span already
            # emitted), shielded so a failing hook can never strand the
            # waiter
            try:
                obs.count("pull.busy_s", job.busy_s)
                if not from_worker:
                    obs.count("pull.wait_s", job.busy_s)
                if job.bytes_hint:
                    obs.count("pull.bytes", job.bytes_hint)
                obs.add_span(
                    "pull.chunk",
                    t0,
                    t0 + job.busy_s,
                    label=job.label,
                    bytes=int(job.bytes_hint),
                    failed=job.error is not None,
                )
                self._set_inflight_gauge()
            except Exception:  # noqa: BLE001 — never strand a waiter
                logger.exception("pull telemetry emission failed")
        job._done.set()


# --- process-global engine --------------------------------------------

_engine: Optional[PullEngine] = None
_engine_key = None
_engine_lock = _tsan.lock("pipeline.engine_state")


def get_engine() -> Optional[PullEngine]:
    """The process pull engine for the CURRENT env configuration, or
    None under ``DBSCAN_PULL_PIPELINE=0`` — the hard off-switch; every
    call site then keeps its original serial code path byte-for-byte.

    Multi-process runs get a COLLECTIVE-AWARE engine (module docstring)
    instead of the historical None: the single worker executing jobs in
    submission order is the per-shard submission barrier that keeps
    every process's cross-host pull sequence identical, and quiesce
    drains rather than cancels so an abort on one process can never
    desynchronize the others.

    The engine is rebuilt (old worker drained and stopped) whenever the
    knob values change, so tests can monkeypatch the env per test."""
    global _engine, _engine_key
    from dbscan_tpu.parallel import mesh as mesh_mod

    key = (
        bool(config.env("DBSCAN_PULL_PIPELINE")),
        int(config.env("DBSCAN_PULL_INFLIGHT")),
        int(config.env("DBSCAN_PULL_INFLIGHT_BYTES")),
        mesh_mod.multiprocess(),
    )
    with _engine_lock:
        _tsan.access("pipeline.engine_state")
        if not key[0]:
            if _engine is not None:
                _engine.close()
                _engine = None
                _engine_key = None
            return None
        if _engine is None or _engine_key != key:
            if _engine is not None:
                _engine.close()
            _engine = PullEngine(
                inflight=key[1], inflight_bytes=key[2], collective=key[3]
            )
            _engine_key = key
        return _engine


def reset_engine() -> None:
    """Stop and drop the process engine (tests)."""
    global _engine, _engine_key
    with _engine_lock:
        _tsan.access("pipeline.engine_state")
        if _engine is not None:
            _engine.close()
        _engine = None
        _engine_key = None


def delta_totals(snap: Optional[dict], now: Optional[dict]) -> dict:
    """One run's pull accounting: difference of two :meth:`totals`
    snapshots, seconds rounded (the shape ``stats["pull"]`` reports)."""
    snap = snap or {}
    now = now or {}
    out = {}
    for k in _TOTAL_KEYS:
        v = now.get(k, 0) - snap.get(k, 0)
        out[k] = round(v, 6) if isinstance(v, float) else int(v)
    out["overlap_ratio"] = round(
        min(1.0, out["overlap_s"] / out["busy_s"]), 4
    ) if out["busy_s"] > 0 else 0.0
    return out
