"""Device-accelerated spill-tree passes (dense cosine decomposition).

The spill tree's host cost is NOT one big matmul — it is hundreds of
sample-sized BLAS passes (farthest-point traversal, Lloyd refinement,
the sampled rejection screen, greedy leader cover, canopy membership),
measured at ~2/3 of the cosine anchor's wall on the single-core host
(VERDICT r4 item 2). This module runs those passes on the accelerator:
the node's rows are uploaded ONCE (bf16), every sequential traversal
becomes a `lax.while_loop` of matvecs against the resident rows, and
only small results cross the link — pivot vectors [m, D], assignment
bytes [n], packed membership bits [n*m/8], a leader adjacency [L, L].

Precision contract: rows are stored bf16 (halves the upload — the
tunnel's ~60 MB/s uplink is the binding resource, see BASELINE.md), and
every band comparison the COVERAGE PROOF depends on is inflated by an
explicit `slack` bound on the bf16 chord error (2*2^-9 dot error for
unit rows -> chord error <= sqrt(2*2^-8) at small chords). Inflating a
band is one-sided: the copy-sets/canopies only GROW, so no accepted
pair is ever missed — quantization costs duplication, never
correctness. Pivot SELECTION and the rejection screen need no slack at
all (pivot choice never affects correctness; the screen only decides
whether to escalate, and the exact full-node pass re-decides).

Reference analog: none — the reference's decomposition is 2-D
rectangles on a driver-local grid (EvenSplitPartitioner.scala:66-103);
this is the high-dimensional counterpart's hot path moved to the chip.
"""

from __future__ import annotations

import functools
import time

import numpy as np

from dbscan_tpu import faults, obs
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.obs import memory as obs_memory

# chord-error bound for bf16-stored unit rows: |dot error| <= 2*2^-9
# (+f32 accumulation, negligible at D<=4096); chord = sqrt(2-2dot) moves
# worst at small chords by sqrt(2 * 2 * 2^-9) ~ 0.0885
BF16_CHORD_SLACK = float(np.sqrt(2.0 * 2.0 * 2.0**-9)) + 1e-4
_LEADER_CAP = 4096  # mirrors spill._LEADER_CAP


class DeviceNodeOps:
    """One spill node's rows resident on the accelerator.

    Drop-in companion to spill._DenseOps for the device-accelerated
    passes; built lazily by the tree driver only when a usable non-CPU
    backend exists (or when forced for tests). ``take`` gathers a child
    subset ON DEVICE from the parent's resident rows — a child upload is
    an int32 index vector, ~500x smaller than its rows."""

    def __init__(self, x, n: int, dim: int):
        self.x = x  # [n, D] bf16 device array
        self.n = n
        self.dim = dim

    @classmethod
    def from_host(cls, x_host: np.ndarray):
        import jax.numpy as jnp
        import ml_dtypes

        xb = np.asarray(x_host, dtype=ml_dtypes.bfloat16)
        # supervised upload: the bf16 payload is the biggest single
        # transfer of the cosine route (~1 GB at 1M x 512 over the
        # tunnel) and exactly where a flaky link faults — retry with
        # backoff before the caller degrades the run to host BLAS.
        # The span/counters below are what lets bench.py split a timed
        # rep's upload_s from its compute_s (hot vs cold resident cache)
        t0 = time.perf_counter()
        with obs.span(
            "spill.payload_upload", bytes=int(xb.nbytes), rows=int(len(xb))
        ) as sp:
            x_dev = faults.supervised(
                faults.SITE_SPILL,
                lambda _b: jnp.asarray(xb),
                label="payload-upload",
            )
            sp.sync(x_dev)
        # counted AFTER the span closes so a device-sync boundary
        # (DBSCAN_TIME_DEVICE=1) folds the blocking wait into upload_s
        obs.count("transfer.h2d_bytes", int(xb.nbytes))
        obs.count("transfer.payload_upload_bytes", int(xb.nbytes))
        obs.timed_count("transfer.payload_upload_s", t0)
        # HBM occupancy right after the biggest single allocation of
        # the cosine route lands — the watermark that says whether the
        # resident payload is what pushes a later dispatch into
        # RESOURCE_EXHAUSTED
        obs_memory.sample("spill.payload_upload")
        return cls(x_dev, x_host.shape[0], x_host.shape[1])

    def take(self, idx: np.ndarray) -> "DeviceNodeOps":
        import jax.numpy as jnp

        idx_np = np.asarray(idx, np.int32)
        # the child's upload is the index vector, not its rows —
        # exactly the transfer saving the resident design buys
        obs.count("transfer.h2d_bytes", int(idx_np.nbytes))
        idx32 = jnp.asarray(idx_np)
        with obs.span("spill.child_gather", rows=int(len(idx))):
            return DeviceNodeOps(
                faults.supervised(
                    faults.SITE_SPILL,
                    lambda _b: obs_compile.tracked_call(
                        "spill.gather", _gather_fn(), self.x, idx32
                    ),
                    label="child-gather",
                ),
                len(idx),
                self.dim,
            )


@functools.lru_cache(maxsize=1)
def _gather_fn():
    import jax

    return jax.jit(lambda x, idx: x[idx])


def _jax():
    import jax
    import jax.numpy as jnp

    return jax, jnp


def _ladder8(m: int, cap: int = 192) -> int:
    """Quantize a pivot count up the shared geometric ladder (multiple
    8, capped): device kernels are keyed on the count, and the raw
    data-dependent values would mint a fresh XLA compile per spill-tree
    node. Extra pivots are harmless — selection quality only, and the
    halo-separation filter drops any excess."""
    from dbscan_tpu.parallel.binning import _ladder_width

    return min(_ladder_width(m, 8), cap)


@functools.lru_cache(maxsize=32)
def _farthest_lloyd_fn(m: int, dim: int, cap_iters: int = 2):
    """Jitted farthest-point seeding + ``cap_iters`` Lloyd steps.

    Farthest-point is the host algorithm verbatim: start from row
    ``seed0``, repeatedly take the row maximizing the running min-chord.
    Lloyd: assign to nearest pivot (max dot), renormalized cell means.
    Returns ([m, D] f32 pivots, [m] bool valid) — empty cells invalid.
    """
    jax, jnp = _jax()

    def fn(x, seed0):
        n = x.shape[0]
        xf = x.astype(jnp.float32)

        def fp_body(i, st):
            piv, dmin = st
            j = jnp.argmax(dmin)
            row = xf[j]
            piv = piv.at[i].set(row)
            d = 2.0 - 2.0 * (xf @ row)
            dmin = jnp.minimum(dmin, jnp.maximum(d, 0.0))
            return piv, dmin

        piv0 = jnp.zeros((m, dim), jnp.float32)
        d0 = jnp.full((n,), jnp.inf, jnp.float32)
        # seed exactly like the host: first pivot is the seed row, the
        # rest follow the farthest-point recurrence
        piv0 = piv0.at[0].set(xf[seed0])
        d0 = jnp.maximum(2.0 - 2.0 * (xf @ xf[seed0]), 0.0)
        piv, _ = jax.lax.fori_loop(1, m, fp_body, (piv0, d0))

        def lloyd(_, piv):
            a = jnp.argmax(xf @ piv.T, axis=1)
            sums = jax.ops.segment_sum(xf, a, num_segments=m)
            norms = jnp.linalg.norm(sums, axis=1, keepdims=True)
            newp = sums / jnp.maximum(norms, 1e-12)
            # empty/degenerate cells keep their previous vector; the
            # host drops them — the valid mask below reproduces that
            return jnp.where(norms > 1e-12, newp, piv)

        piv = jax.lax.fori_loop(0, cap_iters, lloyd, piv)
        a = jnp.argmax(xf @ piv.T, axis=1)
        mass = jax.ops.segment_sum(
            jnp.ones((n,), jnp.int32), a, num_segments=m
        )
        return piv, mass

    return jax.jit(fn)


def pivot_vectors_device(sub: DeviceNodeOps, m: int, halo: float, rng):
    """Device counterpart of spill._pivot_vectors: farthest-point seeds
    + 2 Lloyd steps on the resident rows, then the host's greedy
    halo-separation filter on the pulled [m, D] pivots (O(m^2), host).
    Pivot choice never affects correctness (spill.py module docstring),
    so bf16 rows need no slack here."""
    if sub.n < 2:
        return np.zeros((0, sub.dim), np.float32)
    fn = _farthest_lloyd_fn(_ladder8(int(m)), int(sub.dim))
    seed0 = int(rng.integers(sub.n))
    piv, mass = fn(sub.x, seed0)
    # ONE host sync for both outputs (device_get on the pair) instead of
    # two sequential np.asarray round-trips — per NODE this is small,
    # but the tree calls this once per escalation attempt per node and
    # the tunnel charges ~latency per sync, not per byte
    import jax

    piv, mass = jax.device_get((piv, mass))
    piv = np.asarray(piv, dtype=np.float32)
    mass = np.asarray(mass)
    keep = mass > 0
    piv, mass = piv[keep], mass[keep]
    if len(piv) < 2:
        return piv
    from dbscan_tpu.parallel.spill import halo_separation_filter

    return halo_separation_filter(piv, mass, halo)


@functools.lru_cache(maxsize=32)
def _membership_fn(dim: int):
    """Jitted full-node membership pass. Returns (assign u8, member
    bits packed along the pivot axis, band-hit counts per cell, d_min).

    The band formula mirrors spill._membership exactly — intersection
    of the radius band ``r_c + halo`` and the classic ``d_min + 2*halo``
    — with ``slack`` added where the bf16 chord error could SHRINK a
    band (r from underestimated d_min, d overestimated): bands only
    grow, so the copy-set stays a superset of the host-f32 one.
    """
    jax, jnp = _jax()

    def fn(x, piv, n_valid, halo, slack):
        xf = x.astype(jnp.float32)
        d = 2.0 - 2.0 * (xf @ piv.T)
        d = jnp.sqrt(jnp.maximum(d, 0.0))
        m = d.shape[1]
        # pivots are ladder-padded so the kernel compiles once per rung,
        # not per data-dependent count; padded columns can never win
        d = jnp.where(jnp.arange(m)[None, :] < n_valid, d, jnp.inf)
        assign = jnp.argmin(d, axis=1)
        dmin = jnp.take_along_axis(d, assign[:, None], axis=1)[:, 0]
        r = jax.ops.segment_max(
            dmin, assign, num_segments=m, indices_are_sorted=False
        )
        # segment_max of an empty segment is -inf: exactly the host's
        # "cells nobody is assigned to need no copies" convention.
        # Host formula verbatim (spill._membership), each band +2*slack:
        # measured d overestimates by <= slack while measured r (or the
        # point's own d_min) underestimates by <= slack, so the true-
        # distance copy-set condition implies the inflated measured one.
        member = (d <= (r + halo + 2.0 * slack)[None, :]) & (
            d <= (dmin + 2.0 * halo + 2.0 * slack)[:, None]
        )
        sizes = member.sum(axis=0, dtype=jnp.int32)
        packed = jnp.packbits(member, axis=1)
        return assign.astype(jnp.uint8), packed, sizes, dmin

    return jax.jit(fn)


def membership_device(sub: DeviceNodeOps, piv: np.ndarray, halo: float):
    """(assign, member) for the full node, computed on device; pulls
    [n] assign bytes + packed member bits. Matches spill._membership's
    bands inflated by BF16_CHORD_SLACK (superset copy-sets)."""
    import jax.numpy as jnp

    fn = _membership_fn(int(sub.dim))
    m = piv.shape[0]
    m_pad = _ladder8(max(m, 1), cap=max(192, m))
    piv_pad = np.zeros((m_pad, piv.shape[1]), dtype=np.float32)
    piv_pad[:m] = piv
    assign, packed, sizes, _ = fn(
        sub.x,
        jnp.asarray(piv_pad),
        jnp.int32(m),
        jnp.float32(halo),
        jnp.float32(BF16_CHORD_SLACK),
    )
    member = np.unpackbits(
        np.asarray(packed), axis=1, count=m_pad
    ).astype(bool)[:, :m]
    return np.asarray(assign).astype(np.int64), member


def screen_dup_device(sub: DeviceNodeOps, piv: np.ndarray, halo: float):
    """Sampled rejection screen: (dup per point, cell count). Pulls two
    scalars. No slack — the screen only chooses whether to escalate."""
    import jax.numpy as jnp

    fn = _membership_fn(int(sub.dim))
    m = piv.shape[0]
    m_pad = _ladder8(max(m, 1), cap=max(192, m))
    piv_pad = np.zeros((m_pad, piv.shape[1]), dtype=np.float32)
    piv_pad[:m] = piv
    _, _, sizes, _ = fn(
        sub.x,
        jnp.asarray(piv_pad),
        jnp.int32(m),
        jnp.float32(halo),
        jnp.float32(0.0),
    )
    sizes = np.asarray(sizes)[:m]
    return float(sizes.sum()) / max(1, sub.n), m


_COVER_BLOCK = 512


def _make_cover(jax, jnp, dim: int, cap: int):
    """The greedy-cover loop body shared by the single-radius function
    (kept for targeted tests) and the fused escalation ladder: walk the
    permutation, every row farther than ``t`` from all leaders becomes
    one (minus slack: bf16 could OVERestimate a distance and mint a
    leader the host would skip — extra leaders are harmless, but a
    MISSED cover is not, so the coverage test uses t + slack nowhere and
    the canopy band carries the slack instead; the sequential walk
    semantics match the host exactly up to quantization/reduction
    order). BLOCKED: each while-iteration takes the first K uncovered
    candidates in perm order, resolves the in-block greedy (a candidate
    covered by an earlier in-block pick drops — identical to the
    one-at-a-time walk) with one [K, K] pairwise pass + a K-step scan,
    and updates coverage with ONE [n, K] matmul — ~L/K iterations
    instead of L (measured 5.7 s -> sub-second at L=2000, n=1M,
    D=512). Returns ``cover(xf, t) -> (buf [cap+1, D], nb, overflow)``
    over pre-permuted f32 rows."""
    K = _COVER_BLOCK

    def cover(xf, t):
        n = xf.shape[0]

        # dmin carries SQUARED chords (no per-iteration [n] sqrt);
        # coverage therefore tests against t^2 — comparing chord^2
        # against the LINEAR t would regress the cover radius to
        # sqrt(t), under-mint leaders, and void the canopy exact-cover
        # proof for data with spread in (t, sqrt(t))
        t2 = t * t

        def cond(st):
            _, nb, dmin, overflow = st
            return (~overflow) & (dmin.max() > t2)

        def body(st):
            buf, nb, dmin, _ = st
            unc = dmin > t2
            cs = jnp.cumsum(unc.astype(jnp.int32))
            kfound = jnp.minimum(cs[-1], K)
            # first K uncovered, in perm order: scatter positions into
            # their rank slot (non-selected rows dump into slot K)
            slot = jnp.where(unc & (cs <= K), cs - 1, K)
            idx = (
                jnp.zeros(K + 1, jnp.int32)
                .at[slot]
                .set(jnp.arange(n, dtype=jnp.int32), mode="drop")[:K]
            )
            rows = xf[idx]  # [K, D]; rows at rank >= kfound are junk
            validk = jnp.arange(K) < kfound
            pair2 = 2.0 - 2.0 * (rows @ rows.T)  # squared chords

            # in-block greedy, perm order: keep i iff no EARLIER kept
            # candidate covers it (exactly what the sequential walk
            # would have decided; pre-block leaders can't cover any
            # candidate — they are all measured-uncovered)
            def bstep(i, keep):
                covered = jnp.any(
                    keep
                    & (jnp.arange(K) < i)
                    & (pair2[i] <= t2)
                )
                return keep.at[i].set(validk[i] & ~covered)

            keep = jax.lax.fori_loop(
                1, K, bstep, jnp.zeros(K, bool).at[0].set(validk[0])
            )
            nkeep = keep.sum(dtype=jnp.int32)  # >= 1: progress
            kcs = jnp.cumsum(keep.astype(jnp.int32))
            dest = jnp.where(keep, nb + kcs - 1, cap)
            buf = buf.at[dest].set(rows, mode="drop")
            d2 = 2.0 - 2.0 * (xf @ rows.T)  # [n, K]
            d2 = jnp.where(keep[None, :], d2, jnp.inf)
            dmin = jnp.minimum(dmin, jnp.maximum(d2.min(axis=1), 0.0))
            return buf, nb + nkeep, dmin, nb + nkeep > cap

        buf0 = jnp.zeros((cap + 1, dim), jnp.float32)  # +1: drop slot
        d0 = jnp.full((n,), jnp.inf, jnp.float32)
        buf, nb, _, overflow = jax.lax.while_loop(
            cond, body, (buf0, jnp.int32(0), d0, jnp.bool_(False))
        )
        return buf, nb, overflow

    return cover


@functools.lru_cache(maxsize=8)
def _greedy_leaders_fn(dim: int, cap: int):
    """Jitted single-radius greedy cover (see :func:`_make_cover`);
    returns (leader rows [cap, D] f32, count, overflowed)."""
    jax, jnp = _jax()
    cover = _make_cover(jax, jnp, dim, cap)

    def fn(x, perm, t):
        xf = x.astype(jnp.float32)[perm]
        buf, nb, overflow = cover(xf, t)
        return buf[:cap], nb, overflow

    return jax.jit(fn)


#: fixed rung-ladder width of the fused cover (the escalation list is
#: at most (2, 4, 8) x halo; shorter deduped ladders pad by repeating
#: the last rung, which the `r < n_rungs` loop bound never evaluates)
_LADDER_RUNGS = 3


@functools.lru_cache(maxsize=8)
def _greedy_leaders_ladder_fn(dim: int, cap: int):
    """Jitted FUSED escalation ladder: run the greedy cover at rung
    ``ts[0]``; while it overflows the cap, rerun at the next rung — all
    on device, so the whole ladder costs ONE dispatch and ONE host sync
    instead of one per rung (each rung's overflow check was a ~0.5 s
    round-trip on the tunneled TPU). ``ts`` is the host-deduped [3]
    radius ladder (bf16 floor + the 1.9 canopy cutoff applied on the
    host, exactly the per-rung loop it replaces), ``n_rungs`` the live
    prefix length. Returns (leader rows [cap, D], count, overflowed,
    rung index used)."""
    jax, jnp = _jax()
    cover = _make_cover(jax, jnp, dim, cap)

    def fn(x, perm, ts, n_rungs):
        xf = x.astype(jnp.float32)[perm]

        def outer_cond(st):
            r, _, _, overflow = st
            return (r < n_rungs) & overflow

        def outer_body(st):
            r, _, _, _ = st
            buf, nb, overflow = cover(xf, ts[r])
            return r + jnp.int32(1), buf, nb, overflow

        buf0, nb0, ov0 = cover(xf, ts[0])
        r, buf, nb, overflow = jax.lax.while_loop(
            outer_cond, outer_body, (jnp.int32(1), buf0, nb0, ov0)
        )
        return buf[:cap], nb, overflow, r - 1

    return jax.jit(fn)


@functools.lru_cache(maxsize=32)
def _canopy_fn(dim: int):
    """Jitted canopy pass: nearest leader per point, leader-leader
    canopy-overlap adjacency (M^T M of the banded membership — a point
    in two canopies connects them; clique vs the host's star edges, same
    components), and the total membership count for the edge budget."""
    jax, jnp = _jax()

    def fn(x, leaders, n_valid, band):
        xf = x.astype(jnp.float32)
        d = 2.0 - 2.0 * (xf @ leaders.T)
        d = jnp.sqrt(jnp.maximum(d, 0.0))
        # leaders ladder-padded (one compile per rung); padded columns
        # sit at +inf so they never cover or win nearest
        lmask = jnp.arange(d.shape[1])[None, :] < n_valid
        d = jnp.where(lmask, d, jnp.inf)
        nearest = jnp.argmin(d, axis=1)
        mf = (d <= band).astype(jnp.float32)
        adj = (mf.T @ mf) > 0.0
        # per-leader counts, summed on the host in f64: a single on-
        # device f32 total loses integer precision past 2^24 and int32
        # overflows at n*L ~ 4e9; each column count <= n < 2^24 is exact
        return nearest.astype(jnp.int32), adj, mf.sum(axis=0)

    return jax.jit(fn)


def leader_components_device(
    sub: DeviceNodeOps, halo: float, rng, edge_budget: int
):
    """Device counterpart of spill.leader_components: greedy cover at
    escalating radii, canopy-overlap union, exact-cover components.
    The canopy band carries BF16_CHORD_SLACK on BOTH the cover radius
    (a true distance may exceed the measured-under-t by slack) and the
    accepted-pair halo — the cover proof's triangle inequality then
    holds for TRUE distances, so components remain exact covers."""
    from dbscan_tpu.parallel.graph import uf_components

    n = sub.n
    # ONE permutation shared by every escalation rung: the greedy walk
    # is a deterministic function of (perm, t), so the t == t_prev dedup
    # below is provably sound — a same-radius rerun with the same perm
    # must overflow identically. (Per-rung draws would make that claim
    # false: a different walk order could stay under _LEADER_CAP.)
    perm = rng.permutation(n).astype(np.int32)
    # Host-side rung ladder, exactly the per-rung loop this replaces:
    # bf16 floor on the cover radius (a covered point's MEASURED chord
    # to its leader can read as high as the slack — a self-chord under
    # bf16 is not 0 — so a minting radius below the slack could never
    # terminate; the proof only needs SOME radius, so the floor costs
    # nothing but leader density), clamped duplicates dropped, and the
    # 1.9 canopy cutoff ending the ladder.
    rungs = []
    t_prev = None
    for t_mult in (2.0, 4.0, 8.0):
        t = max(t_mult * halo, BF16_CHORD_SLACK)
        if t == t_prev:
            continue
        t_prev = t
        if t + halo >= 1.9:
            break
        rungs.append(t)
    if not rungs:
        return None
    import jax.numpy as jnp

    # The whole escalation runs FUSED on device: one dispatch, one host
    # sync for up to three rungs, instead of a blocking overflow check
    # per rung (the per-rung host round-trips were the dominant
    # fixed cost of this pass on the tunneled TPU). Pad the ladder by
    # repeating the last rung — the `r < n_rungs` bound never runs pads.
    ts = np.full(_LADDER_RUNGS, rungs[-1], dtype=np.float32)
    ts[: len(rungs)] = rungs
    fn = _greedy_leaders_ladder_fn(int(sub.dim), _LEADER_CAP)
    buf, nb, overflow, used = fn(
        sub.x, jnp.asarray(perm), jnp.asarray(ts), jnp.int32(len(rungs))
    )
    if bool(overflow):
        return None  # every rung exceeded the cap
    nb = int(nb)
    if nb < 2:
        return None
    t = float(rungs[int(used)])
    # true cover radius <= t + slack (measured <= t); both
    # endpoints of an accepted pair then MEASURE within
    # t + halo + 2*slack of the covering leader
    band = t + halo + 2.0 * BF16_CHORD_SLACK
    cfn = _canopy_fn(int(sub.dim))
    l_pad = _ladder8(nb, cap=_LEADER_CAP)
    nearest, adj, col_counts = cfn(
        sub.x,
        jnp.asarray(np.asarray(buf)[:l_pad]),
        jnp.int32(nb),
        jnp.float32(band),
    )
    total = float(
        np.asarray(col_counts, dtype=np.float64)[:nb].sum()
    )
    if total > edge_budget * n:
        return None  # canopies overlap heavily; larger radii more so
    adj = np.asarray(adj)[:nb, :nb]
    ea, eb = np.nonzero(np.triu(adj, k=1))
    n_comp, gids = uf_components(
        ea.astype(np.int64), eb.astype(np.int64), nb
    )
    if n_comp < 2:
        return None
    comp = (np.asarray(gids)[np.asarray(nearest)] - 1).astype(np.int32)
    return comp, int(n_comp)


def device_available() -> bool:
    """True when a non-CPU jax backend is initialized/initializable —
    the gate the spill tree uses before routing passes here. Import
    errors and dead backends degrade to the host path silently."""
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 — any failure means "no device"
        return False
